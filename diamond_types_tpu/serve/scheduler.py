"""The multi-document merge scheduler: router x admission x banks.

Sits between the sync server's DocStore and the device tier. A document
edit lands as `submit(doc_id, n_ops)`; the scheduler routes it to its
shard, coalesces it into a shape bucket, and `pump()` flushes due
buckets into the shard's session bank — one flush drives every doc in
the bucket back-to-back on that shard's chip, so they share the padded
micro-tape shape (and therefore the jit cache entry) instead of each
paying its own compile.

Threading: the global `lock` guards router + queue mutation only; each
shard's BANK has its own lock, so flushes (the device work) run with
the global lock RELEASED and different shards flush concurrently —
submits and reads for other shards never stall behind one shard's
device call. With `flush_workers=True` (default) `pump()` only TAKES
due buckets under the global lock and hands them to per-shard worker
threads, so the pump caller returns immediately and shards genuinely
overlap their flush windows; `drain()` waits for workers to go idle
and `stop_workers()`/`stop_pump()` join them deterministically. The
fencing recheck runs INSIDE the worker (see `_flush_items`), so lease
epochs are validated at actual merge time, not dispatch time.

The old process-global `_sync_lock` over-serialized device syncs
across ALL shards. It is now narrowed to its real job — an OPLOG guard
(`sync_lock`, e.g. DocStore.lock, held around host-side oplog reads so
bank planning never races handler threads mutating the oplog) — while
device execution is guarded by a PER-DEVICE lock (shards placed on the
same chip share one; distinct chips flush concurrently). The one
remaining process-global serialization point is first-touch JAX
backend init (`bank._ensure_jax_ready`), which is not thread-safe and
runs exactly once. Lock order is always
global → shard → sync(oplog) → device, never reversed. Intended
callers: (a) HTTP handler threads submitting and reading, (b) pump
threads flushing (`start_pump`), and (c) bench drivers doing both
inline.

Ownership gate: when `admit` is set (cross-host replication — a
`replicate.ReplicaNode.owns` bound method), `submit` consults it first
and refuses merge work for docs whose lease this host does not hold;
the edit stays durable in the oplog, the device work runs on the
lease-holding host instead. When `epoch_of` is also set
(`ReplicaNode.active_epoch`), each accepted submit is stamped with the
lease epoch it was admitted under and RE-CHECKED at flush time: if the
lease moved (or was fenced off) between admit and flush, the queued
work is dropped — counted as `fenced` — instead of merged under a
stale lease. The ops themselves stay durable in the oplog; the new
owner merges them.
"""

from __future__ import annotations

import contextlib
import queue as _queue
import threading
import time
from typing import Callable, Dict, List, Optional

from ..analysis.witness import make_lock
from ..obs.trace import NOOP_SPAN
from ..qos.classes import QOS_PRIORITY
from .admission import AdmissionQueue, Backpressure
from .bank import SessionBank
from .metrics import ServeMetrics
from .router import ShardRouter


class MergeScheduler:
    def __init__(self, n_shards: int,
                 resolve: Callable[[str], object],
                 engine: str = "device",
                 max_sessions_per_shard: int = 8,
                 max_slots_per_shard: int = 1 << 24,
                 max_pending: int = 256,
                 flush_docs: int = 8,
                 flush_deadline_s: float = 0.05,
                 place_on_devices: bool = False,
                 session_opts: Optional[dict] = None,
                 sync_lock=None,
                 admit: Optional[Callable[[str], bool]] = None,
                 fused: bool = True,
                 fused_opts: Optional[dict] = None,
                 flush_workers: bool = True,
                 warmup: bool = False,
                 mesh_window: bool = False,
                 device_plan: bool = False,
                 pallas: bool = False) -> None:
        """`resolve(doc_id) -> OpLog` is the document authority —
        DocStore.get fits directly. `sync_lock` (e.g. DocStore.lock) is
        the OPLOG guard: held around host-side oplog reads (session
        build / tail planning / host syncs) so bank reads never race
        handler threads mutating the oplog; `resolve` is always called
        OUTSIDE it (DocStore.get takes that same non-reentrant lock).
        Device execution is guarded by per-device locks instead — see
        the module docstring. `fused=True` (device engine only) builds
        flush-fuse sessions and replays whole buckets in one vmapped
        device call; `flush_workers=True` flushes through per-shard
        worker threads; `warmup=True` pre-compiles the fused kernels on
        a background thread at construction. `mesh_window=True`
        (fused device engine only) inverts the flush concurrency model:
        instead of handing each shard's bucket to its own worker (N
        device dispatches per window), `pump()` assembles EVERY due
        shard's fusable tails into one mesh-sharded super-batch and
        issues a single `shard_map` program over the `docs` axis —
        see `_flush_window`. `device_plan=True` (fused device engine
        only) plans tails through the device transform
        (tpu/xform.plan_tails_device) instead of the host tracker walk;
        `pallas=True` adds the Pallas step-kernel replay rung at the
        top of the flush ladder (pallas → mesh → fused → per-doc →
        host), each rung falling back to the next on failure."""
        self.resolve = resolve
        self._sync_lock = sync_lock if sync_lock is not None \
            else contextlib.nullcontext()
        self.router = ShardRouter(n_shards)
        self.queue = AdmissionQueue(n_shards, max_pending=max_pending,
                                    flush_docs=flush_docs,
                                    flush_deadline_s=flush_deadline_s)
        self.metrics = ServeMetrics(n_shards, flush_docs, max_pending)
        devices: List = [None] * n_shards
        if place_on_devices and engine == "device":
            from ..parallel.mesh import serve_shard_devices
            devices = serve_shard_devices(n_shards)
        self.fused = bool(fused) and engine == "device"
        # mesh flush windows ride on fused sessions (the super-batch is
        # assembled from FusedDocSession plan rows)
        self.mesh_window = bool(mesh_window) and self.fused
        self.device_plan = bool(device_plan) and self.fused
        self.pallas = bool(pallas) and self.fused
        self._mesh = None          # lazy: first window / warmup builds
        self.banks = [
            SessionBank(i, max_sessions=max_sessions_per_shard,
                        max_slots=max_slots_per_shard, engine=engine,
                        device=devices[i], metrics=self.metrics,
                        session_opts=session_opts,
                        fused=fused, fused_opts=fused_opts,
                        # the jit cache is process-global: one warmer
                        # covers every shard's shape classes
                        warmup=(warmup and i == 0),
                        flush_docs=flush_docs,
                        mesh_shards=(n_shards if self.mesh_window
                                     else 0),
                        device_plan=device_plan, pallas=pallas)
            for i in range(n_shards)]
        # per-DEVICE locks: shards placed on the same chip share one;
        # unplaced shards (device=None) get their own (the default
        # device is thread-safe — contention there is a perf matter,
        # not a correctness one)
        by_dev: Dict[int, object] = {}
        self._device_locks: List = []
        for i, dev in enumerate(devices):
            key = id(dev) if dev is not None else ("shard", i)
            lock = by_dev.get(key)
            if lock is None:
                # witness rank = the first shard index mapped to the
                # device, so rank order == the sorted-shard-list
                # acquisition order _flush_window uses
                lock = by_dev[key] = make_lock(
                    f"device[{i}]", "device", rank=i)
            self._device_locks.append(lock)
        # `admit(doc_id) -> bool` — the cross-host ownership gate
        # (replicate.ReplicaNode.owns); None = single-host, admit all
        self.admit = admit
        # `epoch_of(doc_id) -> int` — the ACTIVE lease epoch this host
        # holds (replicate.ReplicaNode.active_epoch); None = unfenced
        self.epoch_of: Optional[Callable[[str], int]] = None
        # obs.Observability bundle (attach_obs); None = zero overhead:
        # every obs touchpoint below is guarded by this one attribute
        self.obs = None
        # serve.hydrate.Hydrator (attach_hydrator); None = the classic
        # everything-resident scheduler — no prefetch, no flush gate
        self.hydrator = None
        # qos.QosController (attach_qos); None = the static size-or-
        # deadline trigger, byte-identical to the pre-QoS scheduler
        self.qos = None
        # read.attach_follower_reads wires this to ReadPath.on_flush:
        # a completed flush moved the doc's merged tip, so the
        # follower-read checkout cache drops the doc's entries. Called
        # OUTSIDE shard/bank locks, right after record_flush.
        self.read_invalidate: Optional[Callable[[str], None]] = None
        self.lock = make_lock("scheduler.global", "global")
        self._shard_locks = [make_lock(f"shard[{i}]", "shard", rank=i)
                             for i in range(n_shards)]
        self._pump_stop = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None
        # per-shard flush workers (lazy-spawned daemons): pump() hands
        # taken batches to these so distinct shards' flush windows
        # genuinely overlap; _inflight + the condvar make drain()
        # deterministic
        self._flush_workers = bool(flush_workers)
        self._work_qs: List[_queue.Queue] = [
            _queue.Queue() for _ in range(n_shards)]
        self._workers: List[Optional[threading.Thread]] = \
            [None] * n_shards
        self._inflight = 0
        self._idle_cv = threading.Condition()

    def attach_obs(self, obs) -> None:
        """Wire an obs.Observability bundle into the admit→flush path:
        spans on submit/flush/device-sync, flush latencies into the
        metrics histogram, rare events (evictions, queue-bound
        violations, fenced flushes) into the flight recorder."""
        self.obs = obs
        self.metrics.recorder = obs.recorder
        # live-telemetry tier: counters/latencies double-write into the
        # windowed TimeSeries (rate()/quantile() "now" queries + SLO
        # burn rates); per-doc/agent usage feeds the top-K sketch
        self.metrics.ts = getattr(obs, "ts", None)
        if self.qos is not None:
            self.qos.attach_obs(obs)
        for bank in self.banks:
            bank.recorder = obs.recorder
            bank.journey = getattr(obs, "journey", None)
        if self.hydrator is not None:
            self.hydrator.recorder = obs.recorder
            self.hydrator.attrib = getattr(obs, "attrib", None)

    def attach_hydrator(self, hydrator) -> None:
        """Wire the residency tier in: `submit` prefetches on a doc's
        first admit (budgeted by the bucket flush deadline), the flush
        paths gate on warmth right after the lease fence (cold docs
        requeue — a delayed flush; quarantined docs drop before they
        can join a batch), and every bank eviction routes through the
        hydrator's snapshot queue instead of silently dropping pending
        state. The scheduler's `resolve` should be `hydrator.resolve`
        (the CLI soak wires it that way); `attach_hydrator` does not
        rebind it so store-backed resolves stay possible."""
        self.hydrator = hydrator
        if hydrator.metrics is None:
            hydrator.metrics = self.metrics
        if hydrator.oplog_lock is None and not isinstance(
                self._sync_lock, contextlib.nullcontext):
            hydrator.oplog_lock = self._sync_lock
        if self.obs is not None:
            hydrator.recorder = self.obs.recorder
            hydrator.attrib = getattr(self.obs, "attrib", None)
        for bank in self.banks:
            bank.snapshot_hook = hydrator.request_snapshot

    def attach_qos(self, controller) -> None:
        """Wire a qos.QosController into the admission path: the queue
        consults its published per-(shard, class) effective deadlines
        in place of the static trigger, submits bump its per-class
        counters, and start_pump/stop_pump own its control-loop thread.
        The controller takes its `qos` witness lock BEFORE this
        scheduler's global lock (qos(8) -> global(10) in the canonical
        order) when it reads queue fill each step."""
        controller.bind(self.queue, queue_lock=self.lock,
                        n_shards=self.queue.n_shards)
        if self.obs is not None:
            controller.attach_obs(self.obs)
        self.qos = controller
        self.queue.qos = controller

    # ---- intake ----------------------------------------------------------

    def submit(self, doc_id: str, n_ops: int = 1,
               now: Optional[float] = None, trace=None,
               qos: Optional[str] = None) -> dict:
        """Queue pending merge work. Returns {"accepted": True, "shard",
        "bucket"}, {"accepted": False, "retry_after"} on backpressure,
        or {"accepted": False, "reason": "not_owner"} when the
        ownership gate denies (never raises — rejects and denials are
        normal operation under load / during handoff). `trace` is an
        optional obs SpanContext (the originating HTTP edit); when its
        trace is sampled the admit, the ownership gate, and later the
        flush + device sync all join it. `qos` is the ingress-
        classified class (qos/classes.py; default interactive) — the
        shed gate itself runs at HTTP ingress, BEFORE the edit is
        durable, not here. Unknown classes normalize to interactive
        (mirroring classify_headers' typo-safe fallback) so a direct
        library caller can't poison per-class depth accounting or trip
        QosMetrics on an undeclared class."""
        now = time.monotonic() if now is None else now
        qos_cls = qos if qos in QOS_PRIORITY else "interactive"
        obs = self.obs
        span = NOOP_SPAN
        if obs is not None:
            span = obs.tracer.start("serve.admit", parent=trace,
                                    attrs={"doc": doc_id,
                                           "n_ops": n_ops})
        if self.admit is not None:
            gate = NOOP_SPAN if not span.sampled else obs.tracer.start(
                "serve.ownership_gate", parent=span.context(),
                attrs={"doc": doc_id})
            admitted = self.admit(doc_id)
            gate.end(admitted=admitted)
            if not admitted:
                # shard_of (not assign): a denied doc must not register
                # a live assignment this host will never flush
                shard = self.router.shard_of(doc_id)
                self.metrics.bump(shard, "denied")
                span.end(outcome="denied")
                return {"accepted": False, "shard": shard,
                        "reason": "not_owner"}
        hyd = self.hydrator
        if hyd is not None:
            if hyd.store.is_quarantined(doc_id) is not None:
                shard = self.router.shard_of(doc_id)
                span.end(outcome="quarantined")
                return {"accepted": False, "shard": shard,
                        "reason": "quarantined"}
            # async prefetch on FIRST admit, budgeted by the bucket
            # flush deadline — by the time the bucket is due the doc
            # is usually warm. The unlocked assignments peek is a
            # benign race: a doc already warm/pending is a no-op
            # prefetch, and prefetch itself re-checks under its lock.
            if doc_id not in self.router.assignments:
                hyd.prefetch(doc_id,
                             budget_s=self.queue.flush_deadline_s)
        # stamp the admit-time lease epoch; the flush rechecks it
        epoch = self.epoch_of(doc_id) if self.epoch_of is not None \
            else -1
        with self.lock:
            shard = self.router.assign(doc_id)
            self.metrics.bump(shard, "submits")
            already = self.queue.pending_bucket(shard, doc_id) is not None
            try:
                bucket = self.queue.submit(shard, doc_id, n_ops, now,
                                           epoch=epoch,
                                           trace=span.context(),
                                           qos=qos_cls)
            except Backpressure as bp:
                self.metrics.bump(shard, "rejects")
                span.end(outcome="backpressure")
                return {"accepted": False, "shard": shard,
                        "retry_after": bp.retry_after,
                        "qos": qos_cls}
            if already:
                self.metrics.bump(shard, "coalesced")
            self.metrics.observe_queue(shard, self.queue.depth(shard))
        if self.qos is not None:
            # per-class admitted counter — also the controller's
            # arrival-rate estimator input (qos.admitted.<cls> series)
            self.qos.metrics.bump_class(qos_cls, "admitted")
        span.end(outcome="queued", shard=shard, bucket=bucket)
        if obs is not None and span.sampled:
            # journey: open at the scheduler when the HTTP handler did
            # not (driver-driven submits) — begin() is first-wins, so
            # an ingress-admitted journey keeps its (agent, seq)
            j = obs.journey
            j.begin(None, None, doc=doc_id, trace=span.trace_id)
            j.stamp(span.trace_id, "queued")
        return {"accepted": True, "shard": shard, "bucket": bucket}

    # ---- flush -----------------------------------------------------------

    def pump(self, now: Optional[float] = None,
             force: bool = False) -> int:
        """Flush every due bucket. Returns the number of docs
        dispatched (synced inline, or handed to a shard worker).

        Queue mutation (due/take) happens under the global lock only;
        the flush work runs on per-shard worker threads (or inline
        without workers) under each shard's OWN lock, so shards flush
        concurrently and submits never wait on device calls (ROADMAP
        item (a)). Queue depths are re-recorded in a single pass after
        dispatch — one lock acquisition, each touched shard once."""
        now = time.monotonic() if now is None else now
        taken = []      # (shard, reason, items)
        with self.lock:
            for shard, bucket, reason in self.queue.due(now, force=force):
                items = self.queue.take(shard, bucket)
                if items:
                    taken.append((shard, reason, items))
        synced = 0
        if taken and self.mesh_window:
            # window coordinator: every due shard's bucket folds into
            # ONE mesh-sharded program instead of N worker dispatches
            synced = self._flush_window(taken)
        else:
            for shard, reason, items in taken:
                if self._flush_workers:
                    self._dispatch(shard, reason, items)
                else:
                    self._flush_items(shard, reason, items)
                synced += len(items)
            if taken:
                # the PR-5 control's dispatch accounting: one handoff
                # (>= one device call) per taken bucket per window
                self.metrics.record_window(
                    len(taken), synced,
                    len({s for s, _r, _i in taken}))
        if taken:
            with self.lock:
                for shard in {s for s, _r, _i in taken}:
                    self.metrics.observe_queue(
                        shard, self.queue.depth(shard))
        return synced

    # ---- worker pool -----------------------------------------------------

    def _dispatch(self, shard: int, reason: str, items) -> None:
        """Hand one taken batch to its shard's worker (spawned lazily:
        a host-engine scheduler that never pumps never pays for
        threads)."""
        with self._idle_cv:
            self._inflight += 1
        if self._workers[shard] is None:
            t = threading.Thread(target=self._worker_loop, args=(shard,),
                                 name=f"flush-worker-{shard}",
                                 daemon=True)
            self._workers[shard] = t
            t.start()
        self._work_qs[shard].put((reason, items))

    def _worker_loop(self, shard: int) -> None:
        q = self._work_qs[shard]
        while True:
            job = q.get()
            if job is None:
                return
            reason, items = job
            try:
                self._flush_items(shard, reason, items)
            except Exception:   # pragma: no cover - keep the shard alive
                pass
            finally:
                with self._idle_cv:
                    self._inflight -= 1
                    self._idle_cv.notify_all()

    def _wait_idle(self, timeout: float = 30.0) -> None:
        """Block until every dispatched batch has been flushed."""
        deadline = time.monotonic() + timeout
        with self._idle_cv:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:   # pragma: no cover - defensive
                    return
                self._idle_cv.wait(timeout=left)

    def stop_workers(self) -> None:
        """Join the flush workers deterministically (after a drain()).
        Safe to call repeatedly; workers respawn on the next pump."""
        self._wait_idle()
        for i, w in enumerate(self._workers):
            if w is not None:
                self._work_qs[i].put(None)
        for i, w in enumerate(self._workers):
            if w is not None:
                w.join(timeout=5)
                self._workers[i] = None

    def _fence(self, shard: int, items) -> list:
        """Lease-epoch recheck: drop work admitted under an epoch this
        host no longer holds (`fenced`) — its ops stay durable in the
        oplog for the new owner. Shared by the per-shard flush (recheck
        at merge time inside the worker) and the mesh window coordinator
        (recheck at WINDOW ASSEMBLY — the last host-side moment before a
        doc's rows join the shared super-batch)."""
        if self.epoch_of is None:
            return items
        kept = []
        for item in items:
            if item.epoch != -1 \
                    and self.epoch_of(item.doc_id) != item.epoch:
                self.metrics.bump(shard, "fenced")
                if self.obs is not None:
                    self.obs.recorder.record("flush_fenced",
                                             doc=item.doc_id,
                                             shard=shard,
                                             admit_epoch=item.epoch)
            else:
                kept.append(item)
        return kept

    def _flush_resolve(self, doc_id: str):
        """The flush paths' resolve: identical to `self.resolve` except
        that an exception INSIDE a batch is counted as a flush leak —
        the hydration gate should have filtered the doc first, so the
        soak asserts this stays zero."""
        try:
            return self.resolve(doc_id)
        except Exception as e:
            if self.hydrator is not None:
                self.hydrator.note_flush_leak(doc_id, e)
            raise

    def _hydration_gate(self, shard: int, items) -> list:
        """Residency recheck right after the lease fence: keep warm
        docs, DROP quarantined ones (they must never join a batch),
        and REQUEUE still-cold ones — a delayed flush on the next pump
        once hydration lands, never a stalled batch waiting on disk."""
        hyd = self.hydrator
        if hyd is None:
            return items
        keep, defer, dropped = hyd.flush_gate(shard, items)
        if defer:
            now = time.monotonic()
            with self.lock:
                for it in defer:
                    try:
                        self.queue.submit(shard, it.doc_id, it.n_ops,
                                          now, epoch=it.epoch,
                                          trace=it.trace)
                    except Backpressure:
                        # the queue refilled while this batch was in
                        # flight; the doc's ops are durable, drop the
                        # merge work like a fenced item
                        hyd._bump("deferred_drops")
        if dropped and self.obs is not None:
            self.obs.recorder.record(
                "flush_gate_dropped", shard=shard, docs=len(dropped))
        return keep

    def _flush_items(self, shard: int, reason: str, items) -> None:
        """Sync one taken batch into its shard's bank, under that
        shard's lock only (items are already off the queue, so a
        concurrent submit for the same doc simply queues fresh work).
        The fencing recheck runs first: work admitted under a lease
        epoch this host no longer holds is dropped (`fenced`), never
        merged — its ops are still in the oplog for the new owner.
        The hydration gate runs second (residency recheck: see
        `_hydration_gate`)."""
        obs = self.obs
        items = self._fence(shard, items)
        items = self._hydration_gate(shard, items)
        if not items:
            return
        fspan = NOOP_SPAN
        if obs is not None:
            parent = next(
                (i.trace for i in items if i.trace is not None), None)
            if parent is not None:
                fspan = obs.tracer.start(
                    "serve.flush", parent=parent,
                    attrs={"shard": shard, "reason": reason,
                           "docs": len(items)})
        bank = self.banks[shard]
        t0 = time.perf_counter()
        with self._shard_locks[shard]:
            # one device_sync span per taken batch — the whole bucket
            # is (at best) ONE device call now, so per-doc spans would
            # misrepresent the execution shape
            dspan = NOOP_SPAN if not fspan.sampled else \
                obs.tracer.start("serve.device_sync",
                                 parent=fspan.context(),
                                 attrs={"docs": len(items)})
            res = bank.sync_docs(
                items, self._flush_resolve, oplog_lock=self._sync_lock,
                device_lock=self._device_locks[shard])
            dspan.end(fused_calls=res["fused_calls"],
                      fused_docs=res["fused_docs"])
        dur = time.perf_counter() - t0
        fspan.end(dur_s=round(dur, 6))
        self.metrics.record_flush(
            shard, len(items), sum(i.n_ops for i in items), reason,
            dur_s=dur)
        # live telemetry: admit->flush queue wait per merged item (the
        # admission SLO), a flush-latency exemplar when this flush rode
        # a sampled trace, and per-doc ops/device-time attribution
        now_m = time.monotonic()
        for it in items:
            self.metrics.observe_queue_wait(
                max(0.0, now_m - it.enqueued_at))
        if obs is not None:
            if fspan.sampled:
                obs.exemplars.note("serve.flush", dur,
                                   fspan.context().trace_id)
            dev_share = dur / len(items)
            for it in items:
                obs.attrib.note("ops", doc=it.doc_id, n=it.n_ops)
                obs.attrib.note("device_s", doc=it.doc_id, n=dev_share)
        if self.read_invalidate is not None:
            for it in items:
                self.read_invalidate(it.doc_id)

    # ---- mesh flush window -----------------------------------------------

    def _get_mesh(self):
        """Lazy serve mesh over the shard devices (also built by bank
        0's background warmup indirectly, via the shared jit cache).
        Called BEFORE any shard lock is taken — it briefly needs the
        global lock, and lock order is global → shard, never back."""
        m = self._mesh
        if m is None:
            from ..parallel.mesh import serve_mesh
            with self.lock:
                if self._mesh is None:
                    self._mesh = serve_mesh(len(self.banks))
                m = self._mesh
        return m

    def _flush_window(self, taken) -> int:
        """The mesh flush-window coordinator: ONE device program per
        window instead of one per shard.

        Every due bucket in `taken` — across ALL shards — goes through:

          1. fencing recheck (window assembly is merge time here);
          2. host-side planning per shard (`bank.plan_window`,
             min_fuse=1: lone docs join the shared dispatch);
          3. fusable rows concatenated ACROSS shards by (cap, max_ins)
             shape class and replayed by `mesh_fused_replay` — one
             `shard_map` program over the serve mesh's `docs` axis per
             class (uniform-shape window ⇒ exactly one dispatch);
          4. per-shard adoption (`bank.adopt_window`): poisoned /
             length-drift rows evict to the host oracle, serial
             leftovers run the per-doc ladder — the SAME fallback
             ladder as the per-shard path, one rung higher.

        A mesh replay failure drops its rows to the per-shard fused
        rung (`_window_mesh_fallback`) before the per-doc/host rungs,
        so the ladder is strictly widened, never bypassed.

        Lock order: shard locks (sorted) → oplog lock (inside
        plan/adopt) → device locks (sorted, deduped); the mesh device
        phase holds ONLY the device locks of the shards in the window.
        Returns the number of docs flushed (post-fencing)."""
        from ..obs.devprof import PROFILER
        from ..parallel.mesh import mesh_fused_replay
        obs = self.obs
        entries = []        # (shard, reason, items) — post-fencing
        for shard, reason, items in taken:
            items = self._fence(shard, items)
            items = self._hydration_gate(shard, items)
            if items:
                entries.append((shard, reason, items))
        if not entries:
            # an all-fenced window still counts (dispatches=0 keeps it
            # out of the device_calls_per_window denominator)
            self.metrics.record_window(
                0, 0, len({s for s, _r, _i in taken}))
            return 0
        mesh = self._get_mesh()     # needs self.lock: before shard locks
        shards = sorted({s for s, _r, _i in entries})
        n_docs = sum(len(i) for _s, _r, i in entries)
        fspan = NOOP_SPAN
        if obs is not None:
            parent = next((i.trace for _s, _r, its in entries
                           for i in its if i.trace is not None), None)
            if parent is not None:
                fspan = obs.tracer.start(
                    "serve.mesh_window", parent=parent,
                    attrs={"shards": len(shards), "docs": n_docs})
        t0 = time.perf_counter()
        with contextlib.ExitStack() as sstack:
            for s in shards:
                sstack.enter_context(self._shard_locks[s])
            wins = [self.banks[s].plan_window(
                        items, self._flush_resolve,
                        oplog_lock=self._sync_lock, min_fuse=1)
                    for s, _r, items in entries]
            # concatenate fusable rows across shards by shape class —
            # rows sharing (cap, max_ins) share one mesh program
            classes: Dict[tuple, list] = {}
            for ei, (s, _r, _items) in enumerate(entries):
                for sessions, plans, doc_ids in wins[ei]["groups"]:
                    for sess, plan, d in zip(sessions, plans, doc_ids):
                        classes.setdefault(
                            (sess.cap, sess.max_ins), []).append(
                                (ei, s, sess, plan, d))
            # device locks of the window's shards, deduped in shard
            # order (co-located shards share a lock object). The
            # comprehension runs directly over the sorted shard list so
            # the acquisition order is lexically evident (dt-lint
            # unsorted-locks) and matches the witness's rank order.
            seen: set = set()
            dlocks = [lk for s in shards
                      if id(lk := self._device_locks[s]) not in seen
                      and not seen.add(id(lk))]
            dispatches = mesh_docs = padded_rows = staged_bytes = 0
            failed: List[List[str]] = [[] for _ in entries]
            replayed: List[set] = [set() for _ in entries]
            for (cap, mi), rows in sorted(classes.items()):
                sessions = [r[2] for r in rows]
                plans = [r[3] for r in rows]
                t_cls = time.perf_counter()
                with contextlib.ExitStack() as dstack:
                    for lk in dlocks:
                        dstack.enter_context(lk)
                    dspan = NOOP_SPAN if not fspan.sampled else \
                        obs.tracer.start(
                            "serve.mesh_dispatch",
                            parent=fspan.context(),
                            attrs={"docs": len(rows), "cap": cap,
                                   "max_ins": mi})
                    ok = None
                    staged = 0
                    if self.pallas and len(dlocks) <= 1:
                        # top rung: the Pallas step-kernel replay.
                        # Single-device windows only — the Pallas
                        # program is not mesh-sharded, so a window
                        # spanning devices goes straight to the mesh
                        # rung. Any failure falls through with the
                        # rows untouched (commits happen only at the
                        # adopt_results fence inside a successful
                        # replay).
                        from ..tpu import flush_fuse as _ff
                        try:
                            ok, device_s = _ff.pallas_fused_replay(
                                sessions, plans)
                            dispatches += 1
                            dspan.end(rung="pallas")
                        except Exception as e:
                            ok = None
                            if obs is not None:
                                obs.recorder.record(
                                    "pallas_window_fallback",
                                    docs=len(rows), cap=cap,
                                    error=f"{e.__class__.__name__}: "
                                          f"{e}"[:120])
                    if ok is None:
                        try:
                            ok, device_s, bp, staged = \
                                mesh_fused_replay(mesh, sessions, plans)
                            dispatches += 1
                            mesh_docs += len(rows)
                            padded_rows += bp
                            staged_bytes += staged
                            dspan.end(padded_b=bp, staged_bytes=staged)
                        except Exception as e:
                            # mesh rung failed: these rows drop to the
                            # per-shard fused rung; whatever that can't
                            # recover falls per-doc/host in adoption
                            if obs is not None:
                                obs.recorder.record(
                                    "mesh_window_fallback",
                                    docs=len(rows), cap=cap,
                                    error=f"{e.__class__.__name__}: "
                                          f"{e}"[:120])
                            ok, device_s, calls = \
                                self._window_mesh_fallback(rows)
                            dispatches += calls
                            dspan.end(outcome="fallback")
                wall = time.perf_counter() - t_cls
                PROFILER.observe_window(wall, device_s, len(rows),
                                        len(shards),
                                        staged_bytes=staged)
                for good, (ei, _s, _sess, _plan, d) in zip(ok, rows):
                    if good:
                        replayed[ei].add(d)
                    else:
                        failed[ei].append(d)
            # journey: the window path orchestrates the device phase
            # itself, so the device_replayed stamp lives here (the
            # per-shard path stamps inside bank.sync_docs); planned /
            # adopted ride plan_window / adopt_window for both paths
            if obs is not None:
                j = obs.journey
                for ei, (_s, _r, its) in enumerate(entries):
                    for it in its:
                        if (it.trace is not None and it.trace.sampled
                                and it.doc_id in replayed[ei]):
                            j.stamp(it.trace.trace_id,
                                    "device_replayed")
            # adoption + per-bucket flush accounting, per shard
            for ei, (s, reason, items) in enumerate(entries):
                self.banks[s].adopt_window(
                    wins[ei], failed[ei], oplog_lock=self._sync_lock,
                    device_lock=self._device_locks[s])
                self.metrics.record_flush(
                    s, len(items), sum(i.n_ops for i in items), reason,
                    dur_s=time.perf_counter() - t0)
                if self.read_invalidate is not None:
                    for it in items:
                        self.read_invalidate(it.doc_id)
        dur = time.perf_counter() - t0
        fspan.end(dur_s=round(dur, 6), dispatches=dispatches)
        self.metrics.record_window(dispatches, n_docs, len(shards),
                                   mesh_docs=mesh_docs,
                                   padded_rows=padded_rows,
                                   staged_bytes=staged_bytes)
        # live telemetry (mirrors _flush_items): queue waits, a flush
        # exemplar off the window span, per-doc attribution
        now_m = time.monotonic()
        dev_share = dur / max(n_docs, 1)
        for _s, _r, its in entries:
            for it in its:
                self.metrics.observe_queue_wait(
                    max(0.0, now_m - it.enqueued_at))
                if obs is not None:
                    obs.attrib.note("ops", doc=it.doc_id, n=it.n_ops)
                    obs.attrib.note("device_s", doc=it.doc_id,
                                    n=dev_share)
        if obs is not None and fspan.sampled:
            obs.exemplars.note("serve.flush", dur,
                               fspan.context().trace_id)
        return n_docs

    def _window_mesh_fallback(self, rows):
        """Mesh rung failed for one shape class: re-run its rows
        through the PR-5 per-shard fused rung, grouped back by shard.
        Rows a shard's replay can't recover (or whose replay raises
        too) stay failed and fall to the per-doc/host rungs in
        adoption. Returns (ok, device_s, dispatches) with `ok` aligned
        to `rows`."""
        from ..tpu.flush_fuse import fused_replay
        ok = [False] * len(rows)
        device_s = 0.0
        calls = 0
        by_shard: Dict[int, List[int]] = {}
        for idx, (_ei, s, _sess, _plan, _d) in enumerate(rows):
            by_shard.setdefault(s, []).append(idx)
        for s, idxs in sorted(by_shard.items()):
            bank = self.banks[s]
            sess = [rows[i][2] for i in idxs]
            plans = [rows[i][3] for i in idxs]
            try:
                if bank.device is not None:
                    import jax
                    with jax.default_device(bank.device):
                        oks, ds = fused_replay(sess, plans)
                else:
                    oks, ds = fused_replay(sess, plans)
                calls += 1
                device_s += ds
                self.metrics.record_fused(s, len(idxs))
                for i, good in zip(idxs, oks):
                    ok[i] = good
            except Exception:
                pass    # rows stay failed → host fallback in adoption
        return ok, device_s, calls

    def drain(self) -> int:
        """Flush everything regardless of triggers (shutdown, rebalance,
        parity checks), then wait for the shard workers to go idle —
        the return means every dispatched doc has actually merged. A
        hydration gate deferral requeues from INSIDE a flush worker, so
        after the workers go idle the depth is re-checked: deferred
        docs get further rounds until they hydrate (bounded by the
        hydrator's defer budget) or the queue is genuinely empty."""
        total = 0
        while True:
            progressed = False
            while self.queue.total_depth():
                n = self.pump(force=True)
                if n == 0:
                    break     # defensive: a take() returning nothing
                progressed = True
                total += n
            self._wait_idle()
            if not self.queue.total_depth() or not progressed:
                return total

    # ---- reads / control -------------------------------------------------

    def text(self, doc_id: str) -> str:
        """Merged text from the doc's shard (device-resident state when
        present). Pending queued work for the doc is flushed first so
        the answer reflects every accepted submit. Reads never dispatch
        device work under the oplog guard: a session behind the durable
        oplog serves the oplog's tip snapshot instead, and the flush
        pipeline catches it up off the read path."""
        with self.lock:
            shard = self.router.assign(doc_id)
            bucket = self.queue.pending_bucket(shard, doc_id)
            items = []
            if bucket is not None:
                # flush the doc's whole bucket (its neighbors share the
                # shape anyway), counted as a read-triggered flush
                items = self.queue.take(shard, bucket,
                                        limit=self.queue.max_pending)
        if items:
            self._flush_items(shard, "read", items)
            with self.lock:
                self.metrics.observe_queue(shard,
                                           self.queue.depth(shard))
        ol = self.resolve(doc_id)
        # cross-host ownership gate: a deposed or never-owner host must
        # not serve (or refresh) its device session for the doc — the
        # durable oplog is the only truth it still holds
        if self.admit is not None and not self.admit(doc_id):
            with self._sync_lock:
                return ol.checkout_tip().snapshot()
        with self._shard_locks[shard]:
            return self.banks[shard].text(
                doc_id, ol, oplog_lock=self._sync_lock,
                device_lock=self._device_locks[shard])

    def rebalance(self, n_shards: int) -> Dict[str, tuple]:
        """Shrink (or restore) the live shard count: drain pending work,
        re-route, and evict moved docs' sessions from their OLD shards
        (they rebuild on the new shard at next merge). Growing past the
        constructed bank count needs a new scheduler — banks hold device
        placement decided at construction."""
        if n_shards > len(self.banks):
            raise ValueError(
                f"cannot grow past the constructed {len(self.banks)} "
                "shards; build a new MergeScheduler")
        self.drain()
        with self.lock:
            moved = self.router.rebalance(n_shards)
        for doc_id, (old, _new) in moved.items():
            with self._shard_locks[old]:
                self.banks[old].evict(doc_id)
        return moved

    def metrics_json(self) -> dict:
        snap = self.metrics.snapshot()
        snap["router_counts"] = self.router.counts()
        return snap

    # ---- background pump -------------------------------------------------

    def start_pump(self, interval_s: Optional[float] = None) -> None:
        if self._pump_thread is not None:
            return
        interval = interval_s if interval_s is not None else \
            max(self.queue.flush_deadline_s / 2, 0.01)

        def loop():
            while not self._pump_stop.wait(interval):
                try:
                    self.pump()
                except Exception:       # pragma: no cover - keep pumping
                    pass

        self._pump_thread = threading.Thread(target=loop, daemon=True)
        self._pump_thread.start()
        if self.qos is not None:
            # the controller's loop lives and dies with the pump: no
            # pump, no flushes, nothing for the deadlines to steer
            self.qos.start()

    def stop_pump(self, drain: bool = True) -> None:
        if self.qos is not None:
            self.qos.stop()
        self._pump_stop.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=2)
            self._pump_thread = None
        self._pump_stop = threading.Event()
        if drain:
            self.drain()
        self.stop_workers()
