"""Deterministic doc-id -> shard routing (rendezvous hashing).

Every process that sees the same (doc_id, n_shards, salt) must pick the
same shard — routing happens in the sync server, in serve-bench workers
and in soak tools, and a disagreement would put two live sessions of one
document on different chips. Python's builtin `hash` is per-process
randomized, so scores come from blake2b instead.

Rendezvous (highest-random-weight) hashing rather than `hash % n`: when
the shard count changes, only the docs whose argmax shard changed move
(expected fraction |n' - n| / max(n, n')), instead of nearly all of
them. `rebalance()` makes that movement explicit: it returns exactly the
docs that moved so the caller can drain/flush their sessions before the
new placement takes effect.

The elastic-mesh rebalancer adds one escape hatch: `pin(doc_id, shard)`
overrides the hash for a specific doc (a host absorbing a migrated hot
doc steers it onto its least-loaded shard). Pins are local,
process-lifetime state — cross-host placement authority lives in the
replication tier's PlacementOverrides table, not here.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Tuple


def _score(doc_id: str, shard: int, salt: bytes) -> int:
    h = hashlib.blake2b(digest_size=8, salt=salt[:16])
    h.update(doc_id.encode("utf8"))
    h.update(shard.to_bytes(4, "little"))
    return int.from_bytes(h.digest(), "little")


class ShardRouter:
    """Stateless `shard_of` + a registry of live assignments so rebalance
    can report movement (the registry is bookkeeping, not authority: the
    hash alone decides placement)."""

    def __init__(self, n_shards: int, salt: str = "dt-serve") -> None:
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = n_shards
        self.salt = salt.encode("utf8")
        self.assignments: Dict[str, int] = {}
        # rebalancer pins: doc -> shard, consulted before the hash
        self.pins: Dict[str, int] = {}

    def pin(self, doc_id: str, shard: int) -> None:
        if not (0 <= shard < self.n_shards):
            raise ValueError("shard out of range")
        self.pins[doc_id] = shard
        # a live assignment must follow the pin or counts() lies
        if doc_id in self.assignments:
            self.assignments[doc_id] = shard

    def unpin(self, doc_id: str) -> None:
        self.pins.pop(doc_id, None)

    def shard_of(self, doc_id: str) -> int:
        pinned = self.pins.get(doc_id)
        if pinned is not None and pinned < self.n_shards:
            return pinned
        best, best_score = 0, -1
        for s in range(self.n_shards):
            sc = _score(doc_id, s, self.salt)
            # ties broken by the lower shard id (sc > best_score, not >=)
            if sc > best_score:
                best, best_score = s, sc
        return best

    def assign(self, doc_id: str) -> int:
        s = self.assignments.get(doc_id)
        if s is None:
            s = self.assignments[doc_id] = self.shard_of(doc_id)
        return s

    def forget(self, doc_id: str) -> None:
        self.assignments.pop(doc_id, None)

    def counts(self) -> List[int]:
        out = [0] * self.n_shards
        for s in self.assignments.values():
            out[s] += 1
        return out

    def rebalance(self, n_shards: int) -> Dict[str, Tuple[int, int]]:
        """Re-route every registered doc for a new shard count. Returns
        {doc_id: (old_shard, new_shard)} for exactly the docs that moved;
        the registry is updated in place. The caller owns draining the
        moved docs' old-shard sessions BEFORE resuming submits."""
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        old = dict(self.assignments)
        self.n_shards = n_shards
        moved: Dict[str, Tuple[int, int]] = {}
        for doc_id, prev in old.items():
            new = self.shard_of(doc_id)
            self.assignments[doc_id] = new
            if new != prev:
                moved[doc_id] = (prev, new)
        return moved
