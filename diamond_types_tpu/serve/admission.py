"""Shape-bucketed admission queues with bounded depth + backpressure.

The device tier amortizes dispatch overhead only when work of one padded
shape is flushed together (the zone session's jit cache is keyed on the
padded micro-tape length; a flush whose docs share a bucket shares one
compiled program). Pending merges are therefore bucketed by the
next-power-of-two of their pending op count and flushed when EITHER
trigger fires (Just-in-Time Dynamic Batching, arxiv 1904.07421):

  * size     — a bucket reached `flush_docs` distinct documents;
  * deadline — the bucket's OLDEST entry has waited `flush_deadline_s`
               (latency bound: a lone doc is never starved by the size
               trigger).

Depth is bounded per shard. A submit that would push a shard past
`max_pending` pending DOCUMENTS raises `Backpressure` with a
`retry_after` hint instead of growing the queue — the caller (HTTP
handler, bench driver) surfaces it as a 429-style reject-with-retry.
Re-submitting a doc that is already queued never adds depth: the
pending entry coalesces (its op count accumulates; it may migrate to a
larger shape bucket; its deadline clock keeps the ORIGINAL enqueue time
so coalescing cannot starve the deadline trigger).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


def shape_bucket(n_ops: int) -> int:
    """Next power of two >= n_ops (minimum 1) — the padded shape class."""
    n = max(int(n_ops), 1)
    b = 1
    while b < n:
        b <<= 1
    return b


@dataclass
class PendingMerge:
    doc_id: str
    n_ops: int
    enqueued_at: float
    # lease epoch under which the work was admitted (-1 = unfenced,
    # single-host). The scheduler rechecks it at flush time: work
    # admitted under a lease this host no longer holds is dropped, not
    # merged (the new owner merges the same durable oplog instead).
    epoch: int = -1
    # obs.trace.SpanContext of the sampled admit that queued this work
    # (None when unsampled/untraced) — lets the flush span parent on
    # the originating edit's trace
    trace: object = None


class Backpressure(Exception):
    """Shard queue is full; retry after `retry_after` seconds."""

    def __init__(self, shard: int, depth: int, retry_after: float) -> None:
        self.shard = shard
        self.depth = depth
        self.retry_after = retry_after
        super().__init__(
            f"shard {shard} admission queue full ({depth} pending); "
            f"retry after {retry_after:.3f}s")


class AdmissionQueue:
    def __init__(self, n_shards: int, max_pending: int = 256,
                 flush_docs: int = 8,
                 flush_deadline_s: float = 0.05) -> None:
        if max_pending < 1 or flush_docs < 1:
            raise ValueError("max_pending and flush_docs must be >= 1")
        self.n_shards = n_shards
        self.max_pending = max_pending
        self.flush_docs = flush_docs
        self.flush_deadline_s = flush_deadline_s
        # shard -> bucket -> doc_id -> PendingMerge (dict = FIFO order)
        self._q: List[Dict[int, Dict[str, PendingMerge]]] = [
            {} for _ in range(n_shards)]
        self._where: List[Dict[str, int]] = [{} for _ in range(n_shards)]

    # ---- intake ----------------------------------------------------------

    def depth(self, shard: int) -> int:
        return len(self._where[shard])

    def pending_bucket(self, shard: int, doc_id: str) -> Optional[int]:
        """The shape bucket `doc_id` is queued under, or None."""
        return self._where[shard].get(doc_id)

    def total_depth(self) -> int:
        return sum(len(w) for w in self._where)

    def submit(self, shard: int, doc_id: str, n_ops: int,
               now: float, epoch: int = -1, trace=None) -> int:
        """Queue (or coalesce) `n_ops` of pending merge work for
        `doc_id`. Returns the shape bucket it landed in. Raises
        Backpressure instead of exceeding `max_pending` docs/shard.
        Coalescing adopts the LATEST lease epoch — earlier queued ops
        are covered by the newer admit decision — and keeps a sampled
        trace context if any submit in the batch carried one."""
        where = self._where[shard]
        old_bucket = where.get(doc_id)
        if old_bucket is not None:
            item = self._q[shard][old_bucket].pop(doc_id)
            item.n_ops += max(int(n_ops), 0)
            item.epoch = epoch
            if trace is not None:
                item.trace = trace
            bucket = shape_bucket(item.n_ops)
            self._q[shard].setdefault(bucket, {})[doc_id] = item
            where[doc_id] = bucket
            return bucket
        if len(where) >= self.max_pending:
            # the deadline trigger drains the oldest bucket within one
            # deadline window; that is the honest earliest retry time
            raise Backpressure(shard, len(where), self.flush_deadline_s)
        bucket = shape_bucket(n_ops)
        self._q[shard].setdefault(bucket, {})[doc_id] = PendingMerge(
            doc_id, max(int(n_ops), 1), now, epoch, trace)
        where[doc_id] = bucket
        return bucket

    # ---- flush triggers --------------------------------------------------

    def due(self, now: float,
            force: bool = False) -> List[Tuple[int, int, str]]:
        """(shard, bucket, reason) for every bucket whose size or
        deadline trigger fired (every non-empty bucket when `force`)."""
        out: List[Tuple[int, int, str]] = []
        for shard in range(self.n_shards):
            for bucket, docs in self._q[shard].items():
                if not docs:
                    continue
                if force:
                    out.append((shard, bucket, "force"))
                elif len(docs) >= self.flush_docs:
                    out.append((shard, bucket, "size"))
                else:
                    oldest = next(iter(docs.values()))
                    if now - oldest.enqueued_at >= self.flush_deadline_s:
                        out.append((shard, bucket, "deadline"))
        return out

    def take(self, shard: int, bucket: int,
             limit: Optional[int] = None) -> List[PendingMerge]:
        """Dequeue up to `limit` (default `flush_docs`) docs from one
        bucket, FIFO."""
        docs = self._q[shard].get(bucket)
        if not docs:
            return []
        k = limit if limit is not None else self.flush_docs
        out = []
        for doc_id in list(docs)[:k]:
            out.append(docs.pop(doc_id))
            del self._where[shard][doc_id]
        if not docs:
            del self._q[shard][bucket]
        return out
