"""Shape-bucketed admission queues with bounded depth + backpressure.

The device tier amortizes dispatch overhead only when work of one padded
shape is flushed together (the zone session's jit cache is keyed on the
padded micro-tape length; a flush whose docs share a bucket shares one
compiled program). Pending merges are therefore bucketed by the
next-power-of-two of their pending op count and flushed when EITHER
trigger fires (Just-in-Time Dynamic Batching, arxiv 1904.07421):

  * size     — a bucket reached `flush_docs` distinct documents;
  * deadline — the bucket's OLDEST entry has waited `flush_deadline_s`
               (latency bound: a lone doc is never starved by the size
               trigger).

Depth is bounded per shard. A submit that would push a shard past
`max_pending` pending DOCUMENTS raises `Backpressure` with a
`retry_after` hint instead of growing the queue — the caller (HTTP
handler, bench driver) surfaces it as a 429-style reject-with-retry.
Re-submitting a doc that is already queued never adds depth: the
pending entry coalesces (its op count accumulates; it may migrate to a
larger shape bucket; its deadline clock keeps the ORIGINAL enqueue time
so coalescing cannot starve the deadline trigger).

QoS (qos/): every item carries a class (interactive/bulk/catchup).
With a controller attached (`self.qos`, set by MergeScheduler.
attach_qos) the deadline trigger consults the controller's published
per-(shard, class) effective deadline instead of the static
`flush_deadline_s` — each class's OWN oldest entry is checked, so a
mixed bucket flushes when the earliest per-class deadline passes (a
stretched bulk deadline never delays an interactive doc queued behind
it) — and each class is additionally bounded to its own
depth budget (a fraction of `max_pending`). With no controller the
static trigger runs byte-identically to before — the qos field rides
along inert.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..qos.classes import QOS_PRIORITY


def shape_bucket(n_ops: int) -> int:
    """Next power of two >= n_ops (minimum 1) — the padded shape class."""
    n = max(int(n_ops), 1)
    b = 1
    while b < n:
        b <<= 1
    return b


@dataclass
class PendingMerge:
    doc_id: str
    n_ops: int
    enqueued_at: float
    # lease epoch under which the work was admitted (-1 = unfenced,
    # single-host). The scheduler rechecks it at flush time: work
    # admitted under a lease this host no longer holds is dropped, not
    # merged (the new owner merges the same durable oplog instead).
    epoch: int = -1
    # obs.trace.SpanContext of the sampled admit that queued this work
    # (None when unsampled/untraced) — lets the flush span parent on
    # the originating edit's trace
    trace: object = None
    # QoS class the work was admitted under (qos/classes.py); decides
    # which effective deadline the bucket's trigger consults when a
    # controller is attached
    qos: str = "interactive"


class Backpressure(Exception):
    """Shard queue is full; retry after `retry_after` seconds."""

    def __init__(self, shard: int, depth: int, retry_after: float) -> None:
        self.shard = shard
        self.depth = depth
        self.retry_after = retry_after
        super().__init__(
            f"shard {shard} admission queue full ({depth} pending); "
            f"retry after {retry_after:.3f}s")


class AdmissionQueue:
    def __init__(self, n_shards: int, max_pending: int = 256,
                 flush_docs: int = 8,
                 flush_deadline_s: float = 0.05) -> None:
        if max_pending < 1 or flush_docs < 1:
            raise ValueError("max_pending and flush_docs must be >= 1")
        self.n_shards = n_shards
        self.max_pending = max_pending
        self.flush_docs = flush_docs
        self.flush_deadline_s = flush_deadline_s
        # shard -> bucket -> doc_id -> PendingMerge (dict = FIFO order)
        self._q: List[Dict[int, Dict[str, PendingMerge]]] = [
            {} for _ in range(n_shards)]
        self._where: List[Dict[str, int]] = [{} for _ in range(n_shards)]
        # qos.QosController (or None = static trigger). Set by
        # MergeScheduler.attach_qos; read lock-free on the hot path.
        self.qos = None
        # shard -> class -> pending-doc count (per-class depth budgets;
        # maintained unconditionally, enforced only with a controller)
        self._class_depth: List[Dict[str, int]] = [
            {} for _ in range(n_shards)]

    # ---- intake ----------------------------------------------------------

    def depth(self, shard: int) -> int:
        return len(self._where[shard])

    def pending_bucket(self, shard: int, doc_id: str) -> Optional[int]:
        """The shape bucket `doc_id` is queued under, or None."""
        return self._where[shard].get(doc_id)

    def total_depth(self) -> int:
        return sum(len(w) for w in self._where)

    def class_depth(self, shard: int, qos: str) -> int:
        return self._class_depth[shard].get(qos, 0)

    def bucket_fill(self, shard: int) -> int:
        """Doc count of the shard's fullest shape bucket (0 = empty) —
        the controller's occupancy-gap input. Call under the same lock
        that guards submit/take (the scheduler's global lock)."""
        docs = self._q[shard]
        return max((len(d) for d in docs.values()), default=0)

    def _deadline_for(self, shard: int, qos: str) -> float:
        ctl = self.qos
        if ctl is None:
            return self.flush_deadline_s
        return ctl.effective_deadline(shard, qos)

    def submit(self, shard: int, doc_id: str, n_ops: int,
               now: float, epoch: int = -1, trace=None,
               qos: str = "interactive") -> int:
        """Queue (or coalesce) `n_ops` of pending merge work for
        `doc_id`. Returns the shape bucket it landed in. Raises
        Backpressure instead of exceeding `max_pending` docs/shard (or,
        with a controller attached, the class's own depth budget).
        Coalescing adopts the LATEST lease epoch — earlier queued ops
        are covered by the newer admit decision — keeps a sampled trace
        context if any submit in the batch carried one, and keeps the
        most URGENT class seen (an interactive re-touch of a queued
        bulk doc must not wait out the bulk deadline)."""
        where = self._where[shard]
        cdepth = self._class_depth[shard]
        old_bucket = where.get(doc_id)
        if old_bucket is not None:
            item = self._q[shard][old_bucket].pop(doc_id)
            item.n_ops += max(int(n_ops), 0)
            item.epoch = epoch
            if trace is not None:
                item.trace = trace
            if QOS_PRIORITY.get(qos, 0) < QOS_PRIORITY.get(item.qos, 0):
                cdepth[item.qos] = cdepth.get(item.qos, 1) - 1
                cdepth[qos] = cdepth.get(qos, 0) + 1
                item.qos = qos
            bucket = shape_bucket(item.n_ops)
            self._q[shard].setdefault(bucket, {})[doc_id] = item
            where[doc_id] = bucket
            return bucket
        ctl = self.qos
        if len(where) >= self.max_pending:
            # the deadline trigger drains the oldest bucket within one
            # deadline window; that is the honest earliest retry time
            raise Backpressure(shard, len(where),
                               self._deadline_for(shard, qos))
        if ctl is not None and cdepth.get(qos, 0) \
                >= ctl.depth_budget(qos, self.max_pending):
            raise Backpressure(shard, cdepth.get(qos, 0),
                               self._deadline_for(shard, qos))
        bucket = shape_bucket(n_ops)
        self._q[shard].setdefault(bucket, {})[doc_id] = PendingMerge(
            doc_id, max(int(n_ops), 1), now, epoch, trace, qos)
        where[doc_id] = bucket
        cdepth[qos] = cdepth.get(qos, 0) + 1
        return bucket

    # ---- flush triggers --------------------------------------------------

    def due(self, now: float,
            force: bool = False) -> List[Tuple[int, int, str]]:
        """(shard, bucket, reason) for every bucket whose size or
        deadline trigger fired (every non-empty bucket when `force`)."""
        out: List[Tuple[int, int, str]] = []
        for shard in range(self.n_shards):
            # class -> effective deadline, memoized per shard pass
            deadlines: Dict[str, float] = {}
            for bucket, docs in self._q[shard].items():
                if not docs:
                    continue
                if force:
                    out.append((shard, bucket, "force"))
                elif len(docs) >= self.flush_docs:
                    out.append((shard, bucket, "size"))
                else:
                    # deadline: fire when ANY entry has outlived its
                    # OWN class's effective deadline — equivalently,
                    # min over items of (enqueued_at + deadline(qos))
                    # has passed. A mixed bucket flushes on whichever
                    # class's oldest entry is due first, so a
                    # stretched bulk deadline can never starve an
                    # interactive doc queued behind it in the same
                    # shape bucket. (Checking every item, not just the
                    # first in dict order, also covers coalesced
                    # entries: coalescing re-inserts at the dict tail
                    # while keeping the original enqueue time.)
                    for item in docs.values():
                        d = deadlines.get(item.qos)
                        if d is None:
                            d = deadlines[item.qos] = \
                                self._deadline_for(shard, item.qos)
                        if now - item.enqueued_at >= d:
                            out.append((shard, bucket, "deadline"))
                            break
        return out

    def take(self, shard: int, bucket: int,
             limit: Optional[int] = None) -> List[PendingMerge]:
        """Dequeue up to `limit` (default `flush_docs`) docs from one
        bucket, FIFO."""
        docs = self._q[shard].get(bucket)
        if not docs:
            return []
        k = limit if limit is not None else self.flush_docs
        out = []
        cdepth = self._class_depth[shard]
        for doc_id in list(docs)[:k]:
            item = docs.pop(doc_id)
            out.append(item)
            del self._where[shard][doc_id]
            left = cdepth.get(item.qos, 1) - 1
            if left > 0:
                cdepth[item.qos] = left
            else:
                cdepth.pop(item.qos, None)
        if not docs:
            del self._q[shard][bucket]
        return out
