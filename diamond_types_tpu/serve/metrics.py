"""JSON-exportable scheduler metrics.

One plain-counter surface shared by bench.py, the soak tools and the
sync server's /metrics endpoint. Everything here is host-side Python
ints/floats — recording a sample never touches the device, so the
metrics path can run inside flush loops without perturbing timings.

Schema (snapshot()):

  {"version": 7,                   # counter-set schema; bump on change
   "uptime_s": s,                  # monotonic since construction
   "shards": N, "flush_docs": B,
   "totals": {"submits", "coalesced", "rejects", "denied", "fenced",
              "flushes", "flushed_docs", "flushed_ops", "builds",
              "evictions", "resyncs", "syncs", "host_fallbacks",
              "fused_calls", "fused_docs"},
   "batch_occupancy": mean(flush size) / flush_docs,   # 0..1
   "host_fallback_ratio": host_fallbacks / max(syncs, 1),
   "flush_reasons": {"size": n, "deadline": n, "force": n},
   "flush_size_hist": {"1": n, "2": n, ...},
   "fused": {"device_calls", "docs",          # fused bucket replays
             "occupancy",                     # docs per device call
             "occupancy_hist": {"2": n, ...}},
   "window": {"windows", "device_windows", "dispatches",
              "device_calls_per_window",      # N->1 dispatch signal
              "docs", "mesh_docs", "mesh_padded_rows",
              "mesh_occupancy",               # docs / padded rows
              "shards_hist": {"2": n, ...}},  # shards per window
   "transform": {"device_docs", "host_docs", "fallbacks", "batches",
                 "device_ratio"},             # device tail planning
   "hydration": {"prefetches", "warm_hits", "hydrations", ...},
                                    # the residency tier's counter set
                                    # (HYDRATION_KEYS; all zero until a
                                    # Hydrator is attached)
   "max_depth_seen": d,
   "queue_bound_violations": 0,     # depth observed above max_pending
   "latencies": {"flush": hist,     # obs.hist snapshot w/ p50/p90/p99
                 "hydration_cold_start": hist,   # prefetch/miss -> warm
                 "queue_wait": hist},            # admit -> flush start
   "per_shard": [{"shard", "queue_depth", "submits", "rejects",
                  "flushes", "flushed_docs", "builds", "evictions",
                  "resyncs", "host_fallbacks", "footprint_slots",
                  "flush_wall_s", "device_sync_s"}, ...]}
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

from ..obs.hist import Histogram


_SHARD_KEYS = ("submits", "coalesced", "rejects", "denied", "fenced",
               "flushes", "flushed_docs", "flushed_ops", "builds",
               "evictions", "resyncs", "syncs", "host_fallbacks",
               "fused_calls", "fused_docs", "pallas_fallbacks")

# the residency tier's counter set (serve.hydrate.Hydrator feeds these
# through record_hydration; hydrate.py imports the tuple so the two
# surfaces can never drift)
HYDRATION_KEYS = (
    "prefetches",           # async hydrations queued on first admit
    "warm_hits",            # resolve served from the warm map
    "hydrations",           # cold -> warm installs (async + sync)
    "sync_hydrations",      # resolve cold misses hydrated inline
    "attempts", "retries",  # load attempts / attempts after the first
    "timeouts",             # per-attempt HydrationTimeouts
    "load_errors",          # unexpected load exceptions (transient)
    "hydrate_gave_up",      # async ladder exhausted; doc left cold
    "quarantined",          # docs the HYDRATOR quarantined
    "quarantined_drops",    # flush-gate drops of quarantined docs
    "deferrals",            # cold docs requeued for a delayed flush
    "defer_escalations",    # 2nd gate visit: hydrated sync in-flush
    "defer_gave_up",        # defer budget exhausted -> quarantined
    "deferred_drops",       # deferral requeue hit backpressure
    "prefetch_queue_full",  # prefetch rejected, bounded queue full
    "flush_leaks",          # resolve raised INSIDE a batch (must be 0)
    "snapshot_requests",    # bank eviction hook enqueues
    "snapshots",            # successful doc-file persists
    "snapshot_queue_full",  # hook enqueue rejected
    "snapshot_errors",      # persist failed (doc stays warm)
    "evictions_to_snapshot",  # warm evictions that saved first
    "eviction_aborts",      # eviction raced a resolve; doc kept warm
    "spills_to_snapshot",   # device-tier spills: warm state persisted
                            # to the snapshot home under bank/warm-map
                            # pressure (eviction + bank-evict persists)
    "spill_bytes",          # on-disk bytes those spills wrote (home
                            # file growth, clamped at 0 per spill —
                            # compaction can shrink the home)
    "remote_fills",         # cold misses whose empty home was filled
                            # from a peer's snapshot frame (wire tier)
    "remote_fill_errors",   # remote snapshot fetch/apply failures
                            # (doc stays a legitimate fresh-empty doc)
)


class ServeMetrics:
    # bump whenever the counter set changes so bench/soak tooling can
    # detect schema drift across PRs (v2 = uptime_s + version + the
    # `denied` ownership-gate counter; v3 = `fenced`, queued work
    # skipped at flush because its admit-time lease epoch is no longer
    # the one this host holds; v4 = `latencies.flush` histogram and
    # per-shard `flush_wall_s`/`device_sync_s` device-time attribution;
    # v5 = fused-flush counters (`fused_calls`/`fused_docs`) and the
    # `fused` occupancy block — docs folded per vmapped device call;
    # v6 = the `window` block — flush-window dispatch accounting
    # (`device_calls_per_window` is the N-dispatches-to-1 signal the
    # mesh flush window exists to move) + mesh super-batch occupancy;
    # v7 = the `hydration` block (HYDRATION_KEYS — the cold->warm
    # residency tier's counters) + `latencies.hydration_cold_start`;
    # v8 = the `read` block — the follower-read tier's ReadMetrics
    # snapshot (read/metrics.py READ_KEYS + staleness/read_wait
    # histograms) when a ReadPath is attached, null otherwise;
    # v9 = `latencies.queue_wait` (admit -> flush-start wait per merged
    # item, the admission-SLO signal) + the live-telemetry double-write
    # (`ts` TimeSeries, wired by attach_obs: every counter/latency also
    # lands in the windowed ring so rate()/quantile() answer "now");
    # v10 = the `transform` block (device-resident tail planning,
    # tpu/xform.py: docs planned on device vs. the host tracker walk,
    # per-doc cross-check fallbacks, batched dispatches) + the
    # `pallas_fallbacks` shard counter (Pallas replay rung failures
    # that fell to the XLA fused rung);
    # v11 = device-tier spill accounting (`spills_to_snapshot` /
    # `spill_bytes` in the hydration block — scenario scorecards stamp
    # these; prom exports them as dt_serve_hydration_spill*_total);
    # v12 = wire-tier remote hydration (`remote_fills` /
    # `remote_fill_errors` in the hydration block — cold misses
    # hydrated from a peer's compacted snapshot frame);
    # v13 = shape-steered device-resident staging (`staged_bytes` /
    # `staged_bytes_per_window` in the window block — host->device
    # bytes the mesh windows' state staging paid; near-zero when the
    # arena / device-side gather keeps rows resident)
    SCHEMA_VERSION = 13

    def __init__(self, n_shards: int, flush_docs: int,
                 max_pending: int) -> None:
        self.n_shards = n_shards
        self.flush_docs = flush_docs
        self.max_pending = max_pending
        self.started_at = time.monotonic()
        # flush recording now happens OUTSIDE the scheduler's global
        # lock (per-shard flush locks); counters get their own lock
        self._lock = threading.Lock()
        self.shard: List[Dict[str, int]] = [
            {k: 0 for k in _SHARD_KEYS} for _ in range(n_shards)]
        self.flush_reasons: Dict[str, int] = {}
        self.flush_size_hist: Dict[int, int] = {}
        self.fused_occupancy_hist: Dict[int, int] = {}
        # flush-window dispatch accounting (scheduler-level, not
        # per-shard: a mesh window spans shards by construction)
        self.windows = 0             # pump rounds that took >= 1 bucket
        self.device_windows = 0      # windows issuing >= 1 device prog
        self.window_dispatches = 0   # device programs / worker handoffs
        self.window_docs = 0
        self.mesh_docs = 0           # docs replayed via the mesh prog
        self.mesh_padded_rows = 0    # super-batch rows incl. padding
        self.window_staged_bytes = 0  # host->device staging paid
        self.window_shards_hist: Dict[int, int] = {}
        # device-transform planning accounting (scheduler-level: the
        # batched dispatch is shared across a bucket)
        self.xform_device_docs = 0   # tails planned by the device xform
        self.xform_host_docs = 0     # tails the extractor host-planned
        self.xform_fallbacks = 0     # device cross-check -> host re-plan
        self.xform_batches = 0       # batched xform dispatches
        self.max_depth_seen = 0
        self.queue_bound_violations = 0
        self.queue_depth: List[int] = [0] * n_shards
        self.footprint_slots: List[int] = [0] * n_shards
        self.flush_latency = Histogram()
        self.queue_wait_latency = Histogram()
        # residency-tier counters: all zero until a Hydrator is
        # attached (the block is always exported so dashboards don't
        # need schema forks)
        self.hydration: Dict[str, int] = {k: 0 for k in HYDRATION_KEYS}
        self.cold_start_latency = Histogram()
        self.flush_wall_s: List[float] = [0.0] * n_shards
        self.device_sync_s: List[float] = [0.0] * n_shards
        # obs.recorder.FlightRecorder, wired by
        # MergeScheduler.attach_obs; only rare events touch it
        self.recorder = None
        # follower-read tier (read/metrics.py ReadMetrics), wired by
        # read.attach_follower_reads; the v8 `read` block is its
        # snapshot, null until a ReadPath is attached
        self.read = None
        # live-telemetry tier (obs/timeseries.py TimeSeries), wired by
        # MergeScheduler.attach_obs; None (or disabled) => every
        # double-write below is a single branch, no allocation
        self.ts = None

    # ---- recording -------------------------------------------------------

    def bump(self, shard: int, key: str, n: int = 1) -> None:
        with self._lock:
            self.shard[shard][key] += n
        if self.ts is not None:
            self.ts.inc(f"serve.{key}", n)

    def record_flush(self, shard: int, n_docs: int, n_ops: int,
                     reason: str, dur_s: float = 0.0) -> None:
        with self._lock:
            c = self.shard[shard]
            c["flushes"] += 1
            c["flushed_docs"] += n_docs
            c["flushed_ops"] += n_ops
            self.flush_reasons[reason] = \
                self.flush_reasons.get(reason, 0) + 1
            self.flush_size_hist[n_docs] = \
                self.flush_size_hist.get(n_docs, 0) + 1
        # histogram carries its own lock; record outside ours
        self.flush_latency.record(dur_s)
        if self.ts is not None:
            self.ts.observe("serve.flush", dur_s)
            self.ts.inc("serve.flushed_ops", n_ops)

    def record_fused(self, shard: int, n_docs: int) -> None:
        """One fused bucket replay: `n_docs` documents folded into a
        single vmapped device call (the occupancy histogram is the
        arithmetic-intensity signal the fused flush exists to raise)."""
        with self._lock:
            c = self.shard[shard]
            c["fused_calls"] += 1
            c["fused_docs"] += n_docs
            self.fused_occupancy_hist[n_docs] = \
                self.fused_occupancy_hist.get(n_docs, 0) + 1

    def record_window(self, dispatches: int, n_docs: int,
                      n_shards: int, mesh_docs: int = 0,
                      padded_rows: int = 0,
                      staged_bytes: int = 0) -> None:
        """One flush window: `dispatches` device programs (mesh path:
        the number of shard_map calls, 1 for a uniform-shape window) or
        per-shard worker handoffs (the PR-5 control, >= n_shards when
        several shards' buckets are due) covering `n_docs` docs across
        `n_shards` shards. `device_calls_per_window` in the snapshot is
        dispatches / windows-with-device-work — the N-to-1 dispatch
        claim, directly. `staged_bytes` is the host->device staging
        the window's mesh dispatches paid (v13)."""
        with self._lock:
            self.windows += 1
            if dispatches > 0:
                self.device_windows += 1
            self.window_dispatches += dispatches
            self.window_docs += n_docs
            self.mesh_docs += mesh_docs
            self.mesh_padded_rows += padded_rows
            self.window_staged_bytes += staged_bytes
            self.window_shards_hist[n_shards] = \
                self.window_shards_hist.get(n_shards, 0) + 1

    def record_transform(self, shard: int, device_docs: int = 0,
                         host_docs: int = 0, fallbacks: int = 0,
                         batches: int = 0) -> None:
        """One bucket's device-transform planning outcome
        (tpu/xform.plan_tails_device stats): how many tails resolved
        their merge positions on device vs. fell to the host tracker
        walk — the `device_ratio` in the snapshot is the transform
        rung's engagement signal."""
        with self._lock:
            self.xform_device_docs += device_docs
            self.xform_host_docs += host_docs
            self.xform_fallbacks += fallbacks
            self.xform_batches += batches
        if self.ts is not None and device_docs:
            self.ts.inc("serve.xform_device_docs", device_docs)

    def observe_device_time(self, shard: int, wall_s: float,
                            device_s: float) -> None:
        """Per-shard wall vs. block_until_ready device seconds for one
        doc sync (obs/devprof feeds the process-wide view; this keeps
        the attribution in the /metrics per_shard rows)."""
        with self._lock:
            self.flush_wall_s[shard] += wall_s
            self.device_sync_s[shard] += device_s

    def observe_queue(self, shard: int, depth: int) -> None:
        with self._lock:
            self.queue_depth[shard] = depth
            if depth > self.max_depth_seen:
                self.max_depth_seen = depth
            violated = depth > self.max_pending
            if violated:
                # must stay 0: the bounded-queue contract (admission
                # raises Backpressure before this point); nonzero = a
                # real bug
                self.queue_bound_violations += 1
        if violated and self.recorder is not None:
            self.recorder.record("queue_bound_violation", shard=shard,
                                 depth=depth,
                                 max_pending=self.max_pending)

    def observe_footprint(self, shard: int, slots: int) -> None:
        with self._lock:
            self.footprint_slots[shard] = int(slots)

    def record_hydration(self, event: str, n: int = 1) -> None:
        """One residency-tier event (a HYDRATION_KEYS key). Unknown
        keys are created rather than dropped — a newer Hydrator against
        an older metrics build degrades to extra counters, not lost
        ones."""
        with self._lock:
            self.hydration[event] = self.hydration.get(event, 0) + n
        if self.ts is not None:
            self.ts.inc(f"serve.hydration.{event}", n)

    def observe_cold_start(self, dur_s: float) -> None:
        """Cold-start latency: prefetch enqueue (or resolve miss) to
        warm install. The histogram has its own lock."""
        self.cold_start_latency.record(dur_s)
        if self.ts is not None:
            self.ts.observe("serve.hydration_cold_start", dur_s)

    def observe_queue_wait(self, dur_s: float) -> None:
        """Admit (or coalesce origin) -> flush-start wait for one
        queued merge — the admission-deadline SLO signal."""
        self.queue_wait_latency.record(dur_s)
        if self.ts is not None:
            self.ts.observe("serve.queue_wait", dur_s)

    # ---- export ----------------------------------------------------------

    def snapshot(self) -> dict:
        # the histograms have their own locks: snapshot them before
        # taking ours (never nest)
        flush_hist = self.flush_latency.snapshot()
        cold_hist = self.cold_start_latency.snapshot()
        queue_wait_hist = self.queue_wait_latency.snapshot()
        read_snap = self.read.snapshot() if self.read is not None else None
        with self._lock:
            totals = {k: sum(s[k] for s in self.shard)
                      for k in _SHARD_KEYS}
            flushes = max(totals["flushes"], 1)
            occupancy = (totals["flushed_docs"] / flushes) \
                / self.flush_docs
            return self._snapshot_locked(totals, occupancy, flush_hist,
                                         cold_hist, queue_wait_hist,
                                         read_snap)

    def _snapshot_locked(self, totals, occupancy, flush_hist,
                         cold_hist, queue_wait_hist, read_snap) -> dict:
        return {
            "version": self.SCHEMA_VERSION,
            "uptime_s": round(time.monotonic() - self.started_at, 3),
            "shards": self.n_shards,
            "flush_docs": self.flush_docs,
            "max_pending": self.max_pending,
            "totals": totals,
            "batch_occupancy": round(occupancy, 4),
            "host_fallback_ratio": round(
                totals["host_fallbacks"] / max(totals["syncs"], 1), 4),
            "flush_reasons": dict(self.flush_reasons),
            "flush_size_hist": {str(k): v for k, v in
                                sorted(self.flush_size_hist.items())},
            "fused": {
                "device_calls": totals["fused_calls"],
                "docs": totals["fused_docs"],
                "occupancy": round(
                    totals["fused_docs"]
                    / max(totals["fused_calls"], 1), 4),
                "occupancy_hist": {
                    str(k): v for k, v in
                    sorted(self.fused_occupancy_hist.items())},
            },
            "window": {
                "windows": self.windows,
                "device_windows": self.device_windows,
                "dispatches": self.window_dispatches,
                "device_calls_per_window": round(
                    self.window_dispatches
                    / max(self.device_windows, 1), 4),
                "docs": self.window_docs,
                "mesh_docs": self.mesh_docs,
                "mesh_padded_rows": self.mesh_padded_rows,
                "mesh_occupancy": round(
                    self.mesh_docs
                    / max(self.mesh_padded_rows, 1), 4),
                "staged_bytes": self.window_staged_bytes,
                "staged_bytes_per_window": round(
                    self.window_staged_bytes
                    / max(self.device_windows, 1), 2),
                "shards_hist": {
                    str(k): v for k, v in
                    sorted(self.window_shards_hist.items())},
            },
            "transform": {
                "device_docs": self.xform_device_docs,
                "host_docs": self.xform_host_docs,
                "fallbacks": self.xform_fallbacks,
                "batches": self.xform_batches,
                "device_ratio": round(
                    self.xform_device_docs
                    / max(self.xform_device_docs + self.xform_host_docs
                          + self.xform_fallbacks, 1), 4),
            },
            "hydration": dict(self.hydration),
            "read": read_snap,
            "max_depth_seen": self.max_depth_seen,
            "queue_bound_violations": self.queue_bound_violations,
            "latencies": {"flush": flush_hist,
                          "hydration_cold_start": cold_hist,
                          "queue_wait": queue_wait_hist},
            "per_shard": [
                {"shard": i, "queue_depth": self.queue_depth[i],
                 "footprint_slots": self.footprint_slots[i],
                 "flush_wall_s": round(self.flush_wall_s[i], 6),
                 "device_sync_s": round(self.device_sync_s[i], 6),
                 **self.shard[i]}
                for i in range(self.n_shards)],
        }
