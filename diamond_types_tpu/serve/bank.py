"""Per-shard session bank: LRU-bounded device residency + host fallback.

One bank per shard owns every device-resident `DeviceZoneSession` placed
on that shard's chip. Residency is bounded two ways, mirroring the
eviction/resync machinery the multichip dryrun proved out
(`__graft_entry__._dryrun_session_sharded`):

  * `max_sessions` — at most N documents resident at once;
  * `max_slots`    — total device-slot footprint (sum of each session's
                     `footprint_slots()`, dominated by the W_cap x
                     n_rows state matrix) stays under a VMEM-shaped
                     budget. A session that GROWS past the budget on
                     resync evicts its least-recently-used neighbors.

Eviction drops the device carry; the document itself lives in its host
OpLog, so an evicted doc costs one rebuild (resync) on its next merge —
graceful degradation, exactly like the session's internal row LRU.

Every sync is parity-recoverable: if the device path raises (worker
crash, capacity corner), the bank evicts the broken session, serves the
merge from the host engine (`oplog.checkout_tip()` — always correct)
and counts a host fallback. `engine="host"` forces that path for every
doc: the scheduler then still provides routing/batching/metrics, which
is what the HTTP server uses (first-touch JAX init against a wedged
accelerator tunnel must never hang a request handler).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, Optional

from ..obs.devprof import PROFILER
from .metrics import ServeMetrics


class _HostDoc:
    """Host-engine stand-in for a device session: the oplog IS the
    state, so sync is a no-op and text is a tracker checkout."""

    resyncs = 0

    def __init__(self, oplog) -> None:
        self.oplog = oplog
        self.synced_to = len(oplog)

    def sync(self) -> int:
        new = len(self.oplog) - self.synced_to
        self.synced_to = len(self.oplog)
        return max(new, 0)

    def text(self) -> str:
        return self.oplog.checkout_tip().snapshot()

    def footprint_slots(self) -> int:
        return 0


class SessionBank:
    def __init__(self, shard_id: int, max_sessions: int = 8,
                 max_slots: int = 1 << 24, engine: str = "device",
                 device=None, metrics: Optional[ServeMetrics] = None,
                 session_opts: Optional[dict] = None) -> None:
        if engine not in ("device", "host"):
            raise ValueError(f"unknown engine {engine!r}")
        self.shard_id = shard_id
        self.max_sessions = max(int(max_sessions), 1)
        self.max_slots = int(max_slots)
        self.engine = engine
        self.device = device
        self.metrics = metrics
        self.session_opts = dict(session_opts or {})
        self.sessions: "OrderedDict[str, object]" = OrderedDict()
        self._resyncs_seen: Dict[str, int] = {}
        # obs.recorder.FlightRecorder (MergeScheduler.attach_obs);
        # evictions and fallbacks are rare enough to record each one
        self.recorder = None

    # ---- accounting ------------------------------------------------------

    def _bump(self, key: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.bump(self.shard_id, key, n)

    def footprint_slots(self) -> int:
        return sum(s.footprint_slots() for s in self.sessions.values())

    def _evict_until_fits(self, incoming_slots: int = 0,
                          keep: Optional[str] = None) -> None:
        def over() -> bool:
            return (len(self.sessions) > self.max_sessions or
                    self.footprint_slots() + incoming_slots
                    > self.max_slots)
        while self.sessions and over():
            victim = next((k for k in self.sessions if k != keep), None)
            if victim is None:
                break      # only `keep` is resident; nothing to evict
            self.sessions.pop(victim)
            self._resyncs_seen.pop(victim, None)
            self._bump("evictions")
            if self.recorder is not None:
                self.recorder.record("session_evicted",
                                     shard=self.shard_id, doc=victim,
                                     why="capacity")

    def evict(self, doc_id: str) -> bool:
        if self.sessions.pop(doc_id, None) is not None:
            self._resyncs_seen.pop(doc_id, None)
            self._bump("evictions")
            if self.recorder is not None:
                self.recorder.record("session_evicted",
                                     shard=self.shard_id, doc=doc_id,
                                     why="explicit")
            return True
        return False

    # ---- residency -------------------------------------------------------

    def _build(self, doc_id: str, oplog):
        if self.engine == "host":
            return _HostDoc(oplog)
        from ..tpu.zone_session import DeviceZoneSession
        if self.device is not None:
            import jax
            with jax.default_device(self.device):
                sess = DeviceZoneSession(oplog, **self.session_opts)
        else:
            sess = DeviceZoneSession(oplog, **self.session_opts)
        # the initial build counts as this doc's baseline, not a resync
        self._resyncs_seen[doc_id] = getattr(sess, "resyncs", 0)
        return sess

    def session(self, doc_id: str, oplog):
        """Get-or-build the doc's resident session, updating LRU order
        and enforcing both residency bounds."""
        sess = self.sessions.get(doc_id)
        if sess is not None:
            self.sessions.move_to_end(doc_id)
            return sess
        # make room BEFORE the expensive build (the new session's exact
        # footprint is unknown until built; re-check after)
        self._evict_until_fits()
        sess = self._build(doc_id, oplog)
        self._bump("builds")
        self.sessions[doc_id] = sess
        self._evict_until_fits(keep=doc_id)
        if self.metrics is not None:
            self.metrics.observe_footprint(self.shard_id,
                                           self.footprint_slots())
        return sess

    # ---- merge path ------------------------------------------------------

    def sync_doc(self, doc_id: str, oplog) -> dict:
        """Fold the doc's appended ops into its shard-resident state.
        Never raises for device failures: falls back to the host engine
        and records the fallback."""
        self._bump("syncs")
        t0 = time.perf_counter()
        try:
            sess = self.session(doc_id, oplog)
            if self.device is not None and self.engine == "device":
                import jax
                with jax.default_device(self.device):
                    steps = sess.sync()
            else:
                steps = sess.sync()
            # wall vs device attribution: the sync above returns once
            # dispatch is queued; block_until_ready isolates the device
            # wait. Only measured when the profiler is on — forcing a
            # sync point perturbs the async dispatch pipeline.
            device_s = 0.0
            if self.engine == "device" and PROFILER.enabled:
                carry = getattr(sess, "carry", None)
                if carry is not None:
                    td = time.perf_counter()
                    try:
                        import jax
                        jax.block_until_ready(carry)
                        device_s = time.perf_counter() - td
                    except Exception:
                        device_s = 0.0
            seen = self._resyncs_seen.get(doc_id)
            now_resyncs = getattr(sess, "resyncs", 0)
            if seen is not None and now_resyncs > seen:
                self._bump("resyncs", now_resyncs - seen)
                self._resyncs_seen[doc_id] = now_resyncs
            if self.metrics is not None:
                self.metrics.observe_footprint(self.shard_id,
                                               self.footprint_slots())
                self.metrics.observe_device_time(
                    self.shard_id, time.perf_counter() - t0, device_s)
            PROFILER.observe_flush(self.shard_id,
                                   time.perf_counter() - t0, device_s)
            return {"engine": self.engine, "steps": int(steps)}
        except Exception as e:
            if self.engine == "host":
                raise       # host checkouts failing is a real bug
            self.evict(doc_id)
            self._bump("host_fallbacks")
            if self.recorder is not None:
                self.recorder.record(
                    "host_fallback", shard=self.shard_id, doc=doc_id,
                    error=f"{e.__class__.__name__}: {e}"[:120])
            return {"engine": "host", "steps": _HostDoc(oplog).sync(),
                    "error": f"{e.__class__.__name__}: {e}"[:200]}

    def text(self, doc_id: str, oplog) -> str:
        """Merged text for the doc — from the resident session when one
        exists (device parity surface), host checkout otherwise."""
        sess = self.sessions.get(doc_id)
        if sess is None:
            return oplog.checkout_tip().snapshot()
        if getattr(sess, "synced_to", 0) < len(oplog):
            self.sync_doc(doc_id, oplog)
            sess = self.sessions.get(doc_id)
            if sess is None:     # sync fell back + evicted
                return oplog.checkout_tip().snapshot()
        return sess.text()
