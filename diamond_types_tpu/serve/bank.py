"""Per-shard session bank: LRU-bounded device residency + host fallback.

One bank per shard owns every device-resident `DeviceZoneSession` placed
on that shard's chip. Residency is bounded two ways, mirroring the
eviction/resync machinery the multichip dryrun proved out
(`__graft_entry__._dryrun_session_sharded`):

  * `max_sessions` — at most N documents resident at once;
  * `max_slots`    — total device-slot footprint (sum of each session's
                     `footprint_slots()`, dominated by the W_cap x
                     n_rows state matrix) stays under a VMEM-shaped
                     budget. A session that GROWS past the budget on
                     resync evicts its least-recently-used neighbors.

Eviction drops the device carry; the document itself lives in its host
OpLog, so an evicted doc costs one rebuild (resync) on its next merge —
graceful degradation, exactly like the session's internal row LRU.

Every sync is parity-recoverable: if the device path raises (worker
crash, capacity corner), the bank evicts the broken session, serves the
merge from the host engine (`oplog.checkout_tip()` — always correct)
and counts a host fallback. `engine="host"` forces that path for every
doc: the scheduler then still provides routing/batching/metrics, which
is what the HTTP server uses (first-touch JAX init against a wedged
accelerator tunnel must never hang a request handler).

Fused flush (`fused=True`): sessions are `tpu.flush_fuse`
FusedDocSessions and `sync_docs` replays a whole taken bucket in ONE
jitted vmapped device call. The fallback ladder, most-fused first:

  1. fused group   — ≥2 resident fused sessions sharing (cap, max_ins)
                     whose tails fit: one `fused_replay` call.
  2. per-doc       — host engine, mixed residency (a non-fused session
                     already resident), capacity eviction mid-batch,
                     a tail that overflows its buffer, or a bucket
                     with <2 fusable docs: `sync_doc` per item.
  3. host fallback — a poisoned/mismatched fused length or any device
                     exception: evict the session and serve the doc
                     from `oplog.checkout_tip()` (always correct).

Locking contract for `sync_docs`: `oplog_lock` (the scheduler's
narrowed sync lock — e.g. DocStore.lock) is held only around the
HOST-side phases (session build, tail planning, fallback bookkeeping);
`device_lock` (per physical device) is held only around the device
replay, so shards on distinct chips flush genuinely concurrently. The
one remaining process-global serialization point is `_ensure_jax_ready`
below: the very first JAX backend touch process-wide is not
thread-safe, so it runs once under a module lock (documented exception
to the per-device rule).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from ..obs.devprof import PROFILER
from .metrics import ServeMetrics

# first-touch JAX init is the documented exception to per-device
# locking: backend bootstrap (platform selection, device enumeration)
# is process-global and racy, so the FIRST device touch runs exactly
# once under this module lock; every later device call relies on JAX's
# own thread safety plus the scheduler's per-device locks.
from ..analysis.witness import make_lock as _make_lock
_first_touch_lock = _make_lock("first_touch", "leaf")
_first_touch_done = False


def _ensure_jax_ready() -> None:
    global _first_touch_done
    if _first_touch_done:
        return
    with _first_touch_lock:
        if _first_touch_done:
            return
        import jax
        jax.devices()
        _first_touch_done = True


class _HostDoc:
    """Host-engine stand-in for a device session: the oplog IS the
    state, so sync is a no-op and text is a tracker checkout."""

    resyncs = 0

    def __init__(self, oplog) -> None:
        self.oplog = oplog
        self.synced_to = len(oplog)

    def sync(self) -> int:
        new = len(self.oplog) - self.synced_to
        self.synced_to = len(self.oplog)
        return max(new, 0)

    def text(self) -> str:
        return self.oplog.checkout_tip().snapshot()

    def footprint_slots(self) -> int:
        return 0


class SessionBank:
    def __init__(self, shard_id: int, max_sessions: int = 8,
                 max_slots: int = 1 << 24, engine: str = "device",
                 device=None, metrics: Optional[ServeMetrics] = None,
                 session_opts: Optional[dict] = None,
                 fused: bool = False,
                 fused_opts: Optional[dict] = None,
                 warmup: bool = False,
                 flush_docs: int = 8,
                 mesh_shards: int = 0,
                 device_plan: bool = False,
                 pallas: bool = False) -> None:
        if engine not in ("device", "host"):
            raise ValueError(f"unknown engine {engine!r}")
        self.shard_id = shard_id
        self.max_sessions = max(int(max_sessions), 1)
        self.max_slots = int(max_slots)
        self.engine = engine
        self.device = device
        self.metrics = metrics
        self.session_opts = dict(session_opts or {})
        # fused=True builds tpu.flush_fuse.FusedDocSessions so
        # sync_docs can replay whole buckets in one device call;
        # fused_opts (cap / max_ins / headroom) go to that ctor
        self.fused = bool(fused) and engine == "device"
        self.fused_opts = dict(fused_opts or {})
        self.flush_docs = int(flush_docs)
        # >0: the scheduler runs mesh flush windows over this many
        # shards — warmup then ALSO pre-compiles the mesh super-batch
        # shape classes (B padded to the mesh) so the first window
        # doesn't eat a cold compile
        self.mesh_shards = int(mesh_shards)
        # device_plan routes tail PLANNING through the device transform
        # (tpu/xform.py plan_tails_device) instead of the host tracker
        # walk; pallas routes the fused REPLAY through the Pallas step
        # kernel rung (flush_fuse.pallas_fused_replay), falling back to
        # the XLA fused rung on any failure. Both only apply on the
        # fused device engine.
        self.device_plan = bool(device_plan) and self.fused
        self.pallas = bool(pallas) and self.fused
        self.sessions: "OrderedDict[str, object]" = OrderedDict()
        self._resyncs_seen: Dict[str, int] = {}
        # obs.recorder.FlightRecorder (MergeScheduler.attach_obs);
        # evictions and fallbacks are rare enough to record each one
        self.recorder = None
        # obs.journey.OpJourney (same attach path): planned /
        # device_replayed / adopted stamps for sampled-trace items
        self.journey = None
        # residency tier (MergeScheduler.attach_hydrator): called as
        # snapshot_hook(doc_id, pending_ops) at every eviction site so
        # pending device state is persisted instead of silently
        # dropped. Enqueue-only by contract — eviction runs under
        # shard/oplog locks and must never wait on disk.
        self.snapshot_hook = None
        self._warmup_thread: Optional[threading.Thread] = None
        if warmup and self.fused:
            self._warmup_thread = threading.Thread(
                target=self._warmup, daemon=True)
            self._warmup_thread.start()

    def _warmup(self) -> None:
        """Background jit pre-compilation for the bucket shape classes
        this bank can flush (satellite: the first real flush should hit
        a warm cache, not eat a compile on the request path). Compile
        hits/misses surface through devprof's "fused" jit_cache rows."""
        try:
            _ensure_jax_ready()
            from ..tpu.flush_fuse import (DEFAULT_CAP, DEFAULT_MAX_INS,
                                          WARMUP_SHAPE_CLASSES,
                                          warmup_fused_cache)
            warmup_fused_cache(
                flush_docs=self.flush_docs,
                cap=self.fused_opts.get("cap", DEFAULT_CAP),
                max_ins=self.fused_opts.get("max_ins", DEFAULT_MAX_INS),
                mesh_shards=self.mesh_shards,
                xform_classes=(WARMUP_SHAPE_CLASSES if self.device_plan
                               else ()),
                pallas=self.pallas)
        except Exception:   # pragma: no cover - warmup must never wedge
            pass

    def join_warmup(self, timeout: float = 30.0) -> None:
        """Block until background warmup finishes (tests, benches)."""
        if self._warmup_thread is not None:
            self._warmup_thread.join(timeout=timeout)

    # ---- accounting ------------------------------------------------------

    def _bump(self, key: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.bump(self.shard_id, key, n)

    def footprint_slots(self) -> int:
        return sum(s.footprint_slots() for s in self.sessions.values())

    @staticmethod
    def _pending_ops(sess) -> int:
        """Ops the session's oplog holds beyond its synced frontier —
        what a lossy eviction WOULD have dropped (device carry ahead of
        the durable home). Both session kinds expose oplog/synced_to;
        anything else reads as 0."""
        ol = getattr(sess, "oplog", None)
        if ol is None:
            return 0
        return max(len(ol) - getattr(sess, "synced_to", 0), 0)

    def _drop(self, doc_id: str, sess, why: str) -> None:
        """Shared eviction tail: count it, route the doc through the
        snapshot path (when a residency tier is attached), and record
        the flight-recorder event WITH the pending-op count — the
        event is informational, not a data-loss marker, precisely
        because the snapshot path persists that pending state."""
        self._resyncs_seen.pop(doc_id, None)
        self._bump("evictions")
        pending = self._pending_ops(sess)
        snapshotted = False
        if self.snapshot_hook is not None:
            try:
                snapshotted = bool(self.snapshot_hook(doc_id, pending))
            except Exception:   # pragma: no cover - hook must not wedge
                pass
        if self.recorder is not None:
            self.recorder.record("session_evicted",
                                 shard=self.shard_id, doc=doc_id,
                                 why=why, pending_ops=pending,
                                 snapshotted=snapshotted)

    def _evict_until_fits(self, incoming_slots: int = 0,
                          keep: Optional[str] = None) -> None:
        def over() -> bool:
            return (len(self.sessions) > self.max_sessions or
                    self.footprint_slots() + incoming_slots
                    > self.max_slots)
        while self.sessions and over():
            victim = next((k for k in self.sessions if k != keep), None)
            if victim is None:
                break      # only `keep` is resident; nothing to evict
            sess = self.sessions.pop(victim)
            self._drop(victim, sess, why="capacity")

    def evict(self, doc_id: str) -> bool:
        sess = self.sessions.pop(doc_id, None)
        if sess is not None:
            self._drop(doc_id, sess, why="explicit")
            return True
        return False

    # ---- residency -------------------------------------------------------

    def _build(self, doc_id: str, oplog):
        if self.engine == "host":
            return _HostDoc(oplog)
        _ensure_jax_ready()
        if self.fused:
            from ..tpu.flush_fuse import FusedDocSession as cls
            opts = self.fused_opts
        else:
            from ..tpu.zone_session import DeviceZoneSession as cls
            opts = self.session_opts
        if self.device is not None:
            import jax
            with jax.default_device(self.device):
                sess = cls(oplog, **opts)
        else:
            sess = cls(oplog, **opts)
        # the initial build counts as this doc's baseline, not a resync
        self._resyncs_seen[doc_id] = getattr(sess, "resyncs", 0)
        return sess

    def session(self, doc_id: str, oplog):
        """Get-or-build the doc's resident session, updating LRU order
        and enforcing both residency bounds."""
        sess = self.sessions.get(doc_id)
        if sess is not None and getattr(sess, "oplog", None) is not None \
                and sess.oplog is not oplog:
            # residency churn: the doc was evicted from the WARM tier
            # and re-hydrated into a NEW OpLog object — a session bound
            # to the old oplog would serve a frozen view forever.
            # Rebuild against the live oplog (counted as an eviction,
            # snapshot-routed like any other).
            self.sessions.pop(doc_id)
            self._drop(doc_id, sess, why="stale-oplog")
            sess = None
        if sess is not None:
            self.sessions.move_to_end(doc_id)
            return sess
        # make room BEFORE the expensive build (the new session's exact
        # footprint is unknown until built; re-check after)
        self._evict_until_fits()
        sess = self._build(doc_id, oplog)
        self._bump("builds")
        self.sessions[doc_id] = sess
        self._evict_until_fits(keep=doc_id)
        if self.metrics is not None:
            self.metrics.observe_footprint(self.shard_id,
                                           self.footprint_slots())
        return sess

    # ---- merge path ------------------------------------------------------

    def sync_doc(self, doc_id: str, oplog) -> dict:
        """Fold the doc's appended ops into its shard-resident state.
        Never raises for device failures: falls back to the host engine
        and records the fallback."""
        self._bump("syncs")
        t0 = time.perf_counter()
        try:
            sess = self.session(doc_id, oplog)
            if self.device is not None and self.engine == "device":
                import jax
                with jax.default_device(self.device):
                    steps = sess.sync()
            else:
                steps = sess.sync()
            # wall vs device attribution: the sync above returns once
            # dispatch is queued; block_until_ready isolates the device
            # wait. Only measured when the profiler is on — forcing a
            # sync point perturbs the async dispatch pipeline.
            device_s = 0.0
            if self.engine == "device" and PROFILER.enabled:
                carry = getattr(sess, "carry", None)
                if carry is None:   # fused sessions fence on lens
                    carry = getattr(sess, "lens", None)
                if carry is not None:
                    td = time.perf_counter()
                    try:
                        import jax
                        jax.block_until_ready(carry)
                        device_s = time.perf_counter() - td
                    except Exception:
                        device_s = 0.0
            seen = self._resyncs_seen.get(doc_id)
            now_resyncs = getattr(sess, "resyncs", 0)
            if seen is not None and now_resyncs > seen:
                self._bump("resyncs", now_resyncs - seen)
                self._resyncs_seen[doc_id] = now_resyncs
            if self.metrics is not None:
                self.metrics.observe_footprint(self.shard_id,
                                               self.footprint_slots())
                self.metrics.observe_device_time(
                    self.shard_id, time.perf_counter() - t0, device_s)
            PROFILER.observe_flush(self.shard_id,
                                   time.perf_counter() - t0, device_s)
            return {"engine": self.engine, "steps": int(steps)}
        except Exception as e:
            if self.engine == "host":
                raise       # host checkouts failing is a real bug
            self.evict(doc_id)
            self._bump("host_fallbacks")
            if self.recorder is not None:
                self.recorder.record(
                    "host_fallback", shard=self.shard_id, doc=doc_id,
                    error=f"{e.__class__.__name__}: {e}"[:120])
            return {"engine": "host", "steps": _HostDoc(oplog).sync(),
                    "error": f"{e.__class__.__name__}: {e}"[:200]}

    def plan_window(self, items, resolve, oplog_lock=None,
                    min_fuse: int = 2) -> dict:
        """Plan-only entry point — the host-side half of `sync_docs`,
        with NO device call issued. The mesh flush-window coordinator
        (`scheduler._flush_window`) calls this on every shard's bucket,
        concatenates the fusable rows into one mesh super-batch, issues
        a single `shard_map` program, and hands each shard its results
        back through `adopt_window`. `min_fuse=1` because even one
        fusable doc joins the shared super-batch (the amortization
        argument that demotes lone docs on the per-shard path doesn't
        apply when the dispatch is shared).

        Returns {"items", "ols", "serial", "groups"} where `groups` is
        [(sessions, plans, doc_ids)] keyed by (cap, max_ins) class."""
        import contextlib
        olock = oplog_lock if oplog_lock is not None \
            else contextlib.nullcontext()
        # resolve first, outside every lock (non-reentrant store lock)
        ols = {it.doc_id: resolve(it.doc_id) for it in items}
        serial = list(items)
        groups: List[tuple] = []     # (sessions, plans, doc_ids)
        if self.fused and self.engine == "device":
            serial, groups = self._plan_fused(items, ols, olock,
                                              min_fuse=min_fuse)
        self._journey_stamp(items, "planned")
        return {"items": items, "ols": ols, "serial": serial,
                "groups": groups}

    def _journey_stamp(self, items, stage: str, docs=None) -> None:
        """Journey stamps for sampled-trace items; `docs` narrows to a
        doc-id subset. No-op until attach_obs wires `self.journey`."""
        j = self.journey
        if j is None or not j.enabled:
            return
        for it in items:
            tr = getattr(it, "trace", None)
            if tr is None or not tr.sampled:
                continue
            if docs is not None and it.doc_id not in docs:
                continue
            j.stamp(tr.trace_id, stage)

    def adopt_window(self, win: dict, failed: List[str],
                     oplog_lock=None, device_lock=None) -> dict:
        """Result adoption for one shard's slice of a flush window:
        bump per-doc sync counters for the fused rows (commits already
        happened at the device fence), evict `failed` docs — poisoned
        (-1) or length-drift rows whose device state is untrusted — to
        the host oracle, and run the serial fallback ladder for
        everything that couldn't fuse. Shared tail of `sync_docs` and
        the mesh window path, so the fallback ladder is one code path
        regardless of which program replayed the batch."""
        import contextlib
        olock = oplog_lock if oplog_lock is not None \
            else contextlib.nullcontext()
        dlock = device_lock if device_lock is not None \
            else contextlib.nullcontext()
        out = {"docs": len(win["items"]), "fused_calls": 0,
               "fused_docs": 0, "fallback_docs": 0}
        for _sessions, _plans, doc_ids in win["groups"]:
            for _d in doc_ids:
                self._bump("syncs")
        with olock:
            for d in failed:
                # poisoned (-1) or length-drift result: the session's
                # device state is untrusted — evict it and serve the
                # doc from the host oracle until its next rebuild
                self.evict(d)
                self._bump("host_fallbacks")
                if self.recorder is not None:
                    self.recorder.record(
                        "host_fallback", shard=self.shard_id, doc=d,
                        error="fused_poisoned_or_len_mismatch")
            for it in win["serial"]:
                with dlock:
                    # The serial fallback rung interleaves oplog reads
                    # (span walk, agent keys, host checkouts) with its
                    # device continuation inside one sess.sync(), so it
                    # cannot drop the oplog guard the way the fused
                    # phases do. It is the rare rung — unfusable,
                    # overflowing or poisoned docs — and stalling
                    # oplog readers here is the documented cost of
                    # falling off the fused path.
                    self.sync_doc(it.doc_id, win["ols"][it.doc_id])  # dt-lint: ignore[device-under-lock]
            out["fallback_docs"] = len(win["serial"]) + len(failed)
            if self.metrics is not None:
                self.metrics.observe_footprint(self.shard_id,
                                               self.footprint_slots())
        # journey: every surviving item is merged once adoption ends —
        # fused rows committed at the device fence, serial/failed rows
        # through the fallback ladder just now
        self._journey_stamp(win["items"], "adopted")
        return out

    def sync_docs(self, items, resolve,
                  oplog_lock=None, device_lock=None) -> dict:
        """Flush one taken bucket, fusing where possible (module
        docstring: the fallback ladder). `items` are admission
        PendingMerge rows; `resolve(doc_id) -> OpLog` is called OUTSIDE
        `oplog_lock` (DocStore.get takes that same non-reentrant lock).

        Lock discipline: `oplog_lock` around host-side phases (build,
        plan, fallback bookkeeping), `device_lock` around the fused
        device replay only — see the module docstring.

        Returns {"docs", "fused_calls", "fused_docs", "fallback_docs"}.
        """
        import contextlib
        dlock = device_lock if device_lock is not None \
            else contextlib.nullcontext()
        win = self.plan_window(items, resolve, oplog_lock=oplog_lock)
        fused_calls = fused_docs = 0
        # ---- device phase: one jitted call per fused group, under the
        # device lock ONLY — host threads keep mutating other oplogs
        failed: List[str] = []
        for sessions, plans, doc_ids in win["groups"]:
            from ..tpu.flush_fuse import fused_replay, pallas_fused_replay
            t0 = time.perf_counter()
            with dlock:
                if self.device is not None:
                    import jax
                    with jax.default_device(self.device):
                        ok, device_s = self._replay_group(
                            sessions, plans, fused_replay,
                            pallas_fused_replay)
                else:
                    ok, device_s = self._replay_group(
                        sessions, plans, fused_replay,
                        pallas_fused_replay)
            wall = time.perf_counter() - t0
            n = len(sessions)
            fused_calls += 1
            fused_docs += n
            if self.metrics is not None:
                self.metrics.record_fused(self.shard_id, n)
                self.metrics.observe_device_time(self.shard_id, wall,
                                                 device_s)
            PROFILER.observe_fused(self.shard_id, wall, device_s, n)
            failed.extend(d for good, d in zip(ok, doc_ids)
                          if not good)
        if fused_docs:
            fused = {d for _s, _p, ds in win["groups"] for d in ds}
            self._journey_stamp(items, "device_replayed",
                                docs=fused.difference(failed))
        # ---- host phase: per-doc fallbacks + poisoned-result cleanup
        out = self.adopt_window(win, failed, oplog_lock=oplog_lock,
                                device_lock=device_lock)
        out["fused_calls"] = fused_calls
        out["fused_docs"] = fused_docs
        return out

    def _replay_group(self, sessions, plans, fused_replay,
                      pallas_fused_replay):
        """One fused group through the replay ladder's device rungs:
        the Pallas step kernel when enabled, the XLA fused kernel as
        its fallback (and on every failure). Commit/fence semantics
        are identical, so falling through loses nothing but the
        kernel choice."""
        if self.pallas:
            try:
                return pallas_fused_replay(sessions, plans)
            except Exception:
                self._bump("pallas_fallbacks")
        return fused_replay(sessions, plans)

    def _plan_fused(self, items, ols, olock, min_fuse: int = 2):
        """Host-side phase of the fused flush: get/build each doc's
        session, plan its tail, and group fusable sessions by
        (cap, max_ins). Anything that can't fuse — non-fused residency,
        overflowing tail, LRU-evicted mid-batch, a bucket with fewer
        than `min_fuse` fusable docs — lands in the serial list.

        With `device_plan` the planning itself is split the same way
        the replay is: tail EXTRACTION (native transform + columns)
        under `olock`, the batched device order/position resolution
        OUTSIDE it (extracts are self-contained), then adoption and
        per-doc host re-planning for cross-check failures back under
        `olock` — the transform ladder's own host rung."""
        from ..tpu.flush_fuse import FusedDocSession
        serial = []
        fusable: List[tuple] = []    # (sess, plan, doc_id)
        planned = []                 # (it, sess, TailPlan | TailExtract)
        with olock:
            for it in items:
                try:
                    sess = self.session(it.doc_id, ols[it.doc_id])
                except Exception:
                    serial.append(it)   # build failure -> sync_doc's
                    continue            # own fallback ladder
                if not isinstance(sess, FusedDocSession):
                    serial.append(it)
                    continue
                if self.device_plan:
                    from ..tpu.xform import extract_tail
                    half = extract_tail(sess)   # TailExtract | TailPlan
                else:
                    half = sess.plan_tail()
                planned.append((it, sess, half))
        if self.device_plan:
            # device half OUTSIDE the oplog guard: one batched dispatch
            # resolves every extract's order + positions
            from ..tpu.xform import TailExtract, resolve_positions
            ext = [(j, h) for j, (_it, _s, h) in enumerate(planned)
                   if isinstance(h, TailExtract)]
            stats = {"device_docs": 0,
                     "host_docs": len(planned) - len(ext),
                     "fallbacks": 0, "batches": 1 if ext else 0}
            if ext:
                resolved = resolve_positions([h for _, h in ext])
                for (j, _), plan in zip(ext, resolved):
                    it, sess, _ = planned[j]
                    if plan is None:
                        stats["fallbacks"] += 1
                    else:
                        stats["device_docs"] += 1
                    planned[j] = (it, sess, plan)
            if self.metrics is not None and (ext or stats["host_docs"]):
                self.metrics.record_transform(self.shard_id, **stats)
        with olock:
            for it, sess, plan in planned:
                if plan is None:
                    # device cross-check failed: host re-plan (the
                    # per-doc host rung of the transform ladder)
                    plan = sess.plan_tail()
                if not plan.fits(sess.cap):
                    serial.append(it)   # overflow -> per-doc resync
                    continue
                # building session N can LRU-evict already-planned M:
                # only still-resident sessions may commit device state
                if self.sessions.get(it.doc_id) is not sess:
                    serial.append(it)
                elif plan.n_ops == 0:
                    # frontier advance with no visible ops (e.g. a
                    # delete of an already-deleted span): no device work
                    sess.commit_host(plan)
                    self._bump("syncs")
                else:
                    fusable.append((sess, plan, it.doc_id))
        if len(fusable) < min_fuse:
            # below min_fuse the per-doc path amortizes nothing on the
            # per-shard path (the mesh coordinator passes min_fuse=1:
            # its dispatch is shared, so lone docs still join)
            serial.extend(
                next(it for it in items if it.doc_id == d)
                for _s, _p, d in fusable)
            return serial, []
        by_shape: Dict[tuple, list] = {}
        for sess, plan, d in fusable:
            by_shape.setdefault((sess.cap, sess.max_ins), []).append(
                (sess, plan, d))
        groups = [(
            [s for s, _p, _d in grp],
            [p for _s, p, _d in grp],
            [d for _s, _p, d in grp],
        ) for grp in by_shape.values()]
        return serial, groups

    def text(self, doc_id: str, oplog, oplog_lock=None,
             device_lock=None) -> str:
        """Merged text for the doc — from the resident session when it
        is caught up with the durable oplog (device parity surface),
        host checkout otherwise. Lock discipline matches the flush
        phases: host-side reads (session table, oplog checkout) under
        `oplog_lock`; the device fetch under `device_lock` only. A read
        never issues device work while holding the oplog guard — a
        stale session serves the durable tip and the flush pipeline
        catches it up off the read path."""
        import contextlib
        olock = oplog_lock if oplog_lock is not None \
            else contextlib.nullcontext()
        dlock = device_lock if device_lock is not None \
            else contextlib.nullcontext()
        with olock:
            sess = self.sessions.get(doc_id)
            if sess is None \
                    or getattr(sess, "synced_to", 0) < len(oplog):
                return oplog.checkout_tip().snapshot()
            if self.engine == "host":
                # host sessions read the oplog itself; stay guarded
                return sess.text()
        with dlock:
            return sess.text()
