"""Workload driver for the merge scheduler (cli `serve-bench`).

Replays a trace corpus through a MergeScheduler on N simulated shards
and byte-parity-gates every document against the single-engine merge —
this is what makes the multi-chip path WORKLOAD-DRIVEN instead of
dryrun-only. Two workload shapes:

  * trace      — every doc replays the same editing trace (the
                 reference's crdt-testdata JSON format, text/trace.py),
                 linear single-agent history. All docs share padded
                 shapes, so the whole fleet shares one jit cache entry
                 per micro-tape length — the shape-bucketing payoff in
                 its purest form.
  * concurrent — per doc, two agents keep typing from their OWN heads
                 (the realtime shape device_soak drives). The
                 (agent, length) schedule is shared across docs — same
                 shapes again — while positions derive from a per-doc
                 rng, so content and merge order genuinely differ.

Parity: for engine="device" the scheduler's answer comes from the zone
kernel's device state (DeviceZoneSession.text()) while the single-engine
result is the host tracker checkout — two independent engines, compared
byte-for-byte per document. Runs on CPU (JAX_PLATFORMS=cpu + virtual
devices); a real mesh only changes placement, not the code path.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..obs import Observability
from ..obs.devprof import PROFILER
from ..text.oplog import OpLog
from ..text.trace import TestData, load_trace
from .scheduler import MergeScheduler


def synth_trace(n_txns: int = 40, ops_per_txn: int = 3,
                seed: int = 7) -> TestData:
    """Deterministic typing-shaped trace (inserts with occasional
    deletes) in the crdt-testdata format — the fallback corpus when no
    trace file is given."""
    rng = random.Random(seed)
    doc: List[str] = []
    txns: List[List[Tuple[int, int, str]]] = []
    for _ in range(n_txns):
        txn: List[Tuple[int, int, str]] = []
        for _ in range(ops_per_txn):
            if doc and rng.random() < 0.25:
                pos = rng.randrange(len(doc))
                n = min(rng.randint(1, 3), len(doc) - pos)
                txn.append((pos, n, ""))
                del doc[pos:pos + n]
            else:
                pos = rng.randint(0, len(doc))
                s = "".join(rng.choice("abcdefgh ")
                            for _ in range(rng.randint(1, 4)))
                txn.append((pos, 0, s))
                doc[pos:pos] = list(s)
        txns.append(txn)
    return TestData(start_content="", end_content="".join(doc),
                    txns=txns)


def _trace_feeders(data: TestData, doc_ids: List[str]):
    """Per-doc generators: each yield applies one txn to the doc's oplog
    (linear append, like replay_into_oplog) and reports its op count."""
    def feeder(ol: OpLog):
        agent = ol.get_or_create_agent_id("trace")
        for txn in data.txns:
            n = 0
            for (pos, num_del, ins) in txn:
                if num_del:
                    ol.add_delete_without_content(agent, pos,
                                                  pos + num_del)
                    n += 1
                if ins:
                    ol.add_insert(agent, pos, ins)
                    n += 1
            yield n
    return {d: feeder for d in doc_ids}


def _concurrent_schedule(rounds: int, edits_per_round: int,
                         seed: int) -> List[List[Tuple[int, int]]]:
    """(agent_idx, insert_len) per edit, SHARED across docs so their
    session shapes coincide (positions stay per-doc)."""
    rng = random.Random(seed)
    return [[(e % 2, rng.randint(1, 4))
             for e in range(edits_per_round)]
            for _ in range(rounds)]


def _concurrent_feeders(schedule, doc_ids: List[str], seed: int):
    def make_feeder(doc_idx: int):
        def feeder(ol: OpLog):
            rng = random.Random(seed * 7919 + doc_idx)
            agents = [ol.get_or_create_agent_id(n)
                      for n in ("ca", "cb")]
            heads: Dict[int, list] = {0: [], 1: []}
            lens = {0: 0, 1: 0}
            for round_edits in schedule:
                for (ai, n) in round_edits:
                    pos = rng.randrange(max(lens[ai], 1)) \
                        if lens[ai] else 0
                    ch = chr(ord("a") + (doc_idx % 26))
                    heads[ai] = [ol.add_insert_at(
                        agents[ai], heads[ai], pos, ch * n)]
                    lens[ai] += n
                yield len(round_edits)
        return feeder
    return {d: make_feeder(i) for i, d in enumerate(doc_ids)}


def _flash_feeders(doc_ids: List[str], rounds: int, seed: int):
    """Flash-crowd tape: a migrating hot doc takes op BURSTS while the
    cold tail trickles, so each window's max-op count — and with it
    the pow2 `n` jit shape class — thrashes from round to round. This
    is the shape-steering stress tape: unsteered, nearly every window
    lands on a cold `(b, n)` class; steered, windows pad onto the
    warmed classes and the jit caches stay hot."""
    ndocs = len(doc_ids)

    def make_feeder(doc_idx: int):
        def feeder(ol: OpLog):
            agent = ol.get_or_create_agent_id("flash")
            rng = random.Random(seed * 104729 + doc_idx)
            ln = 0
            for r in range(rounds):
                hot = (r // 2) % max(ndocs, 1)
                if doc_idx == hot:
                    burst = 6 + rng.randrange(10)
                elif (doc_idx + r) % 7 == 0:
                    burst = 3 + rng.randrange(4)
                else:
                    burst = 1 + rng.randrange(2)
                n = 0
                for _ in range(burst):
                    pos = rng.randint(0, ln)
                    s = "".join(rng.choice("abcdefgh ")
                                for _ in range(rng.randint(1, 3)))
                    ol.add_insert(agent, pos, s)
                    ln += len(s)
                    n += 1
                yield n
        return feeder
    return {d: make_feeder(i) for i, d in enumerate(doc_ids)}


def run_serve_bench(shards: int = 4, docs: int = 8,
                    txns: Optional[int] = None, engine: str = "device",
                    mode: str = "trace", corpus: Optional[str] = None,
                    flush_docs: int = 4, flush_deadline_s: float = 0.02,
                    max_pending: int = 64, max_sessions: int = 4,
                    seed: int = 7, place_on_devices: bool = True,
                    session_opts: Optional[dict] = None,
                    obs_sample_rate: float = 0.01,
                    fused: bool = True, flush_workers: bool = True,
                    warmup: bool = False,
                    steady_rounds: int = 0,
                    mesh_window: bool = False,
                    telemetry: bool = True,
                    journey: bool = True,
                    device_plan: bool = False,
                    pallas: bool = False,
                    steer: bool = True,
                    device_stage: bool = True) -> dict:
    """Replay the workload through a fresh scheduler; returns a JSON-able
    report with throughput, the metrics snapshot, the parity gate, and
    the device-profiler snapshot (wall vs. device time per flush, jit
    cache hit/miss — obs/devprof). The bench runs with the production
    observability defaults (1% trace sampling) so its throughput
    numbers ARE the instrumented numbers. `mesh_window=True` routes
    flushes through the scheduler's mesh flush-window coordinator (one
    shard_map dispatch per window instead of one device call per
    shard) — the report's `device_calls_per_window` is the direct
    A/B signal against the per-shard default. `device_plan=True` plans
    flush tails through the device transform (tpu/xform.py) instead of
    the host tracker walk — the report's `transform` block counts how
    many tails actually resolved on device — and `pallas=True` adds the
    Pallas replay rung at the top of the flush ladder.

    `steer=False` / `device_stage=False` are the PR-20 A/B control
    arms: no batch-shape steering (every window dispatches its raw
    pow2 shape class) and host-numpy mesh staging (every resident byte
    round-trips per window). `mode="flash"` replays the flash-crowd
    tape whose per-window op counts thrash the jit shape classes — the
    steering stress shape; with `steady_rounds` the report's
    `steady_jit_hit_rate` measures the post-warm phase alone."""
    doc_ids = [f"doc{i:03d}" for i in range(docs)]
    ols: Dict[str, OpLog] = {}
    for d in doc_ids:
        ol = OpLog()
        ol.doc_id = d
        ols[d] = ol

    if mode == "trace":
        data = load_trace(corpus) if corpus else \
            synth_trace(n_txns=txns or 40, seed=seed)
        if txns:
            data = TestData(start_content=data.start_content,
                            end_content=data.end_content,
                            txns=data.txns[:txns])
        feeders = {d: f(ols[d])
                   for d, f in _trace_feeders(data, doc_ids).items()}
        n_rounds = len(data.txns)
    elif mode == "concurrent":
        n_rounds = txns or 24
        schedule = _concurrent_schedule(n_rounds, 2, seed)
        feeders = {d: f(ols[d]) for d, f in
                   _concurrent_feeders(schedule, doc_ids, seed).items()}
    elif mode == "flash":
        n_rounds = txns or 24
        feeders = {d: f(ols[d]) for d, f in
                   _flash_feeders(doc_ids, n_rounds, seed).items()}
    else:
        raise ValueError(f"unknown mode {mode!r}")

    # enable the profiler BEFORE scheduler construction so background
    # warmup compiles land in the "fused" jit_cache rows
    PROFILER.reset()
    PROFILER.enabled = True
    # steering + staging arms: process-global switches, fresh state per
    # bench run so A/B subprocesses and in-process repeats start equal
    from ..parallel.arena import DEVICE_STAGE, reset_arenas
    from ..tpu.steer import STEER
    STEER.reset(table=True)
    STEER.enabled = steer
    DEVICE_STAGE.enabled = device_stage
    reset_arenas()
    # with flush workers on, worker threads READ oplogs (tail planning)
    # while this loop APPENDS to them — the oplog lock makes that safe,
    # exactly the way the sync server passes DocStore.lock
    oplog_lock = threading.Lock()
    sched = MergeScheduler(
        shards, resolve=ols.__getitem__, engine=engine,
        max_sessions_per_shard=max_sessions,
        max_pending=max_pending, flush_docs=flush_docs,
        flush_deadline_s=flush_deadline_s,
        place_on_devices=place_on_devices, session_opts=session_opts,
        sync_lock=oplog_lock, fused=fused,
        flush_workers=flush_workers, warmup=warmup,
        mesh_window=mesh_window, device_plan=device_plan,
        pallas=pallas)
    obs = Observability(sample_rate=obs_sample_rate, seed=seed,
                        telemetry=telemetry, journey=journey)
    sched.attach_obs(obs)
    if warmup:
        # the bench should measure warm-cache flushes, not count the
        # background compile into the first flush window
        sched.banks[0].join_warmup()

    t0 = time.perf_counter()
    total_ops = 0
    retries = 0
    live = dict(feeders)
    while live:
        done = []
        for d, gen in live.items():
            try:
                with oplog_lock:
                    n = next(gen)
            except StopIteration:
                done.append(d)
                continue
            total_ops += n
            r = sched.submit(d, n_ops=n)
            attempts = 0
            while not r["accepted"]:
                # reject-with-retry-after: flush due work and retry; a
                # couple of polite retries, then force a flush so the
                # feed loop always terminates
                retries += 1
                attempts += 1
                sched.pump(force=attempts > 2)
                r = sched.submit(d, n_ops=n)
        for d in done:
            del live[d]
        sched.pump()
    sched.drain()

    # steady-state phase (lockstep): the continuous feed above runs
    # orders of magnitude faster than the flush cadence, so workers
    # mostly see a backlog whose ops an earlier tip-sync already
    # consumed — realistic for a burst, but it never measures the
    # fused path's steady-state shape. Here every doc is RESIDENT:
    # each round appends one more txn per doc and drains, so each
    # flush carries its whole bucket with fresh tails — the docs-per-
    # device-call occupancy the fused flush exists to raise.
    jit_steady0 = PROFILER.snapshot()["jit_cache"]
    if steady_rounds:
        if mode == "trace":
            sdata = synth_trace(n_txns=steady_rounds, seed=seed + 1)
            sfeeders = {d: f(ols[d]) for d, f in
                        _trace_feeders(sdata, doc_ids).items()}
        elif mode == "flash":
            sfeeders = {d: f(ols[d]) for d, f in _flash_feeders(
                doc_ids, steady_rounds, seed + 1).items()}
        else:
            ssched = _concurrent_schedule(steady_rounds, 2, seed + 1)
            sfeeders = {d: f(ols[d]) for d, f in _concurrent_feeders(
                ssched, doc_ids, seed + 1).items()}
        for _ in range(steady_rounds):
            for d, gen in sfeeders.items():
                try:
                    with oplog_lock:
                        n = next(gen)
                except StopIteration:
                    continue
                total_ops += n
                r = sched.submit(d, n_ops=n)
                while not r["accepted"]:
                    retries += 1
                    sched.pump(force=True)
                    r = sched.submit(d, n_ops=n)
            sched.drain()
    feed_wall = time.perf_counter() - t0
    sched.stop_workers()

    mismatches = []
    for d in doc_ids:
        want = ols[d].checkout_tip().snapshot()
        got = sched.text(d)
        if got != want:
            mismatches.append(d)
    wall = time.perf_counter() - t0

    m = sched.metrics_json()
    # evaluate SLO burn rates over the run's telemetry before building
    # the verdict: a bench that passes parity but burned its latency
    # budget should fail loudly, not average the burn away
    obs.slo.evaluate()
    slo_verdict = obs.slo.verdict()

    # jit hit rates over the REPLAY caches (fused/mesh/pallas — the
    # classes steering snaps); steady rate from the post-burst deltas,
    # the ">= 90% steady-state hits" number ISSUE 20 gates on
    devprof = PROFILER.snapshot()
    _replay = ("fused", "mesh", "pallas")

    def _rate(now, base):
        hits = lookups = 0
        for c in _replay:
            h1 = now.get(c, {}).get("hits", 0)
            m1 = now.get(c, {}).get("misses", 0)
            h0 = base.get(c, {}).get("hits", 0) if base else 0
            m0 = base.get(c, {}).get("misses", 0) if base else 0
            hits += h1 - h0
            lookups += (h1 + m1) - (h0 + m0)
        return (round(hits / lookups, 4) if lookups else None), lookups

    jit_hit_rate, _ = _rate(devprof["jit_cache"], None)
    steady_jit_hit_rate, steady_lookups = _rate(devprof["jit_cache"],
                                                jit_steady0)
    staged_per_window = m["window"]["staged_bytes_per_window"]
    report = {
        "config": {"shards": shards, "docs": docs, "engine": engine,
                   "mode": mode, "corpus": corpus,
                   "rounds": n_rounds, "flush_docs": flush_docs,
                   "flush_deadline_s": flush_deadline_s,
                   "max_pending": max_pending,
                   "max_sessions": max_sessions, "seed": seed,
                   "fused": sched.fused,
                   "flush_workers": flush_workers, "warmup": warmup,
                   "steady_rounds": steady_rounds,
                   "mesh_window": sched.mesh_window,
                   "device_plan": sched.device_plan,
                   "pallas": sched.pallas,
                   "steer": steer, "device_stage": device_stage,
                   "telemetry": telemetry, "journey": journey},
        "total_ops": total_ops,
        "submit_retries": retries,
        "feed_wall_s": round(feed_wall, 3),
        "wall_s": round(wall, 3),
        "ops_per_sec": round(total_ops / max(feed_wall, 1e-9)),
        "parity_ok": not mismatches,
        "parity_mismatches": mismatches,
        "slo": slo_verdict,
        "slo_ok": slo_verdict["slo_ok"],
        "fused_device_calls": m["fused"]["device_calls"],
        "fused_occupancy": m["fused"]["occupancy"],
        # the N-dispatches-to-1 signal: device programs per flush
        # window (mesh mode targets 1.0; the per-shard control pays one
        # per due bucket)
        "device_calls_per_window":
            m["window"]["device_calls_per_window"],
        # shape steering + device-resident staging (PR 20): replay-
        # cache hit rates (overall and steady-phase), host->device
        # staging per mesh window, and the steer policy's own counters
        "jit_hit_rate": jit_hit_rate,
        "steady_jit_hit_rate": steady_jit_hit_rate,
        "steady_jit_lookups": steady_lookups,
        "staged_bytes_per_window": staged_per_window,
        "steer": STEER.snapshot(),
        # the transform rung's engagement: tails whose merge positions
        # resolved on device vs. the host tracker walk
        "transform": m["transform"],
        "metrics": m,
        "devprof": devprof,
        "obs": {"trace": obs.tracer.stats(),
                "ts_recorded": obs.ts.recorded,
                "journey": obs.journey.snapshot()},
    }
    # a banded scorecard so serve-bench A/B arms gate through the SAME
    # engine as scenario runs (`diff_scorecards` / scorecard-diff)
    from ..obs.scorecard import build_scorecard
    steady_or_overall = steady_jit_hit_rate if steady_jit_hit_rate \
        is not None else jit_hit_rate
    report["scorecard"] = build_scorecard(
        scenario={"name": f"serve-bench-{mode}", "seed": seed,
                  "steer": steer, "device_stage": device_stage},
        wall_s=wall, virtual_s=0.0,
        totals={"ops": total_ops, "writes": total_ops, "reads": 0,
                "errors": len(mismatches)},
        latency_p99_s={"flush": m["latencies"]["flush"]["p99"]},
        slo={"slo_ok": slo_verdict["slo_ok"],
             "burning": slo_verdict["burning"],
             "warning": slo_verdict["warning"]},
        ok=bool(not mismatches and slo_verdict["slo_ok"]),
        serve={
            "jit_cache_hit_rate": steady_or_overall,
            "staged_bytes_per_window": staged_per_window,
            "device_calls_per_window":
                m["window"]["device_calls_per_window"],
            "steer_compiles": report["steer"]["compiles"],
        },
    )
    PROFILER.enabled = False
    if mismatches:
        # a parity failure report should be diagnosable standalone:
        # attach the flight-recorder tail (evictions, fallbacks,
        # fencing — the usual suspects for a stale device text)
        report["events_tail"] = obs.recorder.tail(50)
    return report
