"""Deadline-aware hydration: the cold -> warm pump of the residency tier.

The `Hydrator` sits between the `TieredStore` (cold: durable per-doc
homes on disk) and the scheduler's resolve path (warm: host OpLogs the
session banks build device state from). Three jobs:

  * **prefetch on first admit** — `MergeScheduler.submit` calls
    `prefetch(doc_id, budget_s=flush_deadline)` the first time a doc is
    routed; worker threads hydrate it off the request path with a
    per-attempt timeout, jittered retry/backoff (`replicate.peers.
    Backoff`) and a deadline budget derived from the bucket's flush
    deadline, so the doc is usually warm before its bucket is due;
  * **resolve** — the scheduler's `resolve(doc_id) -> OpLog`: warm hit
    returns the resident oplog; a cold miss hydrates synchronously
    (bounded by `sync_wait_s`); a quarantined doc raises the typed
    `DocQuarantined` instead of serving garbage;
  * **flush gating + eviction-to-snapshot** — `flush_gate` classifies a
    taken bucket right after the lease fence: warm docs flush now,
    quarantined docs drop (never poisoning the batch), still-cold docs
    DEFER (requeued by the scheduler — a delayed flush, never a
    stalled one). Warm-map pressure and `SessionBank` evictions route
    through `evict_to_snapshot` / `request_snapshot`, so eviction
    persists pending state instead of dropping it.

Failure containment is per-doc by construction: every quarantine,
timeout and defer names exactly one doc; the rest of its bucket
flushes on time.

Locking: `hydrate.warm` (io rung) guards the warm map / defer table /
eviction marks and is NEVER held across disk IO or sleeps — loads and
saves run lock-free and re-validate on completion (an install never
overwrites a warm oplog that arrived first; an eviction aborts when a
resolve claimed the doc mid-save). The tier's own io-rung locks nest
inside (same class, unranked — no witness edge), and the oplog guard
nests inside those (the documented io -> oplog order).
"""

from __future__ import annotations

import contextlib
import os
import queue as _queue
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

from ..analysis.witness import make_lock
from ..obs.hist import Histogram
from ..replicate.peers import Backoff
from ..storage.tier import DocQuarantined, HydrationTimeout, TieredStore
from .metrics import HYDRATION_KEYS


class Hydrator:
    def __init__(self, store: TieredStore, workers: int = 2,
                 queue_max: int = 256, warm_max: int = 1024,
                 attempt_timeout_s: float = 0.25,
                 max_attempts: int = 4,
                 backoff: Optional[Backoff] = None,
                 sync_wait_s: float = 5.0,
                 defer_budget_s: float = 10.0,
                 gate_wait_s: float = 0.005,
                 evict_grace_s: float = 0.05,
                 oplog_lock=None, metrics=None, recorder=None,
                 seed: int = 0) -> None:
        self.store = store
        self.warm_max = max(int(warm_max), 1)
        self.attempt_timeout_s = float(attempt_timeout_s)
        self.max_attempts = max(int(max_attempts), 1)
        self.sync_wait_s = float(sync_wait_s)
        # a deferred doc that never turns warm OR quarantined within
        # this budget is stuck (e.g. its prefetch queue overflowed
        # forever) — quarantine it so drain() stays bounded
        self.defer_budget_s = float(defer_budget_s)
        # how long flush_gate waits for an in-flight hydration before
        # deferring — bounds the requeue spin during force-drains
        self.gate_wait_s = float(gate_wait_s)
        # a doc resolved within this window is never PICKED as an
        # eviction victim: the caller is still between resolve() and
        # its append, the one gap the unsaved-suffix recheck in
        # evict_to_snapshot cannot see (warm_max is soft under a fully
        # hot working set as a result)
        self.evict_grace_s = float(evict_grace_s)
        self.oplog_lock = oplog_lock
        self.metrics = metrics      # ServeMetrics (attach_hydrator)
        self.recorder = recorder    # obs FlightRecorder, may be None
        self.attrib = None          # obs HotAttribution (attach_obs):
                                    # per-doc cache-miss attribution
        # elastic mesh: called as on_warm(doc_id, ol) after a hydration
        # installs (read.attach_follower_reads wires the checkout-cache
        # pre-materializer here). Invoked with NO hydrator locks held.
        self.on_warm = None
        # wire tier: remote_fetch(doc_id) -> snapshot frame bytes (or
        # None). Wired by attach_replication; a cold miss whose durable
        # home is empty pulls the owner's compacted snapshot instead of
        # serving a spuriously-fresh doc. Called lock-free.
        self.remote_fetch = None
        self.backoff = backoff if backoff is not None else Backoff(
            base_s=0.002, cap_s=0.05, seed=seed, key="hydrate")
        self._hydrate_lock = make_lock("hydrate.warm", "io")
        self._warm: "OrderedDict[str, object]" = OrderedDict()
        self._pending: Dict[str, float] = {}    # doc -> enqueue ts
        self._evicting: Set[str] = set()
        self._touched: Dict[str, float] = {}    # doc -> last resolve ts
        self._defers: Dict[str, Tuple[int, float]] = {}
        self.counters = {k: 0 for k in HYDRATION_KEYS}
        self._counter_lock = threading.Lock()
        self.cold_start = Histogram()
        # plain condvar used ONLY as a wakeup signal (never guards
        # state) — flush_gate waits on it instead of spinning
        self._warm_cv = threading.Condition(threading.Lock())
        self._q: "_queue.Queue" = _queue.Queue(maxsize=max(queue_max, 1))
        self._snap_q: "_queue.Queue" = _queue.Queue(
            maxsize=max(queue_max, 1))
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        for i in range(max(int(workers), 1)):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"hydrate-worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._snapshot_loop,
                             name="hydrate-snapshot", daemon=True)
        t.start()
        self._threads.append(t)

    # ---- accounting ------------------------------------------------------

    def _bump(self, key: str, n: int = 1) -> None:
        with self._counter_lock:
            self.counters[key] += n
        if self.metrics is not None:
            self.metrics.record_hydration(key, n)

    def _observe_cold_start(self, dur_s: float) -> None:
        self.cold_start.record(dur_s)
        if self.metrics is not None:
            self.metrics.observe_cold_start(dur_s)

    def _record(self, event: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.record(event, **fields)

    def status(self, doc_id: str) -> str:
        """"warm" | "quarantined" | "pending" | "cold"."""
        with self._hydrate_lock:
            if doc_id in self._warm:
                return "warm"
        if self.store.is_quarantined(doc_id) is not None:
            return "quarantined"
        with self._hydrate_lock:
            if doc_id in self._pending:
                return "pending"
        return "cold"

    def warm_count(self) -> int:
        with self._hydrate_lock:
            return len(self._warm)

    # ---- prefetch (async cold -> warm) -----------------------------------

    def prefetch(self, doc_id: str,
                 budget_s: Optional[float] = None) -> bool:
        """Queue an async hydration. `budget_s` is the caller's
        deadline hint (the scheduler passes its bucket flush deadline);
        it is floored so at least one full retry ladder fits — a tight
        flush deadline degrades to a DELAYED flush via the defer path,
        never to a doc spuriously timed out before its first attempt."""
        floor = self.attempt_timeout_s * self.max_attempts
        budget = max(budget_s if budget_s is not None
                     else self.sync_wait_s, floor)
        with self._hydrate_lock:
            if doc_id in self._warm or doc_id in self._pending:
                return False
            self._pending[doc_id] = time.monotonic()
        if self.store.is_quarantined(doc_id) is not None:
            with self._hydrate_lock:
                self._pending.pop(doc_id, None)
            return False
        try:
            self._q.put_nowait((doc_id, time.monotonic() + budget))
        except _queue.Full:
            self._bump("prefetch_queue_full")
            with self._hydrate_lock:
                self._pending.pop(doc_id, None)
            return False
        self._bump("prefetches")
        return True

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            try:
                doc_id, deadline = self._q.get(timeout=0.05)
            except _queue.Empty:
                continue
            try:
                self._hydrate(doc_id, deadline)
            except Exception:   # pragma: no cover - keep workers alive
                with self._hydrate_lock:
                    self._pending.pop(doc_id, None)

    def _hydrate(self, doc_id: str, deadline: float) -> None:
        t0 = time.monotonic()
        try:
            ol = self._load_with_retries(doc_id, deadline)
        except DocQuarantined:
            self._note_quarantined(doc_id)
            return
        if ol is None:
            # deadline/attempts exhausted without a permanent verdict:
            # leave the doc COLD — the flush gate re-prefetches on the
            # next defer (fresh budget), and only the defer budget or a
            # sync resolve turns persistent failure into a quarantine
            self._bump("hydrate_gave_up")
            with self._hydrate_lock:
                self._pending.pop(doc_id, None)
            return
        self._finish(doc_id, self._maybe_remote_fill(doc_id, ol), t0)

    def _load_with_retries(self, doc_id: str, deadline: float):
        """One bounded retry ladder. Returns the hydrated OpLog, None
        when the deadline/attempt budget ran out on transient errors,
        raises DocQuarantined on a permanent per-doc verdict."""
        attempt = 0
        while attempt < self.max_attempts:
            left = deadline - time.monotonic()
            if left <= 0:
                return None
            self._bump("attempts")
            if attempt:
                self._bump("retries")
            try:
                return self.store.load(
                    doc_id, timeout_s=min(self.attempt_timeout_s, left))
            except HydrationTimeout:
                self._bump("timeouts")
            except DocQuarantined:
                raise
            except Exception as e:
                self._bump("load_errors")
                self._record("hydration_load_error", doc=doc_id,
                             error=f"{e.__class__.__name__}: {e}"[:120])
            attempt += 1
            if attempt < self.max_attempts:
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                time.sleep(min(self.backoff.delay(attempt - 1), left))
        return None

    def _maybe_remote_fill(self, doc_id: str, ol):
        """A hydration that came back EMPTY may be a doc this host has
        simply never seen: ask the mesh (wire tier snapshot fetch)
        before installing a fresh oplog. Best-effort — any failure
        keeps the legitimate fresh-empty semantics."""
        fetch = self.remote_fetch
        if fetch is None or ol is None or len(ol) > 0:
            return ol
        try:
            frame = fetch(doc_id)
            if frame:
                from ..wire.snapshot import apply_snapshot
                if apply_snapshot(ol, frame):
                    self._bump("remote_fills")
        except Exception:
            self._bump("remote_fill_errors")
        return ol

    def _note_quarantined(self, doc_id: str) -> None:
        with self._hydrate_lock:
            self._pending.pop(doc_id, None)
            self._warm.pop(doc_id, None)
            self._defers.pop(doc_id, None)
            self._touched.pop(doc_id, None)
            self._evicting.discard(doc_id)
        self._record("doc_quarantined", doc=doc_id,
                     reason=self.store.is_quarantined(doc_id) or "?")
        with self._warm_cv:
            self._warm_cv.notify_all()

    def _finish(self, doc_id: str, ol, t0: float):
        """Install a hydration result. NEVER overwrites an oplog that
        is already warm — a concurrent sync resolve may have installed
        (and begun appending to) its own copy; the first install wins
        and this one is discarded. Returns the canonical warm oplog."""
        victims: List[str] = []
        with self._hydrate_lock:
            self._pending.pop(doc_id, None)
            # _defers is NOT cleared here: under thrash a doc can
            # hydrate and be evicted again between two gate visits,
            # and a reset visit count would keep it deferring forever
            # — only passing a gate (or quarantine) clears the entry
            self._evicting.discard(doc_id)
            self._touched[doc_id] = time.monotonic()
            have = self._warm.get(doc_id)
            if have is not None:
                self._warm.move_to_end(doc_id)
                ol = have
            else:
                self._warm[doc_id] = ol
                victims = self._pick_victims_locked(exclude=doc_id)
        self._bump("hydrations")
        self._observe_cold_start(time.monotonic() - t0)
        with self._warm_cv:
            self._warm_cv.notify_all()
        self._evict_victims(victims)
        cb = self.on_warm
        if cb is not None:
            try:
                cb(doc_id, ol)
            except Exception:   # pragma: no cover - warm is best-effort
                pass
        return ol

    # ---- resolve (the scheduler's document authority) --------------------

    def resolve(self, doc_id: str):
        """`MergeScheduler(resolve=...)` entry point. Warm hit returns
        the resident oplog (and aborts any in-flight eviction of it);
        cold miss hydrates synchronously; quarantined raises the typed
        DocQuarantined."""
        reason = self.store.is_quarantined(doc_id)
        if reason is not None:
            raise DocQuarantined(doc_id, reason)
        with self._hydrate_lock:
            ol = self._warm.get(doc_id)
            if ol is not None:
                self._warm.move_to_end(doc_id)
                self._touched[doc_id] = time.monotonic()
                # claim it back from a mid-save eviction: the saver
                # sees the mark gone and keeps the entry resident
                self._evicting.discard(doc_id)
        if ol is not None:
            self._bump("warm_hits")
            return ol
        self._bump("sync_hydrations")
        # a sync hydration is the residency tier's cache miss — the
        # per-doc hot sketch is how "one doc thrashes the warm set"
        # shows up at /debug/hot
        if self.attrib is not None:
            self.attrib.note("cache_misses", doc=doc_id)
        t0 = time.monotonic()
        try:
            ol = self._load_with_retries(doc_id, t0 + self.sync_wait_s)
        except DocQuarantined:
            self._note_quarantined(doc_id)
            raise
        if ol is None:
            self.store.quarantine(doc_id, "hydration_timeout")
            self._bump("quarantined")
            self._note_quarantined(doc_id)
            raise DocQuarantined(doc_id, "hydration_timeout")
        return self._finish(doc_id, self._maybe_remote_fill(doc_id, ol),
                            t0)

    def wait_warm(self, doc_id: str, timeout_s: float) -> bool:
        """Wait (briefly) for an in-flight hydration to land. True when
        the doc is warm; False on timeout or a quarantine verdict."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._hydrate_lock:
                if doc_id in self._warm:
                    return True
            if self.store.is_quarantined(doc_id) is not None:
                return False
            left = deadline - time.monotonic()
            if left <= 0:
                return False
            with self._warm_cv:
                self._warm_cv.wait(timeout=min(left, 0.01))

    # ---- flush gating ----------------------------------------------------

    def flush_gate(self, shard: int, items) -> tuple:
        """Classify one taken bucket right after the lease fence:
        returns (keep, defer, dropped). Warm docs flush now; a
        quarantined doc is dropped HERE, before its rows can join a
        batch; a cold doc defers (the scheduler requeues it — a
        delayed flush) with a fresh prefetch, until the defer budget
        turns a stuck doc into a quarantine."""
        keep, defer, dropped = [], [], []
        now = time.monotonic()
        for it in items:
            d = it.doc_id
            if self.store.is_quarantined(d) is not None:
                dropped.append(it)
                self._bump("quarantined_drops")
                self._record("quarantined_drop", doc=d, shard=shard)
                continue
            if self.wait_warm(d, self.gate_wait_s):
                with self._hydrate_lock:
                    if d in self._warm:
                        self._warm.move_to_end(d)
                        self._touched[d] = now
                        self._evicting.discard(d)
                    self._defers.pop(d, None)
                keep.append(it)
                continue
            if self.store.is_quarantined(d) is not None:
                dropped.append(it)
                self._bump("quarantined_drops")
                self._record("quarantined_drop", doc=d, shard=shard)
                continue
            with self._hydrate_lock:
                n, first = self._defers.get(d, (0, now))
                self._defers[d] = (n + 1, first)
            if now - first > self.defer_budget_s:
                self.store.quarantine(d, "hydration_stuck")
                self._bump("defer_gave_up")
                self._bump("quarantined")
                self._note_quarantined(d)
                dropped.append(it)
                continue
            if n >= 1:
                # second visit: the async path had its round and the
                # doc is STILL cold at gate time. Deferring again can
                # livelock — when the queued working set outnumbers
                # warm_max, every deferred doc's re-prefetch evicts
                # the docs the gate is about to check. Hydrate HERE
                # instead, bounded by sync_wait_s: an undersized warm
                # tier degrades to a delayed flush, never a spinning
                # drain. (The visit count survives hydrate/evict
                # thrash between visits — it clears only on a gate
                # pass or quarantine — so the escalation is certain.)
                try:
                    self.resolve(d)
                except DocQuarantined:
                    dropped.append(it)
                    self._bump("quarantined_drops")
                    self._record("quarantined_drop", doc=d, shard=shard)
                    continue
                with self._hydrate_lock:
                    self._defers.pop(d, None)
                self._bump("defer_escalations")
                keep.append(it)
                continue
            self._bump("deferrals")
            defer.append(it)
            self.prefetch(d)
        return keep, defer, dropped

    def note_flush_leak(self, doc_id: str, exc: BaseException) -> None:
        """A resolve inside a flush batch raised — the gate should have
        filtered this doc. Counted so the soak can assert it stays 0."""
        self._bump("flush_leaks")
        self._record("flush_leak", doc=doc_id,
                     error=f"{exc.__class__.__name__}: {exc}"[:120])

    # ---- eviction-to-snapshot --------------------------------------------

    def _pick_victims_locked(self,
                             exclude: Optional[str] = None) -> List[str]:
        """Mark LRU victims while over `warm_max` (caller holds
        `_lock`). Marked docs stay resident until their snapshot
        lands — `_evict_victims` finishes the job lock-free."""
        victims: List[str] = []
        floor = time.monotonic() - self.evict_grace_s
        while len(self._warm) - len(victims) > self.warm_max:
            v = next((k for k in self._warm
                      if k != exclude and k not in self._evicting
                      and self._touched.get(k, 0.0) <= floor), None)
            if v is None:
                break
            self._evicting.add(v)
            victims.append(v)
        return victims

    def _evict_victims(self, victims: List[str]) -> None:
        for v in victims:
            self.evict_to_snapshot(v, why="pressure")

    def evict_to_snapshot(self, doc_id: str,
                          why: str = "explicit") -> bool:
        """Persist the doc's warm oplog to its durable home, then drop
        it from the warm map. Aborts (keeps the doc warm) when a
        resolve claimed it mid-save, when an append raced in AFTER the
        snapshot was encoded (the persisted op count no longer matches
        the live oplog), or when the save failed transiently —
        eviction must NEVER drop unsaved state."""
        with self._hydrate_lock:
            ol = self._warm.get(doc_id)
            if ol is None:
                self._evicting.discard(doc_id)
                return False
            self._evicting.add(doc_id)
        saved = quarantined = False
        saved_len = -1
        size_before = self._home_size(doc_id)
        try:
            saved_len = self.store.save(doc_id, ol,
                                        oplog_lock=self.oplog_lock)
            saved = True
        except DocQuarantined:
            quarantined = True      # nothing durable to protect now
        except Exception as e:
            self._bump("snapshot_errors")
            self._record("snapshot_error", doc=doc_id, why=why,
                         error=f"{e.__class__.__name__}: {e}"[:120])
        if saved:
            self._bump("snapshots")
        if not saved and not quarantined:
            with self._hydrate_lock:
                self._evicting.discard(doc_id)
            return False
        olock = self.oplog_lock if self.oplog_lock is not None \
            else contextlib.nullcontext()
        with self._hydrate_lock:
            # the oplog guard nests inside (io -> oplog) and freezes
            # len(ol) for the unsaved-suffix recheck below
            with olock:
                if doc_id not in self._evicting:
                    aborted = True      # resolve() claimed it mid-save
                elif saved and len(ol) != saved_len:
                    # a handler appended between the snapshot encode
                    # and this pop: dropping now would lose that
                    # suffix — keep the doc warm, retry under the next
                    # pressure round
                    aborted = True
                    self._evicting.discard(doc_id)
                else:
                    aborted = False
                    self._evicting.discard(doc_id)
                    self._warm.pop(doc_id, None)
                    self._touched.pop(doc_id, None)
        if aborted:
            self._bump("eviction_aborts")
            return False
        self._bump("evictions_to_snapshot")
        if saved:
            self._record_spill(doc_id, size_before)
        self._record("evicted_to_snapshot", doc=doc_id, why=why,
                     saved=saved)
        return True

    def _home_size(self, doc_id: str) -> int:
        """On-disk size of the doc's durable home (0 when absent) —
        the before/after probe spill-byte accounting is built on."""
        try:
            return os.path.getsize(self.store.path(doc_id))
        except OSError:
            return 0

    def _record_spill(self, doc_id: str, size_before: int) -> None:
        """One device-tier spill: warm state persisted to the snapshot
        home under bank/warm-map pressure. Bytes are the home file's
        growth, clamped at 0 (compaction can shrink the home)."""
        self._bump("spills_to_snapshot")
        grew = self._home_size(doc_id) - size_before
        if grew > 0:
            self._bump("spill_bytes", grew)

    # ---- bank snapshot hook (SessionBank.snapshot_hook) ------------------

    def request_snapshot(self, doc_id: str, pending_ops: int = 0) -> bool:
        """Async persistence request — the bank calls this from its
        eviction sites, possibly under shard/oplog locks, so it must
        only enqueue (never touch tier locks or disk)."""
        self._bump("snapshot_requests")
        try:
            self._snap_q.put_nowait((doc_id, pending_ops))
        except _queue.Full:
            self._bump("snapshot_queue_full")
            return False
        return True

    def _snapshot_loop(self) -> None:
        while not self._stop.is_set():
            try:
                doc_id, _pending = self._snap_q.get(timeout=0.05)
            except _queue.Empty:
                continue
            try:
                self._snapshot_job(doc_id)
            except Exception:   # pragma: no cover - keep worker alive
                pass

    def _snapshot_job(self, doc_id: str) -> None:
        with self._hydrate_lock:
            ol = self._warm.get(doc_id)
        if ol is None:
            return      # not warm here: nothing newer than the home
        size_before = self._home_size(doc_id)
        try:
            self.store.save(doc_id, ol, oplog_lock=self.oplog_lock)
            self._bump("snapshots")
            self._record_spill(doc_id, size_before)
        except DocQuarantined:
            pass
        except Exception as e:
            self._bump("snapshot_errors")
            self._record("snapshot_error", doc=doc_id, why="bank_evict",
                         error=f"{e.__class__.__name__}: {e}"[:120])

    # ---- lifecycle -------------------------------------------------------

    def drain_snapshots(self, timeout_s: float = 10.0) -> None:
        deadline = time.monotonic() + timeout_s
        while not self._snap_q.empty() and time.monotonic() < deadline:
            time.sleep(0.005)

    def checkpoint_all(self) -> int:
        """Persist every warm doc (shutdown / parity checks). Docs stay
        warm; returns the number snapshotted."""
        self.drain_snapshots()
        with self._hydrate_lock:
            docs = list(self._warm.items())
        n = 0
        for doc_id, ol in docs:
            try:
                self.store.save(doc_id, ol, oplog_lock=self.oplog_lock)
                self._bump("snapshots")
                n += 1
            except DocQuarantined:
                pass
            except Exception:
                self._bump("snapshot_errors")
        return n

    def stop(self, checkpoint: bool = True) -> None:
        """`checkpoint=False` models a crash: threads are abandoned
        mid-flight and nothing unsaved survives — exactly what the
        soak's crash-restart event needs."""
        if checkpoint:
            self.checkpoint_all()
        self._stop.set()
        for t in self._threads:
            t.join(timeout=1.0)
        self._threads = []

    def counters_snapshot(self) -> dict:
        with self._counter_lock:
            out = dict(self.counters)
        out["warm_docs"] = self.warm_count()
        return out
