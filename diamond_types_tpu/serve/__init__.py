"""Sharded multi-document merge scheduling over the device mesh.

The single-chip device tier peaks well below one host core (VERDICT r5:
VMEM de-amortization past ~8 docs/call plus the ~60 s per-program kill
bound), so production scale goes through the multi-chip path. This
package turns many independent documents into continuously fed,
shape-bucketed, per-shard batches:

  * `router`     — deterministic doc-id -> shard assignment
                   (rendezvous hashing, explicit rebalance)
  * `admission`  — shape-bucketed pending-merge queues with a
                   size-or-deadline flush trigger and bounded depth +
                   backpressure (JIT dynamic batching, arxiv 1904.07421)
  * `bank`       — per-shard DeviceZoneSession bank with LRU eviction
                   and device-slot capacity accounting
  * `metrics`    — JSON-exportable counters for bench.py / soak tools
  * `scheduler`  — the composition: DocStore-facing submit/pump/drain
  * `driver`     — trace-replay bench driver (cli serve-bench) with a
                   byte-parity gate against the single-engine merge
"""

from .admission import AdmissionQueue, Backpressure, shape_bucket
from .bank import SessionBank
from .metrics import ServeMetrics
from .router import ShardRouter
from .scheduler import MergeScheduler

__all__ = [
    "AdmissionQueue", "Backpressure", "MergeScheduler", "ServeMetrics",
    "SessionBank", "ShardRouter", "shape_bucket",
]
