"""Windowed time-series: live rates and quantiles over a ring of
fixed-width windows.

The cumulative `Histogram`s in hist.py answer "what has flush p99 been
since boot" — useless for "what is it *right now*". `TimeSeries` keeps
a ring of `n_windows` fixed-width windows (default 10 s x 360 = one
hour of history); each window holds per-name counter deltas and per-
name log2 bucket counts (same bucket ladder as hist.py, so the bucket
index math and le semantics line up exactly). Recording is one lock,
one dict lookup, one list index; querying merges the windows that
overlap the requested horizon.

This is the signal source for obs/slo.py's multi-window burn rates and
the `rate()` feed ROADMAP item 2's adaptive admission will consume.

Contracts:

  * disabled => allocation-free no-op (one branch; pinned by the
    tracemalloc test in tests/test_telemetry.py)
  * the clock is injectable (fake-clock rollover tests)
  * `_ts_lock` is a leaf in the canonical lock order — record calls
    happen under shard/oplog/device locks all over the serve tier, so
    this lock may never wrap anything that blocks (dt-lint classifies
    `_ts_lock` as leaf and the witness enforces it at runtime)
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.witness import make_lock
from .hist import _FIRST_BOUND_S, _N_BUCKETS, BOUNDS


def bucket_index(seconds: float) -> int:
    """hist.py's bucket math, shared so exemplars key the same le."""
    s = seconds if seconds > 0.0 else 0.0
    if s <= _FIRST_BOUND_S:
        return 0
    return int(math.ceil(math.log2(s / _FIRST_BOUND_S)))


class _WindowHist:
    """Per-window latency buckets — a bare Histogram without its own
    lock (the owning TimeSeries' `_ts_lock` guards it)."""

    __slots__ = ("counts", "overflow", "count", "sum")

    def __init__(self) -> None:
        self.counts: List[int] = [0] * _N_BUCKETS
        self.overflow = 0
        self.count = 0
        self.sum = 0.0

    def record(self, seconds: float, idx: int) -> None:
        self.count += 1
        self.sum += seconds
        if idx >= _N_BUCKETS:
            self.overflow += 1
        else:
            self.counts[idx] += 1


class _Window:
    __slots__ = ("idx", "counters", "hists")

    def __init__(self) -> None:
        self.idx = -1                       # absolute window index
        self.counters: Dict[str, float] = {}
        self.hists: Dict[str, _WindowHist] = {}

    def reset(self, idx: int) -> None:
        self.idx = idx
        self.counters.clear()
        self.hists.clear()


class TimeSeries:
    """Ring of fixed-width time windows holding counter deltas and
    log2 latency buckets, with windowed rate / quantile / count_over
    queries. One instance per Observability bundle."""

    def __init__(self, window_s: float = 10.0, n_windows: int = 360,
                 enabled: bool = True,
                 clock: Optional[Callable[[], float]] = None) -> None:
        if window_s <= 0 or n_windows < 2:
            raise ValueError("need window_s > 0 and n_windows >= 2")
        self.enabled = enabled
        self.window_s = float(window_s)
        self.n_windows = int(n_windows)
        self._clock = clock or time.monotonic
        self._t0 = self._clock()
        self._ring = [_Window() for _ in range(self.n_windows)]
        self._ts_lock = make_lock("obs.timeseries", "leaf")
        self.recorded = 0

    # ---- recording --------------------------------------------------------

    def _slot_locked(self) -> _Window:
        idx = int((self._clock() - self._t0) / self.window_s)
        w = self._ring[idx % self.n_windows]
        if w.idx != idx:
            w.reset(idx)
        return w

    def inc(self, name: str, n: float = 1.0) -> None:
        if not self.enabled:
            return
        with self._ts_lock:
            w = self._slot_locked()
            w.counters[name] = w.counters.get(name, 0.0) + n
            self.recorded += 1

    def observe(self, name: str, seconds: float) -> None:
        if not self.enabled:
            return
        s = seconds if seconds > 0.0 else 0.0
        idx = bucket_index(s)
        with self._ts_lock:
            w = self._slot_locked()
            h = w.hists.get(name)
            if h is None:
                h = w.hists[name] = _WindowHist()
            h.record(s, idx)
            self.recorded += 1

    # ---- queries ----------------------------------------------------------

    def _live_locked(self, window_s: float) -> Tuple[List[_Window], int]:
        """Windows overlapping [now - window_s, now], plus the window
        count the horizon spans (for rate denominators)."""
        n_back = max(1, int(math.ceil(window_s / self.window_s)))
        n_back = min(n_back, self.n_windows)
        cur = int((self._clock() - self._t0) / self.window_s)
        lo = cur - n_back
        return [w for w in self._ring if lo < w.idx <= cur], n_back

    def rate(self, name: str, window_s: float = 60.0) -> float:
        """Events/sec over the trailing horizon. Counter names sum
        their deltas; latency names count their observations."""
        with self._ts_lock:
            live, n_back = self._live_locked(window_s)
            total = 0.0
            for w in live:
                total += w.counters.get(name, 0.0)
                h = w.hists.get(name)
                if h is not None:
                    total += h.count
        return total / (n_back * self.window_s)

    def quantile(self, name: str, q: float,
                 window_s: float = 300.0) -> float:
        """Merged-bucket quantile over the trailing horizon; same
        interpolation (and factor-of-2 error bound) as hist.py."""
        merged = [0] * _N_BUCKETS
        count = 0
        mx = 0.0
        with self._ts_lock:
            live, _ = self._live_locked(window_s)
            for w in live:
                h = w.hists.get(name)
                if h is None:
                    continue
                count += h.count
                for i, c in enumerate(h.counts):
                    merged[i] += c
                if h.overflow:
                    mx = BOUNDS[-1] * 2
        if count == 0:
            return 0.0
        target = max(min(q, 1.0), 0.0) * count
        cum = 0
        for i, c in enumerate(merged):
            if c == 0:
                continue
            if cum + c >= target:
                lo = BOUNDS[i - 1] if i else 0.0
                return lo + (BOUNDS[i] - lo) * ((target - cum) / c)
            cum += c
        return mx or BOUNDS[-1]

    def count_over(self, name: str, threshold_s: float,
                   window_s: float = 300.0) -> Tuple[float, float]:
        """(events slower than threshold, total events) over the
        horizon — the bad/total pair burn rates are built from. A
        threshold exactly on a bucket bound counts that bucket as
        good (le is upper-inclusive)."""
        thr = bucket_index(threshold_s)
        bad = 0.0
        total = 0.0
        with self._ts_lock:
            live, _ = self._live_locked(window_s)
            for w in live:
                h = w.hists.get(name)
                if h is None:
                    continue
                total += h.count
                bad += h.overflow
                for i in range(min(thr + 1, _N_BUCKETS), _N_BUCKETS):
                    bad += h.counts[i]
        return bad, total

    def sum_over(self, name: str, window_s: float = 300.0) -> float:
        """Summed counter deltas (or latency sums) over the horizon."""
        total = 0.0
        with self._ts_lock:
            live, _ = self._live_locked(window_s)
            for w in live:
                total += w.counters.get(name, 0.0)
                h = w.hists.get(name)
                if h is not None:
                    total += h.sum
        return total

    def names(self) -> List[str]:
        out = set()
        with self._ts_lock:
            for w in self._ring:
                if w.idx >= 0:
                    out.update(w.counters)
                    out.update(w.hists)
        return sorted(out)

    # ---- snapshot ---------------------------------------------------------

    def snapshot(self, windows: Tuple[float, ...] = (60.0, 300.0)) -> dict:
        """JSON-able live view for /metrics: per-name rates over each
        requested horizon, plus p50/p99 for latency families."""
        out: dict = {"version": 1, "enabled": self.enabled,
                     "window_s": self.window_s,
                     "n_windows": self.n_windows,
                     "recorded": self.recorded,
                     "series": {}}
        if not self.enabled:
            return out
        for name in self.names():
            row: dict = {}
            for win in windows:
                key = f"{int(win)}s"
                row[f"rate_{key}"] = round(self.rate(name, win), 6)
            row["p50_300s"] = round(self.quantile(name, 0.5, 300.0), 6)
            row["p99_300s"] = round(self.quantile(name, 0.99, 300.0), 6)
            out["series"][name] = row
        return out
