"""Unified observability for the serve scheduler and replication mesh.

One `Observability` bundle per server process ties together:

  trace.py      sampled spans with X-DT-Trace cross-host propagation
  hist.py       log-bucketed latency histograms (p50/p90/p99)
  recorder.py   flight recorder — bounded ring of structured events
  prom.py       Prometheus/OpenMetrics exposition of the /metrics JSON
  devprof.py    wall-vs-device flush timing, jit-cache hits, transfers
  timeseries.py windowed ring: live rate()/quantile() per family
  slo.py        multi-window burn-rate SLO engine (/debug/slo)
  exemplars.py  last sampled trace id per histogram bucket
  attrib.py     top-K hot-doc/agent sketch (/debug/hot)
  journey.py    edit-to-visibility stage stamps + convergence lag
  assemble.py   cross-host trace assembly (clock-aligned waterfall
                + critical path; consumed by `cli dt-trace`)
  scorecard.py  versioned per-scenario scorecards + tolerance-band
                diffs (consumed by `cli scenario` / `scorecard-diff`)

The bundle is attached as `DocStore.obs` by tools/server.serve() and
propagated from there: MergeScheduler.attach_obs() wires the tracer
and recorder into the admit→flush path, attach_replication() hands it
to ReplicaNode for lease/fencing/circuit events and proxy tracing.
Everything degrades to a no-op when the bundle is absent or disabled —
hot paths pay one branch, zero allocations.
"""

from __future__ import annotations

from .attrib import HotAttribution, SpaceSaving
from .devprof import PROFILER, DeviceProfiler, note_jit_lookup, note_transfer
from .exemplars import ExemplarStore
from .hist import BOUNDS, Histogram, HistogramSet
from .incident import INCIDENT_KINDS, AnomalyDetector, IncidentStore
from .journey import STAGES as JOURNEY_STAGES
from .journey import OpJourney
from .prom import CONTENT_TYPE, OPENMETRICS_CONTENT_TYPE, render_metrics
from .recorder import FlightRecorder
from .scorecard import (SCORECARD_VERSION, build_scorecard,
                        diff_scorecards, last_scenario,
                        publish_scenario)
from .slo import Objective, SloEngine, default_objectives
from .timeseries import TimeSeries
from .trace import (NOOP_SPAN, TRACE_HEADER, Span, SpanContext, Tracer,
                    format_context, parse_header)

__all__ = [
    "Observability", "Tracer", "Span", "SpanContext", "NOOP_SPAN",
    "TRACE_HEADER", "format_context", "parse_header",
    "Histogram", "HistogramSet", "BOUNDS",
    "FlightRecorder",
    "CONTENT_TYPE", "OPENMETRICS_CONTENT_TYPE", "render_metrics",
    "PROFILER", "DeviceProfiler", "note_jit_lookup", "note_transfer",
    "TimeSeries", "SloEngine", "Objective", "default_objectives",
    "ExemplarStore", "HotAttribution", "SpaceSaving",
    "OpJourney", "JOURNEY_STAGES",
    "AnomalyDetector", "IncidentStore", "INCIDENT_KINDS",
    "SCORECARD_VERSION", "build_scorecard", "diff_scorecards",
    "publish_scenario", "last_scenario",
]


class Observability:
    """Per-server bundle: tracer + flight recorder + HTTP histograms.

    `sample_rate` head-samples trace roots (default 1%: cheap enough
    to leave on in soak runs); `enabled=False` turns the tracer and
    recorder into allocation-free no-ops while keeping the histograms
    (they are counters, not samples — always worth having).
    """

    def __init__(self, sample_rate: float = 0.01,
                 trace_capacity: int = 2048,
                 recorder_capacity: int = 512,
                 seed: int = 0, enabled: bool = True,
                 telemetry: bool = True,
                 ts_window_s: float = 10.0, ts_windows: int = 360,
                 objectives=None, attrib_k: int = 64,
                 journey: bool = True,
                 journey_capacity: int = 512,
                 incidents: bool = True,
                 incident_dir=None,
                 incident_opts=None) -> None:
        self.tracer = Tracer(sample_rate=sample_rate,
                             capacity=trace_capacity,
                             seed=seed, enabled=enabled)
        self.recorder = FlightRecorder(capacity=recorder_capacity,
                                       enabled=enabled)
        self.hist = HistogramSet()
        # live telemetry tier: windowed time-series + SLO burn rates +
        # exemplars + hot-key attribution. `telemetry=False` keeps the
        # cumulative tier while turning every live-tier write into a
        # single-branch no-op (the bench A/B toggle).
        live = enabled and telemetry
        self.ts = TimeSeries(window_s=ts_window_s, n_windows=ts_windows,
                             enabled=live)
        self.slo = SloEngine(self.ts, objectives=objectives,
                             recorder=self.recorder)
        self.exemplars = ExemplarStore(enabled=live)
        self.attrib = HotAttribution(k=attrib_k, enabled=live)
        # edit-to-visibility journey tracker: stamps ride the sampled
        # traces, so it follows the tracer's enablement; `journey=False`
        # is the bench A/B control arm (single-branch no-op stamps)
        self.journey = OpJourney(capacity=journey_capacity,
                                 ts=self.ts if live else None,
                                 enabled=enabled and journey)
        # incident engine: pull-driven anomaly detection over the live
        # tier + evidence-bundle capture. `incidents=False` is the
        # bench A/B control arm (poll() is a single-branch no-op); the
        # store stays constructed so /debug/incidents answers (empty)
        # and the prom families zero-fill either way.
        opts = dict(incident_opts or {})
        store_opts = {k: opts.pop(k) for k in ("capacity", "prefix")
                      if k in opts}
        self.incidents = IncidentStore(data_dir=incident_dir,
                                       **store_opts)
        self.incidents.attach(self)
        self.incident_detector = AnomalyDetector(
            self.ts, recorder=self.recorder, store=self.incidents,
            enabled=live and incidents, **opts)

    def snapshot(self) -> dict:
        # pull-driven detection (the SloEngine idiom): every snapshot
        # (== every /metrics scrape) re-evaluates the watched series
        self.incident_detector.poll()
        det = self.incident_detector.snapshot()
        sto = self.incidents.snapshot()
        out = {"trace": self.tracer.stats(),
               "recorder": self.recorder.stats(),
               "http": self.hist.snapshot(),
               "devprof": PROFILER.snapshot(),
               "timeseries": self.ts.snapshot(),
               "slo": self.slo.snapshot(),
               "exemplars": self.exemplars.snapshot(),
               "hot": self.attrib.snapshot(),
               "journey": self.journey.snapshot(),
               "incidents": {"version": 1, **sto, **det}}
        # concurrency-invariant tier (analysis/): the runtime lock
        # witness is always reported (enabled=False when off); the
        # lint block appears once a dt-lint run published a report in
        # this process
        from ..analysis import last_report, witness_snapshot
        out["witness"] = witness_snapshot()
        lint = last_report()
        if lint is not None:
            out["lint"] = lint
        # the model-checker verdict rides the same pattern: present
        # once a dt-explore run published in this process
        from ..analysis.explore import last_report as explore_report
        explore = explore_report()
        if explore is not None:
            out["explore"] = explore
        # the scenario runner's live snapshot (workload/runner.py
        # publishes each tick): present while/after a run in this
        # process — obs-watch renders it as the scenario panel
        scen = last_scenario()
        if scen is not None:
            out["scenario"] = scen
        return out
