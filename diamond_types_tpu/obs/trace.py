"""Sampled trace spans with cross-host propagation.

A span is (trace_id, span_id, parent_id, name, t0, dur, attrs). The
tracer head-samples at the root: a root span is either sampled (real
`Span`) or not (the shared `NOOP_SPAN` singleton) and every descendant
inherits that decision, so one slow edit either produces a complete
admit→queue→flush→device-sync tree or nothing. Crossing an HTTP hop
(proxied write, lease grant, quorum propose, anti-entropy pull) the
context rides the `X-DT-Trace` header as `trace_id-span_id-flags`; the
receiving server parses it and parents its own request span on the
remote caller, stitching both hosts into one trace.

Disabled tracers are a hard no-op: `start()` checks one flag and
returns `NOOP_SPAN` without allocating (verified by a tracemalloc test
in tests/test_obs.py), so the serve hot path pays a single branch when
observability is off.

Finished spans land in a bounded ring (deque) — this is a flight
recorder for traces, not an exporter; scrape via Tracer.spans().
"""

from __future__ import annotations

import collections
import os
import random
import threading
import time
from typing import Optional

TRACE_HEADER = "X-DT-Trace"


class SpanContext:
    """The wire-portable third of a span: enough to parent a child on
    another thread or another host."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 sampled: bool = True) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled


def format_context(ctx: SpanContext) -> str:
    return f"{ctx.trace_id}-{ctx.span_id}-{'1' if ctx.sampled else '0'}"


def parse_header(value: Optional[str]) -> Optional[SpanContext]:
    """Parse an `X-DT-Trace` header; malformed values are ignored (a
    bad header must never fail a request)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 3:
        return None
    trace_id, span_id, flags = parts
    if not trace_id or not span_id or len(trace_id) > 32 or len(span_id) > 32:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    return SpanContext(trace_id, span_id, flags == "1")


class _NoopSpan:
    """Shared do-nothing span. All tracer call sites can treat their
    span uniformly; `sampled` is the one flag to branch on when
    creating children costs anything."""

    __slots__ = ()
    sampled = False

    def context(self):
        return None

    def header(self):
        return None

    def annotate(self, **_kw):
        return None

    def end(self, **_kw):
        return None

    def __enter__(self):
        return self

    def __exit__(self, *_exc):
        return False


NOOP_SPAN = _NoopSpan()


class Span:
    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "t0", "attrs", "_done")
    sampled = True

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str],
                 attrs: dict) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self._done = False
        self.t0 = time.monotonic()

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, True)

    def header(self) -> str:
        return format_context(self.context())

    def annotate(self, **kw) -> None:
        self.attrs.update(kw)

    def end(self, **kw) -> None:
        if self._done:
            return
        self._done = True
        if kw:
            self.attrs.update(kw)
        self._tracer._finish(self)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, _exc, _tb):
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self.end()
        return False


class Tracer:
    """Head-sampling tracer with a bounded finished-span ring."""

    def __init__(self, sample_rate: float = 0.01, capacity: int = 2048,
                 seed: int = 0, enabled: bool = True) -> None:
        self.enabled = enabled
        self.sample_rate = float(sample_rate)
        self._lock = threading.Lock()
        self._rng = random.Random((seed << 16) ^ 0x7ace)
        # ids draw from a separate, per-tracer-unique stream: every
        # server defaults to seed=0, so id'ing from the (deterministic)
        # sampling rng would make all hosts mint IDENTICAL span-id
        # sequences — merged cross-host traces (obs/assemble.py) would
        # cross-link colliding ids into parent cycles
        self._id_rng = random.Random(
            ((seed << 16) ^ 0x7ace)
            ^ (os.getpid() << 48) ^ id(self)
            ^ time.monotonic_ns())
        self._spans: collections.deque = collections.deque(
            maxlen=max(int(capacity), 1))
        self.started = 0
        self.sampled_out = 0
        self.finished = 0

    def start(self, name: str, parent: Optional[SpanContext] = None,
              attrs: Optional[dict] = None, force: bool = False):
        """Open a span. `parent` is a SpanContext (from Span.context()
        or parse_header) — its sampling decision is inherited. Roots
        sample at `sample_rate` unless `force`."""
        if not self.enabled:
            return NOOP_SPAN
        with self._lock:
            self.started += 1
            if parent is not None:
                if not parent.sampled:
                    self.sampled_out += 1
                    return NOOP_SPAN
                trace_id = parent.trace_id
                parent_id = parent.span_id
            else:
                if not force and self._rng.random() >= self.sample_rate:
                    self.sampled_out += 1
                    return NOOP_SPAN
                trace_id = "%016x" % self._id_rng.getrandbits(64)
                parent_id = None
            span_id = "%016x" % self._id_rng.getrandbits(64)
        return Span(self, name, trace_id, span_id, parent_id,
                    dict(attrs) if attrs else {})

    def _finish(self, span: Span) -> None:
        rec = {"name": span.name,
               "trace": span.trace_id,
               "span": span.span_id,
               "parent": span.parent_id,
               "t0": round(span.t0, 6),
               "dur_s": round(time.monotonic() - span.t0, 6),
               "attrs": span.attrs}
        with self._lock:
            self.finished += 1
            self._spans.append(rec)

    def spans(self) -> list:
        with self._lock:
            return list(self._spans)

    def find(self, trace_id: str) -> list:
        return [s for s in self.spans() if s["trace"] == trace_id]

    def index(self, limit: int = 50) -> list:
        """Recent sampled traces, newest first: trace id, root span
        name (the earliest span without an in-ring parent), wall time
        and span count. Backs `GET /debug/traces`."""
        traces: dict = {}
        order: list = []
        for s in self.spans():
            tid = s["trace"]
            if tid not in traces:
                traces[tid] = []
                order.append(tid)
            traces[tid].append(s)
        out = []
        for tid in reversed(order):
            spans = traces[tid]
            ids = {s["span"] for s in spans}
            roots = [s for s in spans
                     if not s["parent"] or s["parent"] not in ids]
            root = min(roots or spans, key=lambda s: s["t0"])
            out.append({"trace": tid, "root": root["name"],
                        "t0": root["t0"], "dur_s": root["dur_s"],
                        "spans": len(spans)})
            if len(out) >= max(int(limit), 1):
                break
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "sample_rate": self.sample_rate,
                    "started": self.started,
                    "sampled_out": self.sampled_out,
                    "finished": self.finished,
                    "buffered": len(self._spans)}
