"""Log-bucketed latency histograms with cheap quantile snapshots.

One `Histogram` is a fixed ladder of powers-of-two buckets starting at
1µs (bucket i covers (2**(i-1), 2**i] µs), so recording is one log2 and
one list index — no allocation, no sorting, safe to call on every HTTP
request, flush, and probe. Quantiles are estimated by walking the
cumulative counts and interpolating inside the winning bucket, which
bounds the error to the bucket width (a factor of 2 worst case — good
enough to tell a 2ms flush from a 200ms one, which is all the serve
and replication dashboards need).

`snapshot()` includes the raw cumulative buckets so obs/prom.py can
render a Prometheus histogram (`*_bucket{le=...}` / `_sum` / `_count`)
straight from the JSON document without touching live objects.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

_FIRST_BOUND_S = 1e-6
_N_BUCKETS = 28          # 1µs .. ~134s; slower than that is overflow

BOUNDS: Tuple[float, ...] = tuple(
    _FIRST_BOUND_S * (2.0 ** i) for i in range(_N_BUCKETS))


class Histogram:
    """Thread-safe log2-bucketed histogram of durations in seconds."""

    __slots__ = ("_lock", "counts", "overflow", "count", "sum", "max")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counts: List[int] = [0] * _N_BUCKETS
        self.overflow = 0
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def record(self, seconds: float) -> None:
        s = seconds if seconds > 0.0 else 0.0
        if s <= _FIRST_BOUND_S:
            idx = 0
        else:
            # first bound >= s; exact powers land in their own bucket
            # (upper-inclusive, matching Prometheus `le` semantics)
            idx = int(math.ceil(math.log2(s / _FIRST_BOUND_S)))
        with self._lock:
            self.count += 1
            self.sum += s
            if s > self.max:
                self.max = s
            if idx >= _N_BUCKETS:
                self.overflow += 1
            else:
                self.counts[idx] += 1

    # ---- quantiles --------------------------------------------------------

    def quantile(self, q: float) -> float:
        with self._lock:
            return self._quantile_locked(q)

    def _quantile_locked(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        target = max(min(q, 1.0), 0.0) * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = BOUNDS[i - 1] if i else 0.0
                hi = BOUNDS[i]
                return lo + (hi - lo) * ((target - cum) / c)
            cum += c
        return self.max        # target fell in the overflow bucket

    # ---- snapshots --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "count": self.count,
                "sum": round(self.sum, 6),
                "max": round(self.max, 6),
                "p50": round(self._quantile_locked(0.50), 6),
                "p90": round(self._quantile_locked(0.90), 6),
                "p99": round(self._quantile_locked(0.99), 6),
                "buckets": self._buckets_locked(),
            }

    def _buckets_locked(self) -> list:
        # [[le_seconds, cumulative_count], ...] trimmed to the last
        # non-empty bucket, always terminated by ["+Inf", count]
        out: list = []
        last = -1
        for i, c in enumerate(self.counts):
            if c:
                last = i
        cum = 0
        for i in range(last + 1):
            cum += self.counts[i]
            out.append([BOUNDS[i], cum])
        out.append(["+Inf", self.count])
        return out


class HistogramSet:
    """A family of histograms keyed by (name, labels) — e.g. one
    `http_request` histogram per endpoint. Label cardinality must be
    bounded by the caller (endpoint/action names, never doc ids)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._h: Dict[Tuple[str, tuple], Histogram] = {}

    def observe(self, name: str, seconds: float, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        h = self._h.get(key)
        if h is None:
            with self._lock:
                h = self._h.setdefault(key, Histogram())
        h.record(seconds)

    def get(self, name: str, **labels) -> Optional[Histogram]:
        return self._h.get((name, tuple(sorted(labels.items()))))

    def snapshot(self) -> dict:
        with self._lock:
            items = list(self._h.items())
        out: Dict[str, list] = {}
        for (name, labels), h in sorted(
                items, key=lambda kv: (kv[0][0], kv[0][1])):
            entry = {"labels": dict(labels)}
            entry.update(h.snapshot())
            out.setdefault(name, []).append(entry)
        return out
