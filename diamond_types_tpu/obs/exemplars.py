"""Trace exemplars: last sampled trace ID per histogram bucket.

A p99 outlier in `dt_flush_latency_seconds` is a number; the question
is always "show me THAT flush". Each latency family keeps, per log2
bucket (same ladder as hist.py / timeseries.py), the most recent
sampled trace that landed there — so the prom exporter can emit
OpenMetrics exemplars on the `_bucket` lines and a dashboard click
resolves straight to the flight-recorder / span view of that exact
operation.

Only sampled traces are noted (callers pass the trace id of an
already-sampled span), so the overhead rides the existing head-
sampling budget; disabled => one branch, zero allocations.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

from ..analysis.witness import make_lock
from .hist import _N_BUCKETS, BOUNDS
from .timeseries import bucket_index


class ExemplarStore:
    """(family, bucket) -> (trace_id, value, unix_ts). Cardinality is
    bounded by families x 29 buckets; families are endpoint/flush
    names, never doc ids."""

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self.noted = 0
        self._ts_lock = make_lock("obs.exemplars", "leaf")
        self._ex: Dict[Tuple[str, int], Tuple[str, float, float]] = {}

    def note(self, family: str, seconds: float,
             trace_id: Optional[str]) -> None:
        if not self.enabled or not trace_id:
            return
        idx = min(bucket_index(seconds), _N_BUCKETS)   # 28 == +Inf
        with self._ts_lock:
            self._ex[(family, idx)] = (trace_id, seconds, time.time())
            self.noted += 1

    def get(self, family: str, idx: int):
        with self._ts_lock:
            return self._ex.get((family, idx))

    def for_family(self, family: str) -> Dict[float, dict]:
        """le-keyed exemplars for one family (le math mirrors the
        trimmed-bucket rendering in prom.py: idx 28 is +Inf)."""
        out: Dict[float, dict] = {}
        with self._ts_lock:
            items = [(k, v) for k, v in self._ex.items()
                     if k[0] == family]
        for (_, idx), (tid, val, ts) in items:
            le = BOUNDS[idx] if idx < _N_BUCKETS else float("inf")
            out[le] = {"trace": tid, "value": val, "ts": ts}
        return out

    def snapshot(self) -> dict:
        with self._ts_lock:
            items = sorted(self._ex.items())
        fams: Dict[str, list] = {}
        for (fam, idx), (tid, val, ts) in items:
            le = BOUNDS[idx] if idx < _N_BUCKETS else "+Inf"
            fams.setdefault(fam, []).append(
                {"le": le, "trace": tid, "value": round(val, 6),
                 "ts": round(ts, 3)})
        return {"version": 1, "enabled": self.enabled,
                "noted": self.noted, "families": fams}
