"""Prometheus text exposition rendered from the /metrics JSON document.

`render_metrics(doc)` takes the exact dict `GET /metrics` already
serves ({"serve": ..., "replication": ..., "obs": ...}) and flattens
it to the text format (version 0.0.4) as `dt_*` metrics. Rendering
from the JSON snapshot — not from live objects — guarantees the two
formats can never disagree and keeps this module free of locks.

Naming scheme:
  dt_serve_<counter>_total            scheduler totals
  dt_serve_flush_reason_total{reason}
  dt_serve_shard_*{shard}             per-shard gauges/counters
  dt_repl_<group>_<key>_total         replication counters
  dt_rebalance_<counter>_total /      elastic-mesh migrations (zero-
  dt_rebalance_override_table_size    filled) + override-table gauge
  dt_writergroup_<counter>_total /    hot-doc write splitting (zero-
  dt_writergroup_{active_groups,      filled counters + point-in-time
                  member_entries}    table gauges)
  dt_wire_<key>_total{channel}        wire-tier transport accounting
                                      (bytes_sent, bytes_saved, frames,
                                      snapshot_ships per channel)
  dt_qos_<key>_total{class}           adaptive-admission per-class
                                      counters (admitted/shed/deferred,
                                      zero-filled over the class
                                      taxonomy) + the effective-deadline
                                      gauge and controller decisions
  dt_read_<counter>_total             follower-read tier counters
  dt_read_local_ratio /               local-serve ratio gauge +
  dt_read_staleness_seconds           staleness histogram
  dt_<name>_latency_seconds           histograms (flush, handoff,
                                      quorum_round, probe,
                                      antientropy_round,
                                      rebalance_drain)
  dt_http_request_seconds{endpoint,method}
  dt_trace_* / dt_recorder_* / dt_devprof_*
  dt_slo_*{objective}                 burn-rate gauges + alert state
  dt_hot_*{dim,kind[,key]}            top-K attribution (bounded: the
                                      sketch caps key cardinality)
  dt_ts_*{series}                     live windowed rates / p99
  dt_journey_*{stage}                 edit-to-visibility stage stamps
                                      (zero-filled over journey.STAGES)
  dt_convergence_lag_*{peer}          per-peer admitted->advert lag
                                      rollup (+ the peer="all" row)
  dt_incident_opened_total{kind}      incident engine: bundles opened
                                      (zero-filled over INCIDENT_KINDS)
  dt_incident_suppressed_total        cooldown-deduped detections
  dt_incident_open                    unacknowledged-bundle gauge

Each metric name is declared exactly once (# TYPE line) no matter how
many labeled samples it carries; label values are escaped per the
exposition spec (backslash, double-quote, newline).

Known-at-registration families (`dt_read_*`, `dt_serve_hydration_*`)
are zero-filled whenever a serve block is present, so a scraper never
sees a series flicker into existence on first use.

`render_metrics(doc, openmetrics=True)` emits OpenMetrics 1.0 instead:
counter TYPE lines drop the `_total` suffix (samples keep it), the
output is terminated by `# EOF`, and histogram `_bucket` lines carry
trace exemplars (`# {trace_id="..."} value ts`) wherever the exemplar
store saw a sampled trace land in that bucket — the p99-outlier-to-
flight-recorder hop. tools/server.py negotiates the format from the
Accept header.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .incident import INCIDENT_KINDS
from .journey import STAGES as JOURNEY_STAGES

CONTENT_TYPE = "text/plain; version=0.0.4"
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"

# prom histogram family -> obs.timeseries family, for exemplar lookup
_EXEMPLAR_FAMILIES = {
    "dt_flush_latency_seconds": "serve.flush",
    "dt_queue_wait_latency_seconds": "serve.queue_wait",
    "dt_hydration_cold_start_latency_seconds":
        "serve.hydration_cold_start",
    "dt_quorum_round_latency_seconds": "repl.quorum_round",
    "dt_handoff_latency_seconds": "repl.handoff",
    "dt_read_staleness_seconds": "read.staleness",
    "dt_read_wait_latency_seconds": "read.read_wait",
}

_SLO_STATE_CODE = {"ok": 0, "warning": 1, "burning": 2}

_EMPTY_HIST = {"count": 0, "sum": 0.0, "buckets": [["+Inf", 0]]}


def escape_label_value(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        return repr(round(v, 9))
    return str(v)


def _fmt_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Builder:
    """Accumulates samples grouped by metric family so every name gets
    exactly one # TYPE declaration. In OpenMetrics mode counter TYPE
    lines drop the `_total` suffix, `_bucket` samples may carry
    exemplars, and the output ends with `# EOF`."""

    def __init__(self, openmetrics: bool = False,
                 exemplars: Optional[dict] = None) -> None:
        self.openmetrics = openmetrics
        # metric family name -> {le_string -> {trace, value, ts}}
        self._exemplars = exemplars or {}
        self._order: List[str] = []
        self._fams: Dict[str, dict] = {}

    def add(self, name: str, mtype: str, value,
            labels: Optional[dict] = None,
            suffix: str = "", exemplar: str = "") -> None:
        fam = self._fams.get(name)
        if fam is None:
            fam = {"type": mtype, "lines": []}
            self._fams[name] = fam
            self._order.append(name)
        fam["lines"].append(
            f"{name}{suffix}{_fmt_labels(labels)} "
            f"{_fmt_value(value)}{exemplar}")

    def histogram(self, name: str, snap: dict,
                  labels: Optional[dict] = None) -> None:
        """Render one obs.hist.Histogram.snapshot() (with `buckets`)
        as a Prometheus histogram family."""
        fam_ex = self._exemplars.get(name) if self.openmetrics else None
        for le, cum in snap.get("buckets", []):
            bl = dict(labels or {})
            le_s = le if isinstance(le, str) else repr(float(le))
            bl["le"] = le_s
            ex = ""
            if fam_ex:
                row = fam_ex.get(le_s)
                if row:
                    ex = (f' # {{trace_id="'
                          f'{escape_label_value(row["trace"])}"}} '
                          f'{_fmt_value(row["value"])} '
                          f'{_fmt_value(row["ts"])}')
            self.add(name, "histogram", cum, labels=bl,
                     suffix="_bucket", exemplar=ex)
        self.add(name, "histogram", snap.get("sum", 0.0),
                 labels=labels, suffix="_sum")
        self.add(name, "histogram", snap.get("count", 0),
                 labels=labels, suffix="_count")

    def render(self) -> str:
        out: List[str] = []
        for name in self._order:
            fam = self._fams[name]
            tname = name
            if (self.openmetrics and fam["type"] == "counter"
                    and tname.endswith("_total")):
                tname = tname[:-len("_total")]
            out.append(f"# TYPE {tname} {fam['type']}")
            out.extend(fam["lines"])
        text = "\n".join(out) + "\n"
        if self.openmetrics:
            text += "# EOF\n"
        return text


def _render_serve(b: _Builder, serve: dict) -> None:
    for key, mtype in (("uptime_s", "gauge"),
                       ("batch_occupancy", "gauge"),
                       ("host_fallback_ratio", "gauge"),
                       ("max_depth_seen", "gauge")):
        if key in serve:
            b.add(f"dt_serve_{key}", mtype, serve[key])
    if "queue_bound_violations" in serve:
        b.add("dt_serve_queue_bound_violations_total", "counter",
              serve["queue_bound_violations"])
    for k, v in sorted((serve.get("totals") or {}).items()):
        b.add(f"dt_serve_{k}_total", "counter", v)
    # residency tier (metrics v7): cold->warm hydration + snapshot
    # eviction counters; the cold-start histogram rides the shared
    # latencies loop below as dt_hydration_cold_start_latency_seconds.
    # Zero-filled over HYDRATION_KEYS so the family exists from the
    # first scrape, not from the first hydration.
    from ..serve.metrics import HYDRATION_KEYS
    hyd = {k: 0 for k in HYDRATION_KEYS}
    hyd.update(serve.get("hydration") or {})
    for k, v in sorted(hyd.items()):
        b.add(f"dt_serve_hydration_{k}_total", "counter", v)
    for reason, n in sorted((serve.get("flush_reasons") or {}).items()):
        b.add("dt_serve_flush_reason_total", "counter", n,
              labels={"reason": reason})
    fused = serve.get("fused") or {}
    if fused:
        # fused_calls/fused_docs totals already render from "totals";
        # this block adds the occupancy gauge + histogram (docs folded
        # per vmapped device call)
        b.add("dt_serve_fused_occupancy", "gauge",
              fused.get("occupancy", 0.0))
        for docs, n in sorted((fused.get("occupancy_hist") or {})
                              .items(), key=lambda kv: int(kv[0])):
            b.add("dt_serve_fused_flush_total", "counter", n,
                  labels={"docs": str(docs)})
    window = serve.get("window") or {}
    if window:
        # the mesh flush-window block (metrics schema v6):
        # device_calls_per_window is the N-dispatches-to-1 signal,
        # mesh_occupancy the super-batch padding efficiency
        for key in ("windows", "device_windows", "dispatches", "docs",
                    "mesh_docs", "mesh_padded_rows"):
            if key in window:
                b.add(f"dt_serve_window_{key}_total", "counter",
                      window[key])
        # zero-filled (window.get default): the staging families exist
        # from the first scrape even against a pre-v13 snapshot
        b.add("dt_serve_window_transfer_bytes_total", "counter",
              window.get("staged_bytes", 0))
        b.add("dt_serve_window_staged_bytes_per_window", "gauge",
              window.get("staged_bytes_per_window", 0.0))
        for key in ("device_calls_per_window", "mesh_occupancy"):
            if key in window:
                b.add(f"dt_serve_window_{key}", "gauge", window[key])
        for shards, n in sorted((window.get("shards_hist") or {})
                                .items(), key=lambda kv: int(kv[0])):
            b.add("dt_serve_window_shards_total", "counter", n,
                  labels={"shards": str(shards)})
    for i, row in enumerate(serve.get("per_shard") or []):
        lb = {"shard": str(row.get("shard", i))}
        if "queue_depth" in row:
            b.add("dt_serve_shard_queue_depth", "gauge",
                  row["queue_depth"], labels=lb)
        if "footprint_slots" in row:
            b.add("dt_serve_shard_footprint_slots", "gauge",
                  row["footprint_slots"], labels=lb)
        if "flush_wall_s" in row:
            b.add("dt_serve_shard_flush_wall_seconds_total", "counter",
                  row["flush_wall_s"], labels=lb)
        if "device_sync_s" in row:
            b.add("dt_serve_shard_device_sync_seconds_total", "counter",
                  row["device_sync_s"], labels=lb)
    for name, snap in sorted((serve.get("latencies") or {}).items()):
        b.histogram(f"dt_{name}_latency_seconds", snap)


def _render_qos(b: _Builder, qos: dict) -> None:
    """The adaptive-admission block (QosController.export / the
    scorecard `qos` block). Zero-filled over QOS_CLASSES x
    QOS_CLASS_KEYS and QOS_CTL_KEYS (the HYDRATION_KEYS idiom): an
    idle controller still exports every series, so scrapers never see
    a class flicker into existence on its first shed."""
    from ..qos.classes import QOS_CLASSES
    from ..qos.metrics import QOS_CLASS_KEYS, QOS_CTL_KEYS
    b.add("dt_qos_enabled", "gauge", 1 if qos.get("enabled") else 0)
    classes = qos.get("classes") or {}
    names = sorted(set(QOS_CLASSES) | set(classes))
    for key in QOS_CLASS_KEYS:
        for cls in names:
            b.add(f"dt_qos_{key}_total", "counter",
                  (classes.get(cls) or {}).get(key, 0),
                  labels={"class": cls})
    for cls in names:
        b.add("dt_qos_deadline_seconds", "gauge",
              (classes.get(cls) or {}).get("deadline_s", 0.0),
              labels={"class": cls})
    ctl = qos.get("controller") or {}
    for key in QOS_CTL_KEYS:
        b.add("dt_qos_controller_total", "counter", ctl.get(key, 0),
              labels={"decision": key})
    shed = qos.get("shed") or {}
    if shed:
        b.add("dt_qos_mesh_state", "gauge",
              _SLO_STATE_CODE.get(shed.get("mesh_state", "ok"), 0))
        b.add("dt_qos_hot_tenants", "gauge",
              len(shed.get("hot_tenants") or []))
        b.add("dt_qos_retry_after_seconds", "gauge",
              shed.get("retry_after_s", 0.0))


def _render_read(b: _Builder, read: dict) -> None:
    """The follower-read tier (ServeMetrics v8 `read` block /
    top-level `read` key): READ_KEYS counters as dt_read_*_total, the
    local-serve ratio gauge, the staleness histogram, and the catch-up
    wait histogram (via the shared latency naming)."""
    from ..read.metrics import READ_KEYS
    counters = {k: 0 for k in READ_KEYS}
    counters.update(read.get("counters") or {})
    for k, v in sorted(counters.items()):
        b.add(f"dt_read_{k}_total", "counter", v)
    b.add("dt_read_local_ratio", "gauge",
          read.get("local_ratio") or 0.0)
    st = read.get("staleness")
    b.histogram("dt_read_staleness_seconds",
                st if isinstance(st, dict) and st else _EMPTY_HIST)
    lat = dict(read.get("latencies") or {})
    lat.setdefault("read_wait", _EMPTY_HIST)
    for name, snap in sorted(lat.items()):
        b.histogram(f"dt_{name}_latency_seconds", snap)


def _render_replication(b: _Builder, repl: dict) -> None:
    # elastic mesh: dedicated dt_rebalance_* families, zero-filled (the
    # snapshot always carries the group, so an idle mesh still exports
    # every series). override_table_size is a point-in-time gauge; the
    # rest are counters; the drain histogram rides the shared latency
    # loop below as dt_rebalance_drain_latency_seconds.
    rb = repl.get("rebalance")
    if isinstance(rb, dict):
        for k, v in sorted(rb.items()):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if k == "override_table_size":
                b.add("dt_rebalance_override_table_size", "gauge", v)
            else:
                b.add(f"dt_rebalance_{k}_total", "counter", v)
    # hot-doc write splitting: dedicated dt_writergroup_* families,
    # zero-filled like rebalance; the two table sizes are point-in-time
    # gauges, the rest are counters.
    wg = repl.get("writergroup")
    if isinstance(wg, dict):
        for k, v in sorted(wg.items()):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if k in ("active_groups", "member_entries"):
                b.add(f"dt_writergroup_{k}", "gauge", v)
            else:
                b.add(f"dt_writergroup_{k}_total", "counter", v)
    # wire tier: per-channel transport accounting as dedicated labeled
    # dt_wire_* families — the flat `{channel}_{key}` snapshot keys
    # split back into a channel label so dashboards can sum/stack the
    # four transport channels without regex gymnastics.
    wire = repl.get("wire")
    if isinstance(wire, dict):
        from ..wire.frames import WIRE_CHANNELS, WIRE_KEYS
        for ch in WIRE_CHANNELS:
            for key in WIRE_KEYS:
                v = wire.get(f"{ch}_{key}")
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                b.add(f"dt_wire_{key}_total", "counter", v,
                      labels={"channel": ch})
    for group, vals in sorted(repl.items()):
        if group in ("version", "self", "latencies") or \
                not isinstance(vals, dict):
            continue
        if group in ("per_peer", "membership_view", "quorum_view",
                     "faults", "rebalance", "wire", "writergroup"):
            # rebalance / wire / writergroup rendered above under their
            # own dt_rebalance_* / dt_wire_* / dt_writergroup_*
            # prefixes, not the generic dt_repl_* one
            continue
        for k, v in sorted(vals.items()):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if isinstance(v, float):
                b.add(f"dt_repl_{group}_{k}", "gauge", v)
            else:
                b.add(f"dt_repl_{group}_{k}_total", "counter", v)
    for name, snap in sorted((repl.get("latencies") or {}).items()):
        b.histogram(f"dt_{name}_latency_seconds", snap)


def _render_obs(b: _Builder, obs: dict) -> None:
    for name, series in sorted((obs.get("http") or {}).items()):
        for entry in series:
            b.histogram(f"dt_{name}_seconds", entry,
                        labels=entry.get("labels") or {})
    tr = obs.get("trace") or {}
    for k in ("started", "sampled_out", "finished"):
        if k in tr:
            b.add(f"dt_trace_spans_{k}_total", "counter", tr[k])
    rec = obs.get("recorder") or {}
    for k in ("recorded", "dropped"):
        if k in rec:
            b.add(f"dt_recorder_events_{k}_total", "counter", rec[k])
    dp = obs.get("devprof") or {}
    # zero-fill the known jit families (the HYDRATION_KEYS idiom): the
    # "xform"/"pallas" rows exist from the first scrape, not only after
    # the first transform/Pallas dispatch seeds the cache
    jit: dict = {k: {} for k in ("fused", "mesh", "micro", "tip",
                                 "xform", "pallas")} if dp else {}
    jit.update(dp.get("jit_cache") or {})
    for cache, hm in sorted(jit.items()):
        lb = {"cache": cache}
        hits = hm.get("hits", 0)
        misses = hm.get("misses", 0)
        b.add("dt_devprof_jit_hits_total", "counter", hits, labels=lb)
        b.add("dt_devprof_jit_misses_total", "counter", misses,
              labels=lb)
        # zero-filled hit-rate gauge per cache (0.0 until a lookup)
        b.add("dt_devprof_jit_hit_rate", "gauge",
              round(hits / (hits + misses), 4) if hits + misses
              else 0.0, labels=lb)
    if dp:
        b.add("dt_devprof_flush_wall_seconds_total", "counter",
              dp.get("flush_wall_s", 0.0))
        b.add("dt_devprof_device_sync_seconds_total", "counter",
              dp.get("device_sync_s", 0.0))
        b.add("dt_devprof_transfer_bytes_total", "counter",
              dp.get("transfer_bytes", 0))
        # per-(rung, purpose) transfer split — stage vs plan vs warmup
        for key, row in sorted((dp.get("transfer_detail")
                                or {}).items()):
            rung, _, purpose = key.partition(".")
            lb = {"rung": rung, "purpose": purpose}
            b.add("dt_devprof_transfer_detail_total", "counter",
                  row.get("transfers", 0), labels=lb)
            b.add("dt_devprof_transfer_detail_bytes_total", "counter",
                  row.get("bytes", 0), labels=lb)
    wit = obs.get("witness") or {}
    if wit:
        # one gauge per observed class edge (small, bounded by the
        # canonical order's class count squared) + scalar summary
        b.add("dt_witness_enabled", "gauge",
              1 if wit.get("enabled") else 0)
        b.add("dt_witness_acquires_total", "counter",
              wit.get("acquires", 0))
        b.add("dt_witness_violations_total", "counter",
              wit.get("violation_count", 0))
        b.add("dt_witness_acyclic", "gauge",
              1 if wit.get("acyclic", True) else 0)
        for edge, n in sorted((wit.get("edges") or {}).items()):
            b.add("dt_witness_edges", "gauge", n,
                  labels={"edge": edge})
    lint = obs.get("lint") or {}
    if lint:
        for rule, n in sorted((lint.get("by_rule") or {}).items()):
            b.add("dt_lint_violations_total", "counter", n,
                  labels={"rule": rule})
        b.add("dt_lint_files", "gauge", lint.get("files", 0))
        b.add("dt_lint_ok", "gauge", 1 if lint.get("ok") else 0)
    explore = obs.get("explore") or {}
    if explore:
        lb = {"scenario": explore.get("scenario", "")}
        b.add("dt_explore_ok", "gauge",
              1 if explore.get("ok") else 0, labels=lb)
        b.add("dt_explore_complete", "gauge",
              1 if explore.get("complete") else 0, labels=lb)
        b.add("dt_explore_depth", "gauge",
              explore.get("depth", 0), labels=lb)
        b.add("dt_explore_states_total", "counter",
              explore.get("states", 0), labels=lb)
        b.add("dt_explore_states_per_second", "gauge",
              explore.get("states_per_s", 0.0), labels=lb)
        b.add("dt_explore_violations_total", "counter",
              explore.get("violations", 0), labels=lb)
    # live telemetry tier: SLO burn-rate gauges, windowed rates, and
    # the top-K hot-doc/agent attribution (all bounded cardinality)
    slo = obs.get("slo") or {}
    for row in slo.get("objectives") or []:
        lb = {"objective": row["name"]}
        b.add("dt_slo_state", "gauge",
              _SLO_STATE_CODE.get(row["state"], 0), labels=lb)
        b.add("dt_slo_burn_rate", "gauge", row["fast"]["burn"],
              labels=dict(lb, window="fast"))
        b.add("dt_slo_burn_rate", "gauge", row["slow"]["burn"],
              labels=dict(lb, window="slow"))
        b.add("dt_slo_transitions_total", "counter",
              row["transitions"], labels=lb)
    if slo:
        b.add("dt_slo_ok", "gauge", 1 if slo.get("ok", True) else 0)
    ts = obs.get("timeseries") or {}
    if ts:
        b.add("dt_ts_enabled", "gauge", 1 if ts.get("enabled") else 0)
        b.add("dt_ts_recorded_total", "counter", ts.get("recorded", 0))
        for series, row in sorted((ts.get("series") or {}).items()):
            lb = {"series": series}
            if "rate_60s" in row:
                b.add("dt_ts_rate", "gauge", row["rate_60s"],
                      labels=dict(lb, window="60s"))
            if "p99_300s" in row:
                b.add("dt_ts_p99_seconds", "gauge", row["p99_300s"],
                      labels=lb)
    # edit-to-visibility journey tier: zero-filled stage counters (the
    # jit-family idiom above — every stage row exists from the first
    # scrape) plus the per-peer convergence-lag rollup. The aggregate
    # peer="all" row keeps the lag family present before any peer has
    # adverted, so scrapers see a stable family set.
    jo = obs.get("journey") or {}
    if jo:
        b.add("dt_journey_enabled", "gauge",
              1 if jo.get("enabled") else 0)
        b.add("dt_journey_tracked", "gauge", jo.get("tracked", 0))
        b.add("dt_journey_stamps_total", "counter",
              jo.get("stamped", 0))
        b.add("dt_journey_dropped_total", "counter",
              jo.get("dropped", 0))
        stages = dict.fromkeys(JOURNEY_STAGES, 0)
        stages.update(jo.get("stages") or {})
        for stage in JOURNEY_STAGES:
            b.add("dt_journey_stage_total", "counter", stages[stage],
                  labels={"stage": stage})
        conv = jo.get("convergence") or {}
        all_n = sum(row.get("n", 0) for row in conv.values())
        all_sum = sum(row.get("n", 0) * row.get("mean_s", 0.0)
                      for row in conv.values())
        all_max = max([row.get("max_s", 0.0)
                       for row in conv.values()] or [0.0])
        for peer, row in [("all", {"n": all_n,
                                   "mean_s": all_sum / all_n
                                   if all_n else 0.0,
                                   "max_s": all_max})] \
                + sorted(conv.items()):
            lb = {"peer": peer}
            b.add("dt_convergence_lag_count", "counter",
                  row.get("n", 0), labels=lb)
            b.add("dt_convergence_lag_seconds_sum", "counter",
                  round(row.get("n", 0) * row.get("mean_s", 0.0), 6),
                  labels=lb)
            b.add("dt_convergence_lag_seconds_max", "gauge",
                  row.get("max_s", 0.0), labels=lb)
    # incident engine: zero-filled over INCIDENT_KINDS (the journey-
    # stage idiom) so every kind row exists from the first scrape even
    # on an idle server; the block itself is always present in the obs
    # snapshot, detector enabled or not.
    inc = obs.get("incidents")
    if isinstance(inc, dict):
        b.add("dt_incident_detector_enabled", "gauge",
              1 if inc.get("enabled") else 0)
        kinds = dict.fromkeys(INCIDENT_KINDS, 0)
        kinds.update(inc.get("by_kind") or {})
        for kind in INCIDENT_KINDS:
            b.add("dt_incident_opened_total", "counter", kinds[kind],
                  labels={"kind": kind})
        b.add("dt_incident_suppressed_total", "counter",
              inc.get("suppressed", 0))
        b.add("dt_incident_open", "gauge", inc.get("open", 0))
    hot = obs.get("hot") or {}
    for dim in ("doc", "agent"):
        for kind, block in sorted((hot.get(dim) or {}).items()):
            lb = {"dim": dim, "kind": kind}
            b.add("dt_hot_attributed_total", "counter",
                  block.get("total", 0.0), labels=lb)
            for key, count, _err in block.get("top") or []:
                b.add("dt_hot_top", "gauge", count,
                      labels=dict(lb, key=key))
    ex = obs.get("exemplars") or {}
    if ex:
        b.add("dt_exemplars_noted_total", "counter",
              ex.get("noted", 0))


def _exemplar_index(obs: dict) -> dict:
    """{prom family -> {le_string -> exemplar row}} from the exemplar
    store's snapshot (family names are TimeSeries series names)."""
    fams = (obs.get("exemplars") or {}).get("families") or {}
    out: Dict[str, dict] = {}
    for metric, series in _EXEMPLAR_FAMILIES.items():
        rows = fams.get(series)
        if not rows:
            continue
        out[metric] = {
            (r["le"] if isinstance(r["le"], str)
             else repr(float(r["le"]))): r
            for r in rows}
    return out


def render_metrics(doc: dict, openmetrics: bool = False) -> str:
    """Flatten the /metrics JSON document to Prometheus text format
    (or OpenMetrics 1.0 with exemplars when `openmetrics=True`)."""
    obs_doc = doc.get("obs")
    b = _Builder(openmetrics=openmetrics,
                 exemplars=_exemplar_index(obs_doc)
                 if openmetrics and isinstance(obs_doc, dict) else None)
    serve = doc.get("serve")
    if isinstance(serve, dict):
        _render_serve(b, serve)
    # adaptive admission: the qos block rides top-level in the /metrics
    # document (None/absent when no controller is attached — families
    # omitted entirely, like the wire block on a meshless server)
    qos = doc.get("qos")
    if isinstance(qos, dict):
        _render_qos(b, qos)
    # the read block rides either at top level (scheduler-less
    # servers) or inside the serve snapshot (ServeMetrics v8); render
    # whichever is present, once. A serving process with no read tier
    # yet still zero-fills the dt_read_* families (no series flicker).
    read = doc.get("read")
    if not isinstance(read, dict) and isinstance(serve, dict):
        read = serve.get("read")
    if isinstance(read, dict):
        _render_read(b, read)
    elif isinstance(serve, dict):
        _render_read(b, {})
    repl = doc.get("replication")
    if isinstance(repl, dict):
        _render_replication(b, repl)
    obs = doc.get("obs")
    if isinstance(obs, dict):
        _render_obs(b, obs)
    return b.render()
