"""Declarative SLOs evaluated as multi-window burn rates.

An `Objective` says "target fraction of <series> events must finish
under <threshold_s>". The engine reads bad/total pairs from the
TimeSeries ring over two horizons (fast 5 m, slow 1 h) and computes
the classic burn rate

    burn = bad_fraction / error_budget,   error_budget = 1 - target

A burn of 1.0 spends the budget exactly at the sustainable pace; the
default thresholds (fast 14.4, slow 6.0) are the SRE-workbook pair for
a paged alert. The alert state machine is:

    burning   fast AND slow burn both over their thresholds
              (the AND suppresses one-window blips)
    warning   either horizon is eating budget faster than sustainable
              (burn >= 1.0) but the page condition has not met
    ok        otherwise

Transitions are recorded into the flight recorder (`slo_transition`
events) so `/debug/events?since=` tails them live, and `snapshot()`
feeds `GET /debug/slo`, the `dt_slo_*` prom gauges, and the serve-
bench / soak verdicts (a run that passes parity but leaves an
objective burning fails loudly — see serve/driver.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from .timeseries import TimeSeries

STATES = ("ok", "warning", "burning")


@dataclass
class Objective:
    """One latency SLO over a TimeSeries latency family."""

    name: str                 # stable id, e.g. "flush_p99"
    series: str               # TimeSeries family, e.g. "serve.flush"
    threshold_s: float        # per-event latency budget
    target: float = 0.99      # fraction that must be under threshold
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    fast_burn: float = 14.4   # page thresholds (SRE workbook defaults)
    slow_burn: float = 6.0

    def __post_init__(self) -> None:
        if not (0.0 < self.target < 1.0):
            raise ValueError(f"target must be in (0,1): {self.target}")


def default_objectives() -> List[Objective]:
    """The serving stack's standing objectives. Thresholds are set
    from the CPU-simulated bench envelope (BENCH_r01-r05: fused flush
    p99 ~2 s, cold-start p99 well under a second) with generous
    headroom so healthy soaks stay `ok` — the seeded latency-injection
    test uses tight custom objectives instead."""
    return [
        Objective("flush_p99", "serve.flush", threshold_s=30.0),
        Objective("queue_wait_p99", "serve.queue_wait", threshold_s=30.0),
        Objective("read_staleness_p99", "read.staleness",
                  threshold_s=30.0),
        Objective("hydration_cold_start_p99",
                  "serve.hydration_cold_start", threshold_s=30.0),
        Objective("quorum_round_p99", "repl.quorum_round",
                  threshold_s=10.0),
        # edit-to-visibility: fed by obs/journey.py on advert_usable
        # stamps (admitted -> follower-advert-usable lag per peer)
        Objective("visibility_p99", "journey.visibility",
                  threshold_s=30.0),
    ]


@dataclass
class _AlertState:
    state: str = "ok"
    transitions: int = 0


class SloEngine:
    """Evaluates objectives against a TimeSeries and runs the per-
    objective alert state machines. Evaluation is pull-driven (every
    /debug/slo, /metrics scrape, or verdict embed re-evaluates) — no
    background thread, no timers."""

    def __init__(self, ts: TimeSeries,
                 objectives: Optional[Sequence[Objective]] = None,
                 recorder=None) -> None:
        self.ts = ts
        self.objectives: List[Objective] = list(
            objectives if objectives is not None else default_objectives())
        self.recorder = recorder
        self._alerts: Dict[str, _AlertState] = {
            o.name: _AlertState() for o in self.objectives}

    # ---- evaluation -------------------------------------------------------

    def _burn(self, o: Objective, window_s: float) -> dict:
        bad, total = self.ts.count_over(o.series, o.threshold_s,
                                        window_s)
        budget = 1.0 - o.target
        frac = (bad / total) if total else 0.0
        return {"bad": bad, "total": total,
                "bad_fraction": round(frac, 6),
                "burn": round(frac / budget, 4)}

    def evaluate(self) -> List[dict]:
        """Re-evaluate every objective, advance the state machines,
        and return the per-objective rows."""
        rows = []
        for o in self.objectives:
            fast = self._burn(o, o.fast_window_s)
            slow = self._burn(o, o.slow_window_s)
            if (fast["burn"] >= o.fast_burn
                    and slow["burn"] >= o.slow_burn
                    and fast["total"] > 0):
                state = "burning"
            elif fast["burn"] >= 1.0 or slow["burn"] >= 1.0:
                state = "warning"
            else:
                state = "ok"
            al = self._alerts[o.name]
            if state != al.state:
                al.transitions += 1
                if self.recorder is not None:
                    self.recorder.record(
                        "slo_transition", objective=o.name,
                        series=o.series, frm=al.state, to=state,
                        fast_burn=fast["burn"], slow_burn=slow["burn"])
                al.state = state
            rows.append({
                "name": o.name, "series": o.series,
                "threshold_s": o.threshold_s, "target": o.target,
                "state": state, "transitions": al.transitions,
                "fast": fast, "slow": slow,
                "fast_window_s": o.fast_window_s,
                "slow_window_s": o.slow_window_s,
                "fast_burn_threshold": o.fast_burn,
                "slow_burn_threshold": o.slow_burn,
            })
        return rows

    # ---- snapshot / verdicts ---------------------------------------------

    def snapshot(self) -> dict:
        rows = self.evaluate()
        by_state = {s: 0 for s in STATES}
        for r in rows:
            by_state[r["state"]] += 1
        return {"version": 1, "enabled": self.ts.enabled,
                "objectives": rows, "by_state": by_state,
                "ok": by_state["burning"] == 0}

    def verdict(self) -> dict:
        """Compact block for bench/soak reports: `slo_ok` is False iff
        any objective is burning — parity can pass while the latency
        budget is torched, and that must fail the run."""
        snap = self.snapshot()
        burning = [r["name"] for r in snap["objectives"]
                   if r["state"] == "burning"]
        warning = [r["name"] for r in snap["objectives"]
                   if r["state"] == "warning"]
        return {"slo_ok": snap["ok"], "burning": burning,
                "warning": warning}
