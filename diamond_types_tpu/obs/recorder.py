"""Flight recorder: a bounded ring of structured events.

Counters say *how many* fencing rejections happened; the flight
recorder says *which docs, against which epochs, in what order* — the
last N interesting state transitions (lease moves, fencing rejections,
circuit opens, evictions, queue-bound violations) kept in memory and
dumped on demand via `GET /debug/events` or attached to a failing
soak/bench report. Events are tiny dicts with a monotone `seq` so a
dump is totally ordered even across readers.

Recording when disabled is a single flag check with no allocation —
the same zero-overhead contract as obs.trace.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional


class FlightRecorder:
    def __init__(self, capacity: int = 512, enabled: bool = True) -> None:
        self.capacity = max(int(capacity), 1)
        self.enabled = enabled
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._seq = 0
        self.recorded = 0

    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._seq += 1
            self.recorded += 1
            ev = {"seq": self._seq,
                  "t": round(time.monotonic(), 6),
                  "kind": kind}
            ev.update(fields)
            self._buf.append(ev)

    def dump(self, n: Optional[int] = None) -> list:
        with self._lock:
            evs = list(self._buf)
        return evs[-n:] if n else evs

    def dump_since(self, since: int) -> list:
        """Events with seq > since — incremental tailing for
        `GET /debug/events?since=` / `obs-watch` polling. The ring may
        have dropped events between `since` and the oldest buffered
        one; callers detect the gap when the first returned seq is not
        since + 1."""
        with self._lock:
            return [ev for ev in self._buf if ev["seq"] > since]

    def tail(self, n: int = 50) -> list:
        return self.dump(n)

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "capacity": self.capacity,
                    "recorded": self.recorded,
                    "buffered": len(self._buf),
                    "dropped": self.recorded - len(self._buf)}
