"""Cross-host trace assembly: clock alignment, span-tree merge,
stage waterfall, and critical-path analysis.

Span `t0` timestamps are per-host `time.monotonic()` readings — two
hosts' spans live on unrelated clocks and cannot be interleaved
directly. The assembler aligns them with ping-RTT offset estimation:
each `GET /debug/trace/<id>` fetch records the caller's send/receive
monotonic times around the request, and the response carries the
server's own `now`. Under the symmetric-RTT assumption the server
sampled `now` at the RTT midpoint, so

    offset = remote_now - (t_send + t_recv) / 2

maps every remote timestamp into the caller's clock (`local = remote
- offset`). The estimate is wrong by at most half the RTT asymmetry
plus jitter — small against cross-host replication lags, but not
zero, so after alignment a *monotonic repair* clamps every child's
start to be >= its parent's start: residual skew must never make an
effect precede its cause. Durations are host-local and never
adjusted.

The critical path walks the merged tree from the root, at each span
descending into the child whose interval ends last. Each span on the
path *owns* its duration minus its chosen child's — the telescoping
sum makes the owned segments add up to exactly the root's wall time,
so "which stage owns the edit-to-visibility wall clock" is an exact
decomposition, not a heuristic. A negative owned segment flags a
child that (after alignment) outlives its parent — residual clock
noise worth seeing, not hiding.

`replicate/faults.py`'s clock-skew bookkeeping (`set_clock_skew` /
`now(host)`) is the test seam: tests generate span sets on skewed
clocks and assert the assembly still orders stages monotonically.
"""

from __future__ import annotations

from typing import List, Optional


def estimate_offset(t_send: float, t_recv: float,
                    remote_now: float) -> float:
    """Offset of the remote monotonic clock relative to the caller's,
    assuming `remote_now` was sampled at the RTT midpoint."""
    return remote_now - 0.5 * (t_send + t_recv)


def align(fetches: List[dict]) -> List[dict]:
    """Flatten per-host span fetches onto one clock.

    Each fetch is `{"host", "spans", "now", "t_send", "t_recv"}` (or
    carries a precomputed `"offset_s"`). Returns copies of the spans
    with `host` and `_t0` (aligned start) added."""
    out = []
    for f in fetches:
        off = f.get("offset_s")
        if off is None:
            off = estimate_offset(f["t_send"], f["t_recv"], f["now"])
        for s in f.get("spans") or []:
            rec = dict(s)
            rec["host"] = f.get("host", "?")
            rec["_t0"] = s["t0"] - off
            out.append(rec)
    return out


def build_tree(spans: List[dict]):
    """Index the merged span set into (root, children, orphans) and
    apply the monotonic repair along parent->child edges."""
    by_id = {s["span"]: s for s in spans}
    children: dict = {}
    roots = []
    for s in spans:
        p = s.get("parent")
        if p and p in by_id:
            children.setdefault(p, []).append(s)
        else:
            roots.append(s)
    if not roots:
        return None, children, []
    roots.sort(key=lambda s: s["_t0"])
    root = roots[0]
    # monotonic repair: residual offset error must never order a child
    # before its parent (cause before effect). The `seen` guard keeps
    # a parent cycle (span-id collisions in a hand-fed or adversarial
    # fetch) from hanging the walk — the cycle degrades to a truncated
    # subtree instead.
    seen = {r["span"] for r in roots}
    for r in roots:
        stack = [r]
        while stack:
            node = stack.pop()
            kids = children.get(node["span"])
            if not kids:
                continue
            for k in kids:
                if k["_t0"] < node["_t0"]:
                    k["_t0"] = node["_t0"]
            kids.sort(key=lambda s: s["_t0"])
            fresh = [k for k in kids if k["span"] not in seen]
            seen.update(k["span"] for k in fresh)
            stack.extend(fresh)
    return root, children, roots[1:]


def critical_path(root: dict, children: dict) -> List[dict]:
    """Root-to-leaf chain through the latest-ending child at every
    step, with exact owned-time decomposition (sums to root wall)."""
    path = []
    seen = set()
    node = root
    while node is not None and node["span"] not in seen:
        seen.add(node["span"])
        path.append(node)
        kids = children.get(node["span"]) or []
        node = max(kids, key=lambda s: s["_t0"] + s["dur_s"]) \
            if kids else None
    segs = []
    for i, s in enumerate(path):
        nxt = path[i + 1] if i + 1 < len(path) else None
        owned = s["dur_s"] - (nxt["dur_s"] if nxt is not None else 0.0)
        segs.append({"name": s["name"], "host": s["host"],
                     "span": s["span"],
                     "t0_rel_s": round(s["_t0"] - root["_t0"], 6),
                     "dur_s": s["dur_s"],
                     "owned_s": round(owned, 6)})
    return segs


def _depths(root: dict, children: dict) -> dict:
    depth = {root["span"]: 0}
    stack = [root]
    while stack:
        node = stack.pop()
        for k in children.get(node["span"]) or []:
            if k["span"] in depth:
                continue        # cycle / duplicate id: keep first
            depth[k["span"]] = depth[node["span"]] + 1
            stack.append(k)
    return depth


def assemble_trace(trace_id: str, fetches: List[dict]) -> dict:
    """Merge per-host span fetches for one trace id into a single
    aligned tree with waterfall + critical path."""
    spans = [s for s in align(fetches) if s.get("trace") == trace_id]
    if not spans:
        return {"trace": trace_id, "spans": 0, "hosts": [],
                "waterfall": [], "critical_path": [], "wall_s": 0.0,
                "critical_path_s": 0.0, "root": None, "orphans": 0}
    root, children, orphans = build_tree(spans)
    depth = _depths(root, children)
    water = sorted(
        ({"name": s["name"], "host": s["host"], "span": s["span"],
          "parent": s.get("parent"),
          "depth": depth.get(s["span"], 0),
          "t0_rel_s": round(s["_t0"] - root["_t0"], 6),
          "dur_s": s["dur_s"],
          "attrs": s.get("attrs") or {}}
         for s in spans),
        key=lambda r: (r["t0_rel_s"], r["depth"]))
    cp = critical_path(root, children)
    return {"trace": trace_id,
            "root": {"name": root["name"], "host": root["host"]},
            "hosts": sorted({s["host"] for s in spans}),
            "spans": len(spans),
            "orphans": len(orphans),
            "wall_s": root["dur_s"],
            "waterfall": water,
            "critical_path": cp,
            "critical_path_s": round(sum(r["owned_s"] for r in cp), 6)}


def aggregate(reports: List[dict]) -> dict:
    """Aggregate critical-path ownership across traces: which
    (span name, host) owns the mesh's wall time overall."""
    owners: dict = {}
    total = 0.0
    for rep in reports:
        for seg in rep.get("critical_path") or []:
            key = (seg["name"], seg["host"])
            agg = owners.setdefault(key, {"owned_s": 0.0, "count": 0})
            agg["owned_s"] += seg["owned_s"]
            agg["count"] += 1
            total += seg["owned_s"]
    rows = [{"name": name, "host": host,
             "owned_s": round(agg["owned_s"], 6),
             "count": agg["count"],
             "share": round(agg["owned_s"] / total, 4)
             if total > 0 else 0.0}
            for (name, host), agg in owners.items()]
    rows.sort(key=lambda r: -r["owned_s"])
    return {"traces": len(reports), "total_owned_s": round(total, 6),
            "owners": rows}


def render_human(rep: dict, agg: Optional[dict] = None) -> str:
    """Human waterfall + critical path for `cli dt-trace`."""
    lines = []
    if rep["spans"] == 0:
        return f"trace {rep['trace']}: no spans found"
    lines.append(
        f"== trace {rep['trace']} ({rep['spans']} spans, "
        f"{len(rep['hosts'])} hosts, wall "
        f"{rep['wall_s'] * 1e3:.3f}ms"
        + (f", {rep['orphans']} orphans" if rep["orphans"] else "")
        + ") ==")
    for row in rep["waterfall"]:
        pad = "  " * row["depth"]
        lines.append(
            f"  {row['t0_rel_s'] * 1e3:9.3f}ms {pad}"
            f"{row['name']} @{row['host']} "
            f"{row['dur_s'] * 1e3:.3f}ms")
    lines.append(f"== critical path ({rep['critical_path_s'] * 1e3:.3f}"
                 f"ms of {rep['wall_s'] * 1e3:.3f}ms) ==")
    wall = max(rep["wall_s"], 1e-12)
    for seg in rep["critical_path"]:
        lines.append(
            f"  {seg['name']} @{seg['host']} owns "
            f"{seg['owned_s'] * 1e3:.3f}ms "
            f"({100.0 * seg['owned_s'] / wall:.1f}%)")
    if agg is not None:
        lines.append(f"== aggregated ownership "
                     f"({agg['traces']} traces) ==")
        for row in agg["owners"]:
            lines.append(
                f"  {row['name']} @{row['host']} owns "
                f"{row['owned_s'] * 1e3:.3f}ms "
                f"({100.0 * row['share']:.1f}% of "
                f"{agg['total_owned_s'] * 1e3:.3f}ms, "
                f"{row['count']} segments)")
    return "\n".join(lines)
