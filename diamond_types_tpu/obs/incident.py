"""Incident engine: online anomaly detection + auto-captured evidence.

The scenario harness can tell you a soak *failed*; by the time a human
looks, the flight recorder has wrapped and the "why" is gone. This
module is the flight-data-recorder analog of a training stack's
NaN-watchdog: `AnomalyDetector` watches the live TimeSeries ring for
deviations and, the moment one trips, `IncidentStore` freezes the
evidence that explains it — recorder tail, SLO burn rates, hot-doc
attribution, QoS controller state, per-peer convergence lag, open
journey stages, witness edge count, last sampled trace ids — into a
JSON bundle served at `GET /debug/incidents[/<id>]` and persisted
under the run's data dir.

Detector kinds (the declared schema surface — `INCIDENT_KINDS` is what
prom.py zero-fills `dt_incident_opened_total{kind}` from and what the
metrics-schema-drift lint rule checks literal kinds against):

  rate_stall   a series that was flowing (warmed past `warmup_polls`)
               goes silent for >= `stall_after_s` — e.g. `serve.flush`
               on a wedged scheduler, `convergence_lag.<peer>` behind
               a partition. Quiet-from-birth series never alarm; a
               fired stall re-arms only after the series flows again.
  rate_spike   current rate exceeds `spike_factor` x the trailing EWMA
               of an established series (warm-up gates the classic
               new-series false positive).
  p99_step     the short-window p99 of a latency family jumps past
               `p99_factor` x its trailing EWMA.
  slo_burn     an SLO objective transitioned to `burning` (the PR 10
               `slo_transition` flight-recorder events, tailed by
               cursor — no SloEngine coupling).

Detection is pull-driven like the SLO engine: `poll()` is invoked by
Observability.snapshot() (every /metrics scrape) and once per runner
tick — no threads, no timers. Dedup is by (kind, series) under a
`cooldown_s` window; a suppressed firing bumps `suppressed` instead of
opening a duplicate bundle.

Contracts shared with the rest of obs/:

  * disabled => allocation-free no-op (`poll()` is one branch; pinned
    by the tracemalloc test in tests/test_incident.py)
  * the clock is injectable (fake-clock detector matrix tests)
  * `_incident_lock` is a leaf in the canonical lock order: all
    TimeSeries / recorder / bundle-assembly reads happen OUTSIDE it —
    the lock only guards the detector's own state tables and the
    store's index (dt-lint classifies `_incident_lock` as leaf and the
    witness enforces it at runtime)
"""

from __future__ import annotations

import json
import os
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.witness import make_lock

# the declared incident-kind surface (prom zero-fill + lint drift rule)
INCIDENT_KINDS = ("rate_stall", "rate_spike", "p99_step", "slo_burn")

_EMPTY: tuple = ()


class AnomalyDetector:
    """Watches a TimeSeries ring for stalls / spikes / p99 steps and
    the flight recorder for SLO burn transitions. One per bundle."""

    def __init__(self, ts, recorder=None, store=None,
                 enabled: bool = True,
                 clock: Optional[Callable[[], float]] = None,
                 cooldown_s: float = 60.0,
                 rate_window_s: float = 30.0,
                 stall_after_s: float = 30.0,
                 spike_factor: float = 8.0,
                 p99_factor: float = 4.0,
                 ewma_alpha: float = 0.3,
                 warmup_polls: int = 3,
                 min_rate: float = 0.5,
                 min_p99_s: float = 0.001) -> None:
        self.enabled = enabled
        self.ts = ts
        self.recorder = recorder
        self.store = store
        self._clock = clock or time.monotonic
        self.cooldown_s = float(cooldown_s)
        self.rate_window_s = float(rate_window_s)
        self.stall_after_s = float(stall_after_s)
        self.spike_factor = float(spike_factor)
        self.p99_factor = float(p99_factor)
        self.ewma_alpha = float(ewma_alpha)
        self.warmup_polls = int(warmup_polls)
        self.min_rate = float(min_rate)
        self.min_p99_s = float(min_p99_s)
        self._incident_lock = make_lock("obs.incident", "leaf")
        self._flow: Dict[str, dict] = {}     # series -> rate state
        self._p99: Dict[str, dict] = {}      # series -> p99 state
        self._last: Dict[Tuple[str, str], float] = {}   # cooldown table
        self._rec_cursor = 0
        self.polls = 0
        self.suppressed = 0

    # ---- firing (cooldown dedup) ------------------------------------------

    def _open_locked(self, kind: str, series: str, now: float,
                     detail: dict, fired: List[tuple]) -> None:
        """Record one detection under the lock: dedup by (kind, series)
        inside the cooldown window, else queue it for capture. The
        `kind` literal at every call site is checked against
        INCIDENT_KINDS by the metrics-schema-drift lint rule."""
        key = (kind, series)
        last = self._last.get(key)
        if last is not None and now - last < self.cooldown_s:
            self.suppressed += 1
            return
        self._last[key] = now
        fired.append((kind, series, detail))

    # ---- the poll ---------------------------------------------------------

    def poll(self) -> tuple:
        """Re-evaluate every watched series; returns the (kind, series,
        detail) tuples that fired this poll (after cooldown dedup).
        Bundle capture happens here too, strictly outside the lock."""
        if not self.enabled:
            return _EMPTY
        now = self._clock()
        ts = self.ts
        # all ring reads happen BEFORE the incident lock: _ts_lock is
        # itself a leaf and may not nest under another leaf
        names = ts.names()
        rates = [(n, ts.rate(n, self.rate_window_s)) for n in names]
        p99s = [(n, ts.quantile(n, 0.99, self.rate_window_s))
                for n in names]
        transitions: List[dict] = []
        rec = self.recorder
        if rec is not None:
            evs = rec.dump_since(self._rec_cursor)
            if evs:
                self._rec_cursor = evs[-1]["seq"]
                transitions = [ev for ev in evs
                               if ev.get("kind") == "slo_transition"
                               and ev.get("to") == "burning"]
        fired: List[tuple] = []
        with self._incident_lock:
            self.polls += 1
            for name, rate in rates:
                st = self._flow.get(name)
                if st is None:
                    st = self._flow[name] = {
                        "ewma": 0.0, "warm": 0,
                        "last_flow": now, "flowing": False}
                if rate > 0.0:
                    if (st["warm"] >= self.warmup_polls
                            and st["ewma"] > 0.0
                            and rate >= self.min_rate
                            and rate > self.spike_factor * st["ewma"]):
                        self._open_locked(
                            "rate_spike", name, now,
                            {"rate": round(rate, 6),
                             "ewma": round(st["ewma"], 6),
                             "factor": self.spike_factor}, fired)
                    st["ewma"] = rate if st["ewma"] == 0.0 else (
                        self.ewma_alpha * rate
                        + (1.0 - self.ewma_alpha) * st["ewma"])
                    st["warm"] += 1
                    st["last_flow"] = now
                    st["flowing"] = True
                elif (st["flowing"] and st["warm"] >= self.warmup_polls
                        and st["ewma"] >= self.min_rate
                        and now - st["last_flow"] >= self.stall_after_s):
                    self._open_locked(
                        "rate_stall", name, now,
                        {"silent_s": round(now - st["last_flow"], 3),
                         "ewma": round(st["ewma"], 6)}, fired)
                    st["flowing"] = False   # re-arm only on new flow
            for name, p99 in p99s:
                if p99 <= 0.0:
                    continue
                st = self._p99.get(name)
                if st is None:
                    st = self._p99[name] = {"ewma": 0.0, "warm": 0}
                if (st["warm"] >= self.warmup_polls
                        and st["ewma"] > 0.0
                        and p99 >= self.min_p99_s
                        and p99 > self.p99_factor * st["ewma"]):
                    self._open_locked(
                        "p99_step", name, now,
                        {"p99_s": round(p99, 6),
                         "ewma_s": round(st["ewma"], 6),
                         "factor": self.p99_factor}, fired)
                st["ewma"] = p99 if st["ewma"] == 0.0 else (
                    self.ewma_alpha * p99
                    + (1.0 - self.ewma_alpha) * st["ewma"])
                st["warm"] += 1
            for ev in transitions:
                self._open_locked(
                    "slo_burn", str(ev.get("objective", "?")), now,
                    {"series": ev.get("series"),
                     "frm": ev.get("frm"), "to": ev.get("to"),
                     "fast_burn": ev.get("fast_burn"),
                     "slow_burn": ev.get("slow_burn")}, fired)
        store = self.store
        if store is not None:
            for kind, series, detail in fired:
                store.open_incident(kind, series, detail)
        return tuple(fired)

    def snapshot(self) -> dict:
        with self._incident_lock:
            return {"enabled": self.enabled, "polls": self.polls,
                    "suppressed": self.suppressed,
                    "watched": len(self._flow)}


class IncidentStore:
    """Bounded in-memory index of incident bundles + JSON persistence.

    A bundle freezes everything a postmortem needs at detection time.
    Assembly reads the other obs structures through their own (leaf)
    locks, strictly OUTSIDE `_incident_lock`; only the index mutation
    runs under it. `kind` is validated against INCIDENT_KINDS — an
    undeclared kind raises, the ReadMetrics contract."""

    def __init__(self, data_dir: Optional[str] = None,
                 capacity: int = 64, prefix: str = "",
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.data_dir = data_dir
        self.capacity = max(int(capacity), 1)
        self.prefix = prefix
        self._clock = clock or time.monotonic
        self._incident_lock = make_lock("obs.incident_store", "leaf")
        self._bundles: "OrderedDict[str, dict]" = OrderedDict()
        self._seq = 0
        self._acked: set = set()
        self.persisted = 0
        self.obs = None              # back-ref, set by attach()
        self.qos_provider = None     # () -> qos export dict or None
        self.context_provider = None  # () -> extra capture context
        self._by_kind: Dict[str, int] = dict.fromkeys(INCIDENT_KINDS, 0)

    def attach(self, obs) -> None:
        self.obs = obs

    # ---- capture ----------------------------------------------------------

    def _capture(self) -> dict:
        """Assemble the evidence snapshot (no incident lock held)."""
        obs = self.obs
        cap: dict = {}
        if obs is None:
            return cap
        cap["recorder_tail"] = obs.recorder.tail(100)
        slo_rows = obs.slo.evaluate()
        cap["slo"] = [{"name": r["name"], "state": r["state"],
                       "fast_burn": r["fast"]["burn"],
                       "slow_burn": r["slow"]["burn"]}
                      for r in slo_rows]
        cap["hot"] = obs.attrib.snapshot(top=5)
        cap["convergence_lag"] = obs.journey.lag_summary()
        jo = obs.journey.snapshot()
        cap["journey_stages"] = jo.get("stages")
        cap["journeys_tracked"] = jo.get("tracked")
        from ..analysis import witness_snapshot
        wit = witness_snapshot()
        cap["witness_edges"] = len(wit.get("edges") or {})
        cap["traces"] = [row.get("trace")
                         for row in obs.tracer.index(limit=5)]
        qp = self.qos_provider
        if qp is not None:
            try:
                cap["qos"] = qp()
            except Exception:
                cap["qos"] = None
        ctx = self.context_provider
        if ctx is not None:
            try:
                cap["context"] = ctx()
            except Exception:
                cap["context"] = None
        return cap

    def open_incident(self, kind: str, series: str,
                      detail: Optional[dict] = None) -> dict:
        if kind not in INCIDENT_KINDS:
            raise ValueError(f"undeclared incident kind {kind!r} "
                             f"(INCIDENT_KINDS={INCIDENT_KINDS})")
        cap = self._capture()
        now = self._clock()
        with self._incident_lock:
            self._seq += 1
            iid = f"inc-{self.prefix}{self._seq:04d}"
            bundle = {"version": 1, "id": iid, "t": round(now, 6),
                      "kind": kind, "series": series,
                      "detail": dict(detail or {}), **cap}
            self._bundles[iid] = bundle
            self._by_kind[kind] += 1
            while len(self._bundles) > self.capacity:
                old, _ = self._bundles.popitem(last=False)
                self._acked.discard(old)
        self._persist(iid, bundle)
        obs = self.obs
        if obs is not None:
            obs.recorder.record("incident_opened", id=iid,
                                incident_kind=kind, series=series)
        return bundle

    def _persist(self, iid: str, bundle: dict) -> None:
        if self.data_dir is None:
            return
        try:
            root = os.path.join(self.data_dir, "incidents")
            os.makedirs(root, exist_ok=True)
            path = os.path.join(root, f"{iid}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf8") as f:
                f.write(json.dumps(bundle, default=str) + "\n")
            os.replace(tmp, path)
            self.persisted += 1
        except OSError:
            pass    # persistence is best-effort evidence, never fatal

    # ---- views ------------------------------------------------------------

    def ack(self, iid: str) -> bool:
        with self._incident_lock:
            if iid not in self._bundles:
                return False
            self._acked.add(iid)
            return True

    def get(self, iid: str) -> Optional[dict]:
        with self._incident_lock:
            b = self._bundles.get(iid)
            return dict(b) if b is not None else None

    def index_json(self) -> dict:
        with self._incident_lock:
            rows = [{"id": b["id"], "t": b["t"], "kind": b["kind"],
                     "series": b["series"], "detail": b["detail"],
                     "acknowledged": b["id"] in self._acked}
                    for b in self._bundles.values()]
            rows.reverse()          # newest first
            last_id = next(reversed(self._bundles)) \
                if self._bundles else None
            return {"version": 1, "total": self._seq,
                    "open": sum(1 for r in rows
                                if not r["acknowledged"]),
                    "by_kind": dict(self._by_kind),
                    "last_id": last_id,
                    "incidents": rows}

    def snapshot(self) -> dict:
        with self._incident_lock:
            last_id = next(reversed(self._bundles)) \
                if self._bundles else None
            return {"total": self._seq,
                    "open": sum(1 for i in self._bundles
                                if i not in self._acked),
                    "by_kind": dict(self._by_kind),
                    "last_id": last_id,
                    "persisted": self.persisted}
