"""Edit-to-visibility journey tracking (the convergence waterfall).

The per-stage dashboards (flush p99, queue wait, AE round time, read
staleness) each measure one machine; the product metric of a CRDT mesh
is *edit-to-visibility* — how long until an accepted edit is durable,
replicated, and servable from every follower. `OpJourney` stamps each
sampled edit as it crosses the pipeline stages:

  admitted        HTTP ingress accepted the edit (agent/seq known)
  queued          admission queue took the merge intent
  planned         flush planning produced an op schedule (host or
                  device rung — the rung shows on the trace spans)
  device_replayed the fused/mesh/pallas device phase replayed the tail
                  (host-engine flushes skip this stamp by design)
  adopted         the merge result was adopted into the session/oplog
  wal_durable     DocStore persisted the doc (atomic tmp+rename)
  ae_shipped      anti-entropy pushed the patch at a peer
  applied_at_peer the peer acknowledged applying the pushed patch
  advert_usable   the peer's frontier advert came back dominating the
                  edit — a follower read can now be served from it

Journeys are keyed by the edit's `X-DT-Trace` id when the ingress span
was sampled (falling back to `agent:seq`), carry the `(agent, seq)`
identity and doc id, and live in a bounded FIFO table. Only the
*owner* stamps: peer-side facts (shipped/applied/advert) are stamped
when the owner observes them, so the whole journey assembles on one
host without a cross-host table. Stage counters are zero-filled over
`STAGES` — prom and the dataflow lint key off the same tuple.

On `advert_usable` the per-peer convergence lag (stamp time minus
`admitted`) is double-written into the live TimeSeries as
`convergence_lag.{peer}` and the aggregate `journey.visibility` — the
family the `visibility_p99` SLO objective burns on.

Disabled journeys are a hard no-op: every public method checks one
flag and returns without allocating (tracemalloc-pinned, same contract
as the disabled tracer/TimeSeries). The internal lock is a leaf —
stamps arrive under shard/oplog/io locks and must never wrap blocking
work; TimeSeries writes happen after the lock is released.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Optional

STAGES = ("admitted", "queued", "planned", "device_replayed", "adopted",
          "wal_durable", "ae_shipped", "applied_at_peer",
          "advert_usable")

# stages observed about a specific peer (stamped with peer=...)
PEER_STAGES = ("ae_shipped", "applied_at_peer", "advert_usable")

# TimeSeries families the journey double-writes (the SLO objective and
# prom exemplar join read these names)
VISIBILITY_SERIES = "journey.visibility"
CONVERGENCE_PREFIX = "convergence_lag"


class OpJourney:
    """Bounded edit-journey table + per-peer convergence-lag rollup."""

    def __init__(self, capacity: int = 512, ts=None,
                 enabled: bool = True, clock=None) -> None:
        self.enabled = enabled
        self.capacity = max(int(capacity), 1)
        self.ts = ts
        self._clock = time.monotonic if clock is None else clock
        from ..analysis.witness import make_lock
        self._lock = make_lock("obs.journey", "leaf")
        self._journeys: OrderedDict = OrderedDict()  # key -> entry
        self._by_doc: dict = {}                      # doc -> set(keys)
        self._stage_counts = dict.fromkeys(STAGES, 0)
        self._peer_lags: dict = {}   # peer -> {"n","sum","max"}
        self.stamped = 0
        self.dropped = 0

    # ---- stamping ---------------------------------------------------------

    def begin(self, agent, seq, doc=None, trace=None,
              t: Optional[float] = None) -> Optional[str]:
        """Open a journey at the `admitted` stage. Returns the journey
        key (the trace id when the ingress span was sampled, else
        `agent:seq`). First begin wins: a later begin for the same key
        (the scheduler re-announcing an ingress-admitted edit) is a
        no-op, so the HTTP handler's (agent, seq) identity sticks."""
        if not self.enabled:
            return None
        key = trace if trace else f"{agent}:{seq}"
        now = self._clock() if t is None else t
        with self._lock:
            if key in self._journeys:
                return key
            entry = {"trace": trace, "agent": agent, "seq": seq,
                     "doc": doc, "t_admitted": now,
                     "stages": {"admitted": now}, "peers": {}}
            self._journeys[key] = entry
            if doc is not None:
                self._by_doc.setdefault(doc, set()).add(key)
            while len(self._journeys) > self.capacity:
                old_key, old = self._journeys.popitem(last=False)
                self.dropped += 1
                keys = self._by_doc.get(old.get("doc"))
                if keys is not None:
                    keys.discard(old_key)
                    if not keys:
                        self._by_doc.pop(old.get("doc"), None)
            self._stage_counts["admitted"] += 1
            self.stamped += 1
        return key

    def stamp(self, key, stage: str, peer: Optional[str] = None,
              t: Optional[float] = None) -> None:
        """Stamp one journey by key (trace id or `agent:seq`)."""
        if not self.enabled:
            return
        self._record((key,), stage, peer, t)

    def stamp_doc(self, doc, stage: str, peer: Optional[str] = None,
                  t: Optional[float] = None) -> None:
        """Stamp every in-flight journey of `doc` — the WAL flush, AE
        ship/apply and advert paths know the doc, not the trace."""
        if not self.enabled:
            return
        with self._lock:
            keys = tuple(self._by_doc.get(doc, ()))
        if keys:
            self._record(keys, stage, peer, t)

    def _record(self, keys, stage, peer, t) -> None:
        now = self._clock() if t is None else t
        observations = []   # (peer, lag) flushed to ts OUTSIDE the lock
        with self._lock:
            for key in keys:
                entry = self._journeys.get(key)
                if entry is None:
                    continue
                if peer is not None:
                    slots = entry["peers"].setdefault(peer, {})
                else:
                    slots = entry["stages"]
                if stage in slots:
                    continue            # first stamp wins
                if (stage == "advert_usable" and peer is not None
                        and "applied_at_peer" not in slots):
                    # an advert that predates the peer applying this
                    # edit proves nothing about ITS visibility — skip
                    # until the AE push acked (first-wins then takes
                    # the first post-apply advert)
                    continue
                slots[stage] = now
                self._stage_counts[stage] = \
                    self._stage_counts.get(stage, 0) + 1
                self.stamped += 1
                if stage == "advert_usable" and peer is not None:
                    lag = max(now - entry["t_admitted"], 0.0)
                    agg = self._peer_lags.setdefault(
                        peer, {"n": 0, "sum": 0.0, "max": 0.0})
                    agg["n"] += 1
                    agg["sum"] += lag
                    agg["max"] = max(agg["max"], lag)
                    observations.append((peer, lag))
        ts = self.ts
        if ts is not None:
            for peer_id, lag in observations:
                ts.observe(f"{CONVERGENCE_PREFIX}.{peer_id}", lag)
                ts.observe(VISIBILITY_SERIES, lag)

    # ---- views ------------------------------------------------------------

    def journey(self, key) -> Optional[dict]:
        """Deep-enough copy of one journey (stage map + per-peer map)."""
        with self._lock:
            entry = self._journeys.get(key)
            if entry is None:
                return None
            return {"trace": entry["trace"], "agent": entry["agent"],
                    "seq": entry["seq"], "doc": entry["doc"],
                    "stages": dict(entry["stages"]),
                    "peers": {p: dict(s)
                              for p, s in entry["peers"].items()}}

    def waterfall(self, key) -> list:
        """Ordered [(stage, offset_s, peer)] rows for one journey —
        offsets are relative to `admitted`."""
        j = self.journey(key)
        if j is None:
            return []
        t0 = j["stages"].get("admitted", 0.0)
        rows = [(stage, round(t - t0, 6), None)
                for stage, t in j["stages"].items()]
        for peer_id, slots in j["peers"].items():
            rows.extend((stage, round(t - t0, 6), peer_id)
                        for stage, t in slots.items())
        rows.sort(key=lambda r: (r[1], STAGES.index(r[0])))
        return rows

    def lag_summary(self) -> dict:
        """Per-peer convergence-lag rollup — the soak-verdict column."""
        with self._lock:
            return {peer: {"n": agg["n"],
                           "mean_s": round(agg["sum"] / agg["n"], 6)
                           if agg["n"] else 0.0,
                           "max_s": round(agg["max"], 6)}
                    for peer, agg in sorted(self._peer_lags.items())}

    def snapshot(self) -> dict:
        with self._lock:
            stages = {s: self._stage_counts.get(s, 0) for s in STAGES}
            convergence = {
                peer: {"n": agg["n"],
                       "mean_s": round(agg["sum"] / agg["n"], 6)
                       if agg["n"] else 0.0,
                       "max_s": round(agg["max"], 6)}
                for peer, agg in sorted(self._peer_lags.items())}
            return {"version": 1,
                    "enabled": self.enabled,
                    "tracked": len(self._journeys),
                    "stamped": self.stamped,
                    "dropped": self.dropped,
                    "stages": stages,
                    "convergence": convergence}
