"""Hot-doc / hot-agent attribution via a space-saving top-K sketch.

"Which doc is burning the device budget this minute" needs per-doc
counters, but per-doc prom series are a cardinality bomb at millions
of docs. The space-saving sketch (Metwally et al.) keeps exactly K
slots per dimension: a hit on a tracked key increments it; a miss on a
full table evicts the minimum-count key and inherits its count as the
new key's error bound. Guarantees: any key with true count >
total/K is present, and every reported count overestimates truth by at
most the reported `err` — good enough to rank rebalancing and
follower-read-placement candidates, which is all this feeds.

Dimensions tracked (each per-doc and per-agent):

    ops           merged CRDT ops (scheduler flush path)
    bytes         request body bytes (server POST handlers)
    device_s      per-flush device seconds, split over the bucket docs
    cache_misses  hydration sync-points + checkout-cache misses

Surfaced at `GET /debug/hot` and as bounded `dt_hot_*` prom series
(top-N only, N << K). `_sketch_lock` is a leaf lock: note() calls run
under shard locks in the flush path.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..analysis.witness import make_lock

KINDS = ("ops", "bytes", "device_s", "cache_misses")
DIMS = ("doc", "agent")


class SpaceSaving:
    """Metwally space-saving heavy-hitter sketch, float-weighted.
    NOT thread-safe — the owning HotAttribution's lock guards it."""

    __slots__ = ("k", "counts", "errs", "total")

    def __init__(self, k: int) -> None:
        self.k = k
        self.counts: Dict[str, float] = {}
        self.errs: Dict[str, float] = {}
        self.total = 0.0

    def offer(self, key: str, n: float = 1.0) -> None:
        self.total += n
        if key in self.counts:
            self.counts[key] += n
            return
        if len(self.counts) < self.k:
            self.counts[key] = n
            self.errs[key] = 0.0
            return
        victim = min(self.counts, key=self.counts.__getitem__)
        floor = self.counts.pop(victim)
        self.errs.pop(victim, None)
        self.counts[key] = floor + n
        self.errs[key] = floor

    def top(self, n: int) -> List[Tuple[str, float, float]]:
        """[(key, count, err)] — count overestimates truth by <= err."""
        rows = sorted(self.counts.items(), key=lambda kv: -kv[1])[:n]
        return [(k, round(c, 6), round(self.errs.get(k, 0.0), 6))
                for k, c in rows]


class HotAttribution:
    """One sketch per (dimension, kind); bounded memory regardless of
    doc/agent cardinality. Disabled => one branch, no allocation."""

    def __init__(self, k: int = 64, enabled: bool = True) -> None:
        self.enabled = enabled
        self.k = k
        self.noted = 0
        self._sketch_lock = make_lock("obs.attrib", "leaf")
        self._sketches: Dict[Tuple[str, str], SpaceSaving] = {
            (dim, kind): SpaceSaving(k)
            for dim in DIMS for kind in KINDS}

    def note(self, kind: str, doc: str = None, agent: str = None,
             n: float = 1.0) -> None:
        if not self.enabled or n <= 0.0:
            return
        with self._sketch_lock:
            if doc is not None:
                self._sketches[("doc", kind)].offer(doc, n)
            if agent is not None:
                self._sketches[("agent", kind)].offer(agent, n)
            self.noted += 1

    def top(self, dim: str, kind: str,
            n: int = 10) -> List[Tuple[str, float, float]]:
        with self._sketch_lock:
            return self._sketches[(dim, kind)].top(n)

    def snapshot(self, top: int = 10) -> dict:
        out: dict = {"version": 1, "enabled": self.enabled,
                     "k": self.k, "noted": self.noted}
        with self._sketch_lock:
            for dim in DIMS:
                block = out[dim] = {}
                for kind in KINDS:
                    sk = self._sketches[(dim, kind)]
                    block[kind] = {
                        "total": round(sk.total, 6),
                        "tracked": len(sk.counts),
                        "top": [list(r) for r in sk.top(top)],
                    }
        return out
