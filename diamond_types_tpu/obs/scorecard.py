"""Versioned per-scenario scorecards + one-diff regression detection.

`build_scorecard` normalizes a scenario run's raw collections into a
stable, versioned JSON document; `diff_scorecards` compares two of
them metric-by-metric against per-metric tolerance bands and is the
engine behind `cli scorecard-diff old new --gate` (exit non-zero on
regression), which is what makes BASELINE.md scenario rows
machine-checkable.

Band semantics: every gated metric declares a direction ("lower" or
"higher" is better). A change in the good direction always passes; a
change in the bad direction passes only while within BOTH the
relative band (`rel`, fraction of the old value) and the absolute
slack (`abs`, which keeps tiny-latency jitter from tripping
percentage bands). Metrics missing from either scorecard are reported
but never gate — new columns must not fail old baselines.

The module also parks the live run snapshot (`publish_scenario` /
`last_scenario`): the runner publishes each tick, Observability.
snapshot() embeds it as the `scenario` block, and `cli obs-watch`
renders it as the scenario panel.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

SCORECARD_VERSION = 1


# ---- live snapshot (obs-watch scenario panel) ----------------------------

_LAST_SCENARIO: Optional[dict] = None


def publish_scenario(snap: Optional[dict]) -> None:
    """Park the runner's live snapshot (name, phase, progress, SLO
    verdict) for the obs pipeline; None clears it (run finished)."""
    global _LAST_SCENARIO
    _LAST_SCENARIO = dict(snap) if snap is not None else None


def last_scenario() -> Optional[dict]:
    return _LAST_SCENARIO


# ---- scorecard assembly --------------------------------------------------

def build_scorecard(*, scenario: dict, wall_s: float, virtual_s: float,
                    totals: Dict[str, float],
                    latency_p99_s: Dict[str, float],
                    latencies: Optional[dict] = None,
                    slo: Optional[dict] = None,
                    burn_minutes: Optional[Dict[str, float]] = None,
                    convergence: Optional[dict] = None,
                    hydration: Optional[Dict[str, int]] = None,
                    wire: Optional[Dict[str, Dict[str, float]]] = None,
                    per_server: Optional[List[dict]] = None,
                    ok: bool = True,
                    qos: Optional[dict] = None,
                    incidents: Optional[dict] = None,
                    serve: Optional[dict] = None,
                    extra: Optional[dict] = None) -> dict:
    """Assemble the stable scorecard document. Derived ratios
    (throughput, bytes/op) are computed here so every producer agrees
    on their definition."""
    ops = float(totals.get("ops", 0))
    reads = float(totals.get("reads", 0))
    writes = float(totals.get("writes", 0))
    bytes_total = float(totals.get("bytes_sent", 0)
                        + totals.get("bytes_received", 0))
    wall = max(float(wall_s), 1e-9)
    card = {
        "version": SCORECARD_VERSION,
        "scenario": dict(scenario),
        "wall_s": round(float(wall_s), 3),
        "virtual_s": round(float(virtual_s), 3),
        "totals": {k: totals[k] for k in sorted(totals)},
        "throughput": {
            "ops_per_s": round(ops / wall, 3),
            "writes_per_s": round(writes / wall, 3),
            "reads_per_s": round(reads / wall, 3),
        },
        "latency_p99_s": {k: (None if v is None else round(float(v), 6))
                          for k, v in sorted(latency_p99_s.items())},
        "slo": dict(slo or {}),
        "burn_minutes": {k: round(float(v), 4) for k, v in
                         sorted((burn_minutes or {}).items())},
        "convergence": dict(convergence or {}),
        "bytes_per_op": round(bytes_total / max(ops, 1.0), 2),
        "hydration": {k: int(v) for k, v in
                      sorted((hydration or {}).items())},
        "ok": bool(ok),
    }
    card["burn_minutes_total"] = round(
        sum(card["burn_minutes"].values()), 4)
    if wire is not None:
        # mesh-transport accounting per wire channel (raw counters
        # summed across servers by the runner); bytes_per_op derived
        # HERE so every producer divides by the same op count
        card["wire"] = {
            ch: {
                "bytes_sent": int(vals.get("bytes_sent", 0)),
                "bytes_saved": int(vals.get("bytes_saved", 0)),
                "frames": int(vals.get("frames", 0)),
                "snapshot_ships": int(vals.get("snapshot_ships", 0)),
                "bytes_per_op": round(
                    float(vals.get("bytes_sent", 0)) / max(ops, 1.0), 2),
            }
            for ch, vals in sorted(wire.items())
        }
    if qos is not None:
        # adaptive-admission block (merged QosMetrics snapshot). Absent
        # on static-admission runs so pre-QoS baselines diff clean; no
        # band gates on it — shed counts are policy, not regressions.
        card["qos"] = dict(qos)
    if incidents is not None:
        # incident engine rollup (count by kind, worst burn-minutes
        # bundle id, timeline). Absent from pre-incident baselines so
        # they diff clean; `incidents.count` is band-gated.
        card["incidents"] = dict(incidents)
    if serve is not None:
        # device flush-pipeline block (shape steering + staging): jit
        # hit rate, staged bytes per mesh window, dispatch fan-in.
        # Absent on host-engine runs (and pre-steer baselines) so the
        # new bands skip instead of gating — missing-path semantics.
        card["serve"] = dict(serve)
    if latencies is not None:
        card["latencies"] = latencies
    if per_server is not None:
        card["per_server"] = per_server
    if extra:
        card["extra"] = extra
    return card


# ---- tolerance bands -----------------------------------------------------

@dataclass(frozen=True)
class Band:
    """Tolerance band for one metric path. `better` names the good
    direction; `rel`/`abs_` bound how far the BAD direction may move
    before the gate trips (the larger of the two wins, so abs_ is the
    jitter floor for near-zero metrics)."""

    better: str          # "higher" | "lower"
    rel: float = 0.25
    abs_: float = 0.0

    def allows(self, old: float, new: float) -> bool:
        delta = new - old
        if self.better == "higher":
            delta = -delta          # normalize: positive = worse
        if delta <= 0:
            return True             # unchanged or improved
        return delta <= max(abs(old) * self.rel, self.abs_)


# Gated metric paths (dotted into the scorecard). Deliberately a
# curated list, not "every numeric leaf": config echoes, histograms
# and per-server detail are context, not gates.
DEFAULT_BANDS: Dict[str, Band] = {
    "throughput.ops_per_s": Band("higher", rel=0.30, abs_=5.0),
    "throughput.reads_per_s": Band("higher", rel=0.35, abs_=5.0),
    "throughput.writes_per_s": Band("higher", rel=0.35, abs_=2.0),
    "latency_p99_s.flush": Band("lower", rel=0.50, abs_=0.010),
    "latency_p99_s.read": Band("lower", rel=0.50, abs_=0.010),
    "latency_p99_s.visibility": Band("lower", rel=0.50, abs_=0.025),
    "burn_minutes_total": Band("lower", rel=0.0, abs_=1.0),
    "bytes_per_op": Band("lower", rel=0.30, abs_=128.0),
    "totals.errors": Band("lower", rel=0.0, abs_=0.0),
    "hydration.spills_to_snapshot": Band("lower", rel=1.0, abs_=32.0),
    "hydration.spill_bytes": Band("lower", rel=1.0, abs_=262144.0),
    "hydration.quarantined": Band("lower", rel=0.0, abs_=0.0),
    "hydration.flush_leaks": Band("lower", rel=0.0, abs_=0.0),
    # wire tier: per-channel transport cost. Absent from pre-wire (or
    # single-server) scorecards — missing paths report but never gate.
    "wire.antientropy.bytes_per_op": Band("lower", rel=0.30, abs_=16.0),
    "wire.proxy.bytes_per_op": Band("lower", rel=0.30, abs_=16.0),
    "wire.hydrate.bytes_per_op": Band("lower", rel=0.30, abs_=16.0),
    "wire.gossip.bytes_per_op": Band("lower", rel=0.30, abs_=16.0),
    # incident engine: more auto-captured incidents than the baseline
    # is a health regression even when the boolean gates still pass.
    # Generous absolute slack — a chaos tape legitimately opens a few.
    "incidents.count": Band("lower", rel=0.5, abs_=4.0),
    # device flush pipeline (shape steering + device-resident staging,
    # scorecard `serve` block): hit rate must not drop more than 5
    # points; staged bytes per window must not grow past the band;
    # dispatch fan-in (device calls per window) must not balloon.
    # Absent entirely on host-engine scorecards — never gates there.
    # The staged band is sized to catch STATE-staging regressions
    # (losing device residency multiplies the figure ~5x), while
    # letting steering's padded plan arrays through — padding a window
    # up to a warm class grows the host-built plan upload by up to
    # `max_waste` (4x cells) by design, and that is the trade the
    # steer A/B makes on purpose (plan kilobytes for compile seconds).
    "serve.jit_cache_hit_rate": Band("higher", rel=0.0, abs_=0.05),
    "serve.staged_bytes_per_window": Band("lower", rel=1.0,
                                          abs_=16384.0),
    "serve.device_calls_per_window": Band("lower", rel=0.50, abs_=1.0),
}

# Boolean invariants: must never flip good -> bad.
_BOOL_GATES = ("ok", "convergence.converged", "slo.slo_ok")


def _dig(doc: dict, path: str):
    cur = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def diff_scorecards(old: dict, new: dict,
                    bands: Optional[Dict[str, Band]] = None) -> dict:
    """Compare two scorecards. `ok` is False iff any gated metric
    moved in its bad direction past its band, or a boolean invariant
    flipped false. Metrics absent from either side are listed under
    `skipped` and never gate."""
    bands = bands if bands is not None else DEFAULT_BANDS
    rows: List[dict] = []
    skipped: List[str] = []
    for path, band in sorted(bands.items()):
        o, n = _dig(old, path), _dig(new, path)
        if not isinstance(o, (int, float)) or isinstance(o, bool) \
                or not isinstance(n, (int, float)) \
                or isinstance(n, bool):
            skipped.append(path)
            continue
        ok = band.allows(float(o), float(n))
        rows.append({
            "metric": path, "old": o, "new": n,
            "delta": round(float(n) - float(o), 6),
            "better": band.better,
            "band": {"rel": band.rel, "abs": band.abs_},
            "ok": ok,
        })
    for path in _BOOL_GATES:
        o, n = _dig(old, path), _dig(new, path)
        if not isinstance(o, bool) or not isinstance(n, bool):
            skipped.append(path)
            continue
        rows.append({"metric": path, "old": o, "new": n,
                     "delta": None, "better": "true",
                     "band": None, "ok": not (o and not n)})
    regressions = [r["metric"] for r in rows if not r["ok"]]
    return {
        "version": {"old": old.get("version"),
                    "new": new.get("version")},
        "scenario": {"old": _dig(old, "scenario.name"),
                     "new": _dig(new, "scenario.name")},
        "rows": rows,
        "skipped": skipped,
        "regressions": regressions,
        "ok": not regressions,
    }


def render_diff(diff: dict) -> str:
    """Human-readable diff table (the non-JSON CLI output)."""
    lines = [f"scorecard-diff: {diff['scenario']['old']} -> "
             f"{diff['scenario']['new']}  "
             f"[{'OK' if diff['ok'] else 'REGRESSION'}]"]
    for r in diff["rows"]:
        mark = "ok" if r["ok"] else "FAIL"
        lines.append(f"  [{mark:4}] {r['metric']:36} "
                     f"{r['old']} -> {r['new']}")
    if diff["skipped"]:
        lines.append("  (not gated — missing on one side: "
                     + ", ".join(diff["skipped"]) + ")")
    return "\n".join(lines)
