"""Device-time profiling: wall vs. device seconds per flush, compile
cache hit/miss counts, and host<->device transfer bytes.

A process-wide `PROFILER` singleton (disabled by default) keeps the
hooks in tpu/zone_session.py and serve/bank.py down to one attribute
check when profiling is off — the jit-cache lookup path must not pay
for observability it isn't using. serve/driver.py enables it for
bench runs so `bench_serve_sched` can report how much of each flush
was actual `block_until_ready` device time versus host bookkeeping,
which is the measurement ROADMAP item (c)'s fused-flush claim needs.
"""

from __future__ import annotations

import threading
from typing import Dict


class DeviceProfiler:
    def __init__(self, enabled: bool = False) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._jit: Dict[str, list] = {}
        self._shard: Dict[int, dict] = {}
        self.transfers = 0
        self.transfer_bytes = 0

    def reset(self) -> None:
        with self._lock:
            self._jit = {}
            self._shard = {}
            self.transfers = 0
            self.transfer_bytes = 0

    def note_jit(self, cache: str, hit: bool) -> None:
        if not self.enabled:
            return
        with self._lock:
            c = self._jit.setdefault(cache, [0, 0])
            c[0 if hit else 1] += 1

    def observe_flush(self, shard: int, wall_s: float,
                      device_s: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            s = self._shard.setdefault(
                int(shard), {"flushes": 0, "wall_s": 0.0, "device_s": 0.0})
            s["flushes"] += 1
            s["wall_s"] += wall_s
            s["device_s"] += device_s

    def note_transfer(self, nbytes: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.transfers += 1
            self.transfer_bytes += int(nbytes)

    def snapshot(self) -> dict:
        with self._lock:
            jit = {k: {"hits": v[0], "misses": v[1]}
                   for k, v in sorted(self._jit.items())}
            per_shard = {
                str(k): {"flushes": v["flushes"],
                         "wall_s": round(v["wall_s"], 6),
                         "device_s": round(v["device_s"], 6)}
                for k, v in sorted(self._shard.items())}
            wall = sum(v["wall_s"] for v in self._shard.values())
            dev = sum(v["device_s"] for v in self._shard.values())
            return {"enabled": self.enabled,
                    "jit_cache": jit,
                    "flush_wall_s": round(wall, 6),
                    "device_sync_s": round(dev, 6),
                    "device_fraction": round(dev / wall, 4) if wall else 0.0,
                    "transfers": self.transfers,
                    "transfer_bytes": self.transfer_bytes,
                    "per_shard": per_shard}


PROFILER = DeviceProfiler(enabled=False)


def note_jit_lookup(cache: str, hit: bool) -> None:
    if PROFILER.enabled:
        PROFILER.note_jit(cache, hit)


def note_transfer(nbytes: int) -> None:
    if PROFILER.enabled:
        PROFILER.note_transfer(nbytes)
