"""Device-time profiling: wall vs. device seconds per flush, compile
cache hit/miss counts, and host<->device transfer bytes.

A process-wide `PROFILER` singleton (disabled by default) keeps the
hooks in tpu/zone_session.py and serve/bank.py down to one attribute
check when profiling is off — the jit-cache lookup path must not pay
for observability it isn't using. serve/driver.py enables it for
bench runs so `bench_serve_sched` can report how much of each flush
was actual `block_until_ready` device time versus host bookkeeping,
which is the measurement ROADMAP item (c)'s fused-flush claim needs.
"""

from __future__ import annotations

import threading
from typing import Dict


class DeviceProfiler:
    def __init__(self, enabled: bool = False) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._jit: Dict[str, list] = {}
        self._shard: Dict[int, dict] = {}
        self.transfers = 0
        self.transfer_bytes = 0
        self._transfer_detail: Dict[tuple, list] = {}
        self._fused = {"device_calls": 0, "docs": 0,
                       "wall_s": 0.0, "device_s": 0.0}
        self._window = {"dispatches": 0, "docs": 0, "shards": 0,
                        "staged_bytes": 0,
                        "wall_s": 0.0, "device_s": 0.0}

    def reset(self) -> None:
        with self._lock:
            self._jit = {}
            self._shard = {}
            self.transfers = 0
            self.transfer_bytes = 0
            self._transfer_detail = {}
            self._fused = {"device_calls": 0, "docs": 0,
                           "wall_s": 0.0, "device_s": 0.0}
            self._window = {"dispatches": 0, "docs": 0, "shards": 0,
                            "staged_bytes": 0,
                            "wall_s": 0.0, "device_s": 0.0}

    def note_jit(self, cache: str, hit: bool) -> None:
        if not self.enabled:
            return
        with self._lock:
            c = self._jit.setdefault(cache, [0, 0])
            c[0 if hit else 1] += 1

    def observe_flush(self, shard: int, wall_s: float,
                      device_s: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            s = self._shard.setdefault(
                int(shard), {"flushes": 0, "wall_s": 0.0, "device_s": 0.0})
            s["flushes"] += 1
            s["wall_s"] += wall_s
            s["device_s"] += device_s

    def observe_fused(self, shard: int, wall_s: float, device_s: float,
                      n_docs: int) -> None:
        """One fused bucket replay: `wall_s` is the whole dispatch +
        commit, `device_s` the completion-fence wait (the
        block_until_ready-equivalent) — the wall-vs-device attribution
        for the fused path, per ROADMAP item (c). Also counts toward
        the shard's flush totals so per_shard rows stay comparable
        between fused and per-doc flushes."""
        if not self.enabled:
            return
        with self._lock:
            f = self._fused
            f["device_calls"] += 1
            f["docs"] += int(n_docs)
            f["wall_s"] += wall_s
            f["device_s"] += device_s
            s = self._shard.setdefault(
                int(shard), {"flushes": 0, "wall_s": 0.0, "device_s": 0.0})
            s["flushes"] += 1
            s["wall_s"] += wall_s
            s["device_s"] += device_s

    def observe_window(self, wall_s: float, device_s: float,
                       n_docs: int, n_shards: int,
                       staged_bytes: int = 0) -> None:
        """One mesh flush-window dispatch: `n_docs` docs from
        `n_shards` shards replayed in a single shard_map program
        (scheduler._flush_window). `staged_bytes` is the host->device
        byte count the window's state staging actually paid (0 when
        the arena fast path or the device-side gather kept rows
        resident — the saving ISSUE 20's staging claim is about).
        Kept SEPARATE from the per-shard flush totals — a window is
        cross-shard by construction, so attributing its wall time to
        any one shard would double-count against the per_shard rows."""
        if not self.enabled:
            return
        with self._lock:
            w = self._window
            w["dispatches"] += 1
            w["docs"] += int(n_docs)
            w["shards"] += int(n_shards)
            w["staged_bytes"] += int(staged_bytes)
            w["wall_s"] += wall_s
            w["device_s"] += device_s

    def note_transfer(self, nbytes: int, rung: str = "",
                      purpose: str = "") -> None:
        """Count one host->device transfer. `rung` names the ladder
        rung that paid it (session/fused/mesh/pallas), `purpose` what
        moved: "stage" (resident doc state), "plan" (the window's op
        arrays — always host-built), or "warmup" (ahead-of-time
        compiles). Untagged calls keep the legacy totals working."""
        if not self.enabled:
            return
        with self._lock:
            self.transfers += 1
            self.transfer_bytes += int(nbytes)
            if rung or purpose:
                d = self._transfer_detail.setdefault(
                    (rung or "other", purpose or "other"), [0, 0])
                d[0] += 1
                d[1] += int(nbytes)

    def snapshot(self) -> dict:
        with self._lock:
            jit = {k: {"hits": v[0], "misses": v[1]}
                   for k, v in sorted(self._jit.items())}
            per_shard = {
                str(k): {"flushes": v["flushes"],
                         "wall_s": round(v["wall_s"], 6),
                         "device_s": round(v["device_s"], 6)}
                for k, v in sorted(self._shard.items())}
            wall = sum(v["wall_s"] for v in self._shard.values())
            dev = sum(v["device_s"] for v in self._shard.values())
            f = self._fused
            calls = f["device_calls"]
            fused = {"device_calls": calls, "docs": f["docs"],
                     "occupancy": round(f["docs"] / calls, 4)
                     if calls else 0.0,
                     "wall_s": round(f["wall_s"], 6),
                     "device_sync_s": round(f["device_s"], 6),
                     "device_fraction": round(
                         f["device_s"] / f["wall_s"], 4)
                     if f["wall_s"] else 0.0}
            w = self._window
            nw = w["dispatches"]
            window = {"dispatches": nw, "docs": w["docs"],
                      "docs_per_dispatch": round(w["docs"] / nw, 4)
                      if nw else 0.0,
                      "mean_shards": round(w["shards"] / nw, 4)
                      if nw else 0.0,
                      "staged_bytes": w["staged_bytes"],
                      "staged_bytes_per_window": round(
                          w["staged_bytes"] / nw, 2) if nw else 0.0,
                      "wall_s": round(w["wall_s"], 6),
                      "device_sync_s": round(w["device_s"], 6),
                      "device_fraction": round(
                          w["device_s"] / w["wall_s"], 4)
                      if w["wall_s"] else 0.0}
            detail = {f"{r}.{p}": {"transfers": v[0], "bytes": v[1]}
                      for (r, p), v
                      in sorted(self._transfer_detail.items())}
            return {"enabled": self.enabled,
                    "jit_cache": jit,
                    "flush_wall_s": round(wall, 6),
                    "device_sync_s": round(dev, 6),
                    "device_fraction": round(dev / wall, 4) if wall else 0.0,
                    "transfers": self.transfers,
                    "transfer_bytes": self.transfer_bytes,
                    "transfer_detail": detail,
                    "fused": fused,
                    "mesh_window": window,
                    "per_shard": per_shard}


PROFILER = DeviceProfiler(enabled=False)


def note_jit_lookup(cache: str, hit: bool) -> None:
    if PROFILER.enabled:
        PROFILER.note_jit(cache, hit)


def note_transfer(nbytes: int, rung: str = "", purpose: str = "") -> None:
    if PROFILER.enabled:
        PROFILER.note_transfer(nbytes, rung=rung, purpose=purpose)
