"""Native (C++) host core bindings via ctypes.

The hot merge path (graph queries + spanning-tree walk + treap tracker +
transform pipeline) is implemented in native/dt_core.cpp, mirroring how the
reference implements its host tier in Rust. Python falls back to the pure
implementation in diamond_types_tpu.listmerge when the shared library isn't
built. Build with: python -m diamond_types_tpu.native.build
"""

from .core import (NativeContext, merge_native, native_available,  # noqa: F401
                   transform_native)


def native_ctx_or_none(oplog):
    """The oplog's native context, or None when the native engine is
    disabled (DT_TPU_NO_NATIVE) or the library is unavailable — the one
    gate for every native fast path that needs a per-oplog context
    (composer, encoder, merge, conflict counting). The fresh-load decoder
    gates separately (no oplog exists yet at decode time)."""
    import os
    if os.environ.get("DT_TPU_NO_NATIVE"):
        return None
    if not native_available():
        return None
    from .core import get_native_ctx
    return get_native_ctx(oplog)
