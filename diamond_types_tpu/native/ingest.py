"""Native local-ingest session — the editor-typing hot path at C speed.

`OpLog.add_insert_at`/`add_delete_at` pay Python-object costs per op
(~300k ops/s on automerge-paper, BENCH_r04); the reference ingests local
ops natively (reference: src/list/oplog.rs:203-296). A `LocalSession`
batches one agent's linear tip edits in a C extension
(native/dt_ingest.cpp) that RLE-merges runs with the exact
`can_append_ops`/`append_ops` rules, then `flush()` lands them in the
oplog in one bulk append: one agent-assignment span, one graph push, one
arena extend — precisely what the per-op path's own RLE would have
produced, so the flushed oplog is structurally identical (tests prove
encode-byte parity).

Scope: local edits only — one agent, every op at the current tip (the
shape typing has). The session holds PENDING state: the oplog does not
see the ops until flush(). Use as a context manager; single writer.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Optional

from ..text.op import DEL, INS, OpRun

_ext = False  # False = not probed; None = unavailable


def _load_ext():
    global _ext
    if os.environ.get("DT_TPU_NO_NATIVE"):
        # the one kill switch every native fast path honors — an oracle
        # run must be genuinely native-free
        return None
    if _ext is False:
        try:
            # unconditional: build_ingest no-ops when the .so is fresh,
            # and rebuilds when dt_ingest.cpp changed (loading a stale
            # binary would make the parity suite test old code)
            from .build import build_ingest
            path = build_ingest()
            if path:
                spec = importlib.util.spec_from_file_location("_dtingest",
                                                              path)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                _ext = mod
            else:
                _ext = None
        except Exception:  # noqa: BLE001 - any failure means "no native"
            _ext = None
    return _ext


def native_ingest_available() -> bool:
    return _load_ext() is not None


class PySession:
    """Pure-Python fallback with LocalSession's API: per-op calls go
    straight through add_insert_at/add_delete_at (the oracle path), so
    the kill switch and compiler-less environments keep working."""

    __slots__ = ("oplog", "agent")

    def __init__(self, oplog, agent: int) -> None:
        self.oplog = oplog
        self.agent = agent

    def insert(self, pos: int, content: str) -> int:
        if not content:
            raise ValueError("empty insert")
        return self.oplog.add_insert(self.agent, pos, content)

    def delete(self, start: int, end: int,
               content: Optional[str] = None) -> int:
        if end <= start:
            raise ValueError("empty delete")
        if content is not None and len(content) != end - start:
            raise ValueError("content length != delete length")
        return self.oplog.add_delete_at(self.agent, self.oplog.version,
                                        start, end, content)

    def pending(self) -> int:
        return 0  # ops land immediately on this path

    def hot(self):
        def ins(_s, pos, text):
            return self.insert(pos, text)

        def dele(_s, start, end, content=None):
            return self.delete(start, end, content)

        return None, ins, dele

    def flush(self) -> None:
        pass

    def __enter__(self) -> "PySession":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


class LocalSession:
    """Batched linear local edits on one oplog by one agent.

    insert()/delete() return the op's last LV (same contract as
    add_insert_at/add_delete_at). The edits become visible in the oplog
    only at flush() — callers that need to read oplog state mid-stream
    flush first (the context manager flushes on exit).
    """

    __slots__ = ("oplog", "agent", "_s", "_base_lv", "_frontier", "_ext")

    def __new__(cls, oplog, agent: int):
        if _load_ext() is None:
            # DT_TPU_NO_NATIVE / no compiler: same API, per-op Python
            # path (the oracle) — callers keep working, just slower
            return PySession(oplog, agent)
        return super().__new__(cls)

    def __init__(self, oplog, agent: int) -> None:
        self._ext = _load_ext()
        self.oplog = oplog
        self.agent = agent
        self._begin()

    def _begin(self) -> None:
        ol = self.oplog
        self._base_lv = len(ol)
        self._frontier = list(ol.version)
        runs = ol.ops.runs
        if runs:
            last = runs[-1]
            self._s = self._ext.new(last.kind, last.start, last.end,
                                    last.fwd, last.content_pos is not None)
        else:
            self._s = self._ext.new()

    def insert(self, pos: int, content: str) -> int:
        return self._base_lv + self._ext.ins(self._s, pos, content) - 1

    def delete(self, start: int, end: int,
               content: Optional[str] = None) -> int:
        return self._base_lv + self._ext.del_(self._s, start, end,
                                              content) - 1

    def pending(self) -> int:
        return self._ext.count(self._s)

    def hot(self):
        """(session, ins, del_) for tight ingest loops: `ins(sess, pos,
        text)` / `del_(sess, start, end[, content])` skip this wrapper's
        attribute loads and LV arithmetic (~25% on automerge-paper
        replay). The handles are valid until the next flush(); LVs can
        be recovered afterwards as base_lv + running count."""
        return self._s, self._ext.ins, self._ext.del_

    def flush(self) -> None:
        """Land the pending edits in the oplog (one bulk append)."""
        ol = self.oplog
        if self._ext.count(self._s) == 0:
            # nothing pending: a no-op flush just re-seeds (the oplog
            # may legitimately have moved on since the last flush)
            self._begin()
            return
        if len(ol) != self._base_lv:
            # checked BEFORE drain (drain irreversibly resets the C++
            # session) and with a real exception (an -O run must not
            # land runs against a stale base LV silently)
            raise RuntimeError(
                f"oplog mutated during local session (base lv "
                f"{self._base_lv}, now {len(ol)}); pending edits kept")
        runs, ins_a, del_a, count, seed = self._ext.drain(self._s)
        if count:
            ops = ol.ops
            bases = (ops.arena_len(INS), ops.arena_len(DEL))
            if ins_a:
                ops._arenas[INS].push(ins_a)
            if del_a:
                ops._arenas[DEL].push(del_a)
            if seed is not None:
                # ops merged into the (seeded) predecessor run: apply its
                # final loc values and extend its content span with the
                # chars the session prepended to this kind's arena
                s_start, s_end, s_fwd, appended = seed
                last = ops.runs[-1]
                last.start, last.end, last.fwd = s_start, s_end, s_fwd
                if appended:
                    cp = last.content_pos
                    assert cp is not None and cp[1] == bases[last.kind], \
                        "seed content is not the arena tail"
                    last.content_pos = (cp[0], cp[1] + appended)
            for (lv, kind, start, end, fwd, cp0, cp1) in runs:
                cp = None if cp0 < 0 else (cp0 + bases[kind],
                                           cp1 + bases[kind])
                ops.runs.append(OpRun(self._base_lv + lv, kind, start, end,
                                      fwd, cp))
            ol.cg.assign_local_op_with_parents(self._frontier, self.agent,
                                               count)
        self._begin()

    # --- context manager -------------------------------------------------

    def __enter__(self) -> "LocalSession":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.flush()
