"""Build the native host core: g++ -O2 -shared -fPIC native/dt_core.cpp."""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
SRC = os.path.join(REPO, "native", "dt_core.cpp")
SRC_DECODE = os.path.join(REPO, "native", "dt_decode.cpp")
OUT = os.path.join(REPO, "native", "libdt_core.so")


def build(force: bool = False) -> str | None:
    if not os.path.exists(SRC):
        return None
    srcs = [SRC] + ([SRC_DECODE] if os.path.exists(SRC_DECODE) else [])
    if not force and os.path.exists(OUT) and \
            all(os.path.getmtime(OUT) >= os.path.getmtime(s) for s in srcs):
        return OUT
    # -fno-semantic-interposition: lets the compiler inline across
    # functions inside the DSO despite -fPIC (ELF interposition rules
    # otherwise force calls through the PLT); ~14% on the git-makefile
    # merge in interleaved A/B runs. (-flto HURTS the shared build —
    # measured 20% slower — even though it helps the static bench binary.)
    cmd = ["g++", "-O3", "-march=native", "-fno-semantic-interposition",
           "-std=c++17", "-shared", "-fPIC", "-DNDEBUG", *srcs, "-o", OUT]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        sys.stderr.write(f"native build failed: {e}\n")
        if hasattr(e, "stderr") and e.stderr:
            sys.stderr.write(e.stderr[:2000] + "\n")
        return None
    return OUT


if __name__ == "__main__":
    out = build(force="--force" in sys.argv)
    print(out or "BUILD FAILED")
    sys.exit(0 if out else 1)
