"""Build the native host core: g++ -O2 -shared -fPIC native/dt_core.cpp."""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
SRC = os.path.join(REPO, "native", "dt_core.cpp")
SRC_DECODE = os.path.join(REPO, "native", "dt_decode.cpp")
OUT = os.path.join(REPO, "native", "libdt_core.so")
SRC_INGEST = os.path.join(REPO, "native", "dt_ingest.cpp")


def _ingest_out() -> str:
    # ABI-tagged filename (e.g. _dtingest.cpython-312-x86_64-linux-gnu.so):
    # unlike the ctypes-driven libdt_core.so this is a real CPython
    # extension, and loading one built for another interpreter is UB
    import sysconfig
    return os.path.join(REPO, "native",
                        "_dtingest" + sysconfig.get_config_var("EXT_SUFFIX"))


def build(force: bool = False) -> str | None:
    if not os.path.exists(SRC):
        return None
    srcs = [SRC] + ([SRC_DECODE] if os.path.exists(SRC_DECODE) else [])
    if not force and os.path.exists(OUT) and \
            all(os.path.getmtime(OUT) >= os.path.getmtime(s) for s in srcs):
        return OUT
    # -fno-semantic-interposition: lets the compiler inline across
    # functions inside the DSO despite -fPIC (ELF interposition rules
    # otherwise force calls through the PLT); ~14% on the git-makefile
    # merge in interleaved A/B runs. (-flto HURTS the shared build —
    # measured 20% slower — even though it helps the static bench binary.)
    cmd = ["g++", "-O3", "-march=native", "-fno-semantic-interposition",
           "-std=c++17", "-shared", "-fPIC", "-DNDEBUG", *srcs, "-o", OUT]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        sys.stderr.write(f"native build failed: {e}\n")
        if hasattr(e, "stderr") and e.stderr:
            sys.stderr.write(e.stderr[:2000] + "\n")
        return None
    return OUT


def build_ingest(force: bool = False) -> str | None:
    """Build the local-ingest CPython extension (native/dt_ingest.cpp).

    A real extension module (not ctypes) because the per-call overhead
    IS the hot path being fixed — see dt_ingest.cpp's header comment."""
    if not os.path.exists(SRC_INGEST):
        return None
    out_ingest = _ingest_out()
    if not force and os.path.exists(out_ingest) and \
            os.path.getmtime(out_ingest) >= os.path.getmtime(SRC_INGEST):
        return out_ingest
    import sysconfig
    inc = sysconfig.get_paths()["include"]
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
           "-DNDEBUG", f"-I{inc}", SRC_INGEST, "-o", out_ingest]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        sys.stderr.write(f"ingest ext build failed: {e}\n")
        if hasattr(e, "stderr") and e.stderr:
            sys.stderr.write(e.stderr[:2000] + "\n")
        return None
    return out_ingest


if __name__ == "__main__":
    out = build(force="--force" in sys.argv)
    out2 = build_ingest(force="--force" in sys.argv)
    print(out or "BUILD FAILED")
    print(out2 or "INGEST BUILD FAILED")
    # a broken ingest build must fail loudly: its tests skip when the
    # extension is unavailable, so a silent exit-0 would leave the
    # parity suite green with zero coverage
    sys.exit(0 if (out and out2) else 1)
