"""ctypes wrapper over native/libdt_core.so."""

from __future__ import annotations

import ctypes as ct
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .build import OUT as _SO_PATH, build as _build

_lib = None

# Underwater sentinel base (ids at or above this are pre-zone placeholder
# text, not real op LVs) — one definition, shared with native/dt_core.cpp's
# UNDERWATER constant.
from ..core.span import UNDERWATER_START as UNDERWATER  # noqa: E402


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = _build()
    if path is None or not os.path.exists(path):
        return None
    lib = ct.CDLL(path)
    try:
        _configure(lib)
    except AttributeError as e:
        # an .so predating the current symbol set (e.g. checkout with
        # equal mtimes skipping the rebuild): degrade to the Python
        # fallbacks instead of crashing every native call site
        import sys
        sys.stderr.write(f"stale native library ({e}); native paths "
                         f"disabled — rebuild with python -m "
                         f"diamond_types_tpu.native.build --force\n")
        return None
    _lib = lib
    return lib


def _configure(lib) -> None:
    lib.dt_ctx_new.restype = ct.c_void_p
    lib.dt_ctx_free.argtypes = [ct.c_void_p]
    lib.dt_add_agent.argtypes = [ct.c_void_p, ct.c_char_p]
    lib.dt_load_graph.argtypes = [ct.c_void_p, ct.c_int64] + [
        np.ctypeslib.ndpointer(np.int64, flags="C")] * 5
    lib.dt_load_agent_runs.argtypes = [ct.c_void_p, ct.c_int64] + [
        np.ctypeslib.ndpointer(np.int64, flags="C")] * 4
    lib.dt_load_ops.argtypes = [
        ct.c_void_p, ct.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C")]
    lib.dt_load_ins_arena.argtypes = [
        ct.c_void_p, ct.c_int64, np.ctypeslib.ndpointer(np.int32, flags="C")]
    lib.dt_merge_into_doc.argtypes = [
        ct.c_void_p, np.ctypeslib.ndpointer(np.int32, flags="C"), ct.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C"), ct.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C"), ct.c_int64]
    lib.dt_merge_into_doc.restype = ct.c_int64
    lib.dt_get_doc.argtypes = [
        ct.c_void_p, np.ctypeslib.ndpointer(np.int32, flags="C")]
    lib.dt_transform.argtypes = [
        ct.c_void_p,
        np.ctypeslib.ndpointer(np.int64, flags="C"), ct.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C"), ct.c_int64]
    lib.dt_transform.restype = ct.c_int64
    lib.dt_get_out.argtypes = [
        ct.c_void_p,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C")]
    lib.dt_get_out_frontier.argtypes = [
        ct.c_void_p, np.ctypeslib.ndpointer(np.int64, flags="C"), ct.c_int64]
    lib.dt_get_out_frontier.restype = ct.c_int64
    lib.dt_dump_tracker.argtypes = [
        ct.c_void_p, ct.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C")]
    lib.dt_dump_tracker.restype = ct.c_int64
    lib.dt_dump_del_rows.argtypes = [
        ct.c_void_p, ct.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C")]
    lib.dt_dump_del_rows.restype = ct.c_int64
    lib.dt_last_collisions.argtypes = [ct.c_void_p]
    lib.dt_last_collisions.restype = ct.c_int64
    lib.dt_decode_new.argtypes = [
        np.ctypeslib.ndpointer(np.uint8, flags="C"), ct.c_int64]
    lib.dt_decode_new.restype = ct.c_void_p
    lib.dt_decode_free.argtypes = [ct.c_void_p]
    lib.dt_dec_status.argtypes = [ct.c_void_p]
    lib.dt_dec_status.restype = ct.c_int64
    lib.dt_dec_err.argtypes = [ct.c_void_p, ct.c_char_p, ct.c_int64]
    lib.dt_dec_err.restype = ct.c_int64
    lib.dt_dec_counts.argtypes = [
        ct.c_void_p, np.ctypeslib.ndpointer(np.int64, flags="C")]
    lib.dt_dec_strings.argtypes = [
        ct.c_void_p,
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C")]
    lib.dt_dec_agent_runs.argtypes = [
        ct.c_void_p,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C")]
    lib.dt_dec_ops.argtypes = [
        ct.c_void_p,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C")]
    lib.dt_crc32c.argtypes = [
        np.ctypeslib.ndpointer(np.uint8, flags="C"), ct.c_int64, ct.c_int64]
    lib.dt_crc32c.restype = ct.c_int64
    lib.dt_lz4_compress.argtypes = [
        np.ctypeslib.ndpointer(np.uint8, flags="C"), ct.c_int64,
        np.ctypeslib.ndpointer(np.uint8, flags="C"), ct.c_int64]
    lib.dt_lz4_compress.restype = ct.c_int64
    lib.dt_dec_graph.argtypes = [
        ct.c_void_p,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C")]
    lib.dt_get_zone_common.argtypes = [
        ct.c_void_p, np.ctypeslib.ndpointer(np.int64, flags="C"), ct.c_int64]
    lib.dt_get_zone_common.restype = ct.c_int64
    lib.dt_release_tracker.argtypes = [ct.c_void_p]
    lib.dt_get_counters.argtypes = [
        np.ctypeslib.ndpointer(np.uint64, flags="C"), ct.c_int64]
    lib.dt_get_counters.restype = ct.c_int64
    lib.dt_reset_counters.argtypes = []
    _i64p = np.ctypeslib.ndpointer(np.int64, flags="C")
    _i32p = np.ctypeslib.ndpointer(np.int32, flags="C")
    _u8p = np.ctypeslib.ndpointer(np.uint8, flags="C")
    lib.dt_compose_plan.argtypes = [ct.c_void_p, ct.c_int64, _i64p, _i64p]
    lib.dt_compose_plan.restype = ct.c_int64
    lib.dt_compose_counts.argtypes = [ct.c_void_p, _i64p]
    lib.dt_compose_serial.argtypes = [ct.c_void_p]
    lib.dt_compose_serial.restype = ct.c_int64
    lib.dt_compose_fetch.argtypes = [
        ct.c_void_p, _i64p, _i64p, _i32p, _u8p, _u8p, _i64p, _i32p,
        _i64p, _i64p, _i32p, _i64p, _i32p, _i32p,
        _i64p, _i64p, _i64p, _i64p]
    lib.dt_compose_linear.argtypes = [ct.c_void_p, ct.c_int64, _i64p, _i64p]
    lib.dt_compose_linear.restype = ct.c_int64
    lib.dt_fetch_linear.argtypes = [ct.c_void_p, _i64p, _i64p]
    lib.dt_encode_full.argtypes = [ct.c_void_p, ct.c_char_p, ct.c_int64,
                                   ct.c_char_p, ct.c_int64, ct.c_int64,
                                   ct.c_int64]
    lib.dt_encode_full.restype = ct.c_int64
    lib.dt_encode_patch.argtypes = [ct.c_void_p, ct.c_char_p, ct.c_int64,
                                    ct.c_char_p, ct.c_int64, ct.c_int64,
                                    ct.c_int64, _i64p, ct.c_int64]
    lib.dt_encode_patch.restype = ct.c_int64
    lib.dt_encode_fetch.argtypes = [ct.c_void_p, _u8p]
    lib.dt_zone_ins_runs.argtypes = [ct.c_void_p, ct.c_int64, _i64p,
                                     _i64p, _i64p, _i64p, _i64p]
    lib.dt_zone_ins_runs.restype = ct.c_int64
    lib.dt_graph_rebuild.argtypes = [ct.c_int64] + [_i64p] * 15
    lib.dt_graph_rebuild.restype = ct.c_int64
    lib.dt_zone_pack.argtypes = [
        ct.c_void_p, ct.c_int64, _i64p, _i64p, _i64p,          # actions
        ct.c_int64, _i64p,                                      # counts
        _i64p, _i64p, _u8p, _i64p, _i32p, _i64p,                # q + ch cols
        _i32p, _i64p, _i32p, _i32p,                             # blk cols
        _i64p, _i64p, _i64p, _i64p,                             # del cols
        ct.c_int64, _i64p, _i64p, ct.c_int64,                   # slot map
        _i64p, _i64p,                                           # keys
        ct.c_int64, ct.c_int64, ct.c_int64, ct.c_int64]  # MB MC MD cache
    lib.dt_zone_pack.restype = ct.c_int64
    lib.dt_zone_pack_fetch.argtypes = [ct.c_void_p] + [_i32p] * 19 + [
        ct.c_int64, ct.c_int64, ct.c_int64]


def native_available() -> bool:
    return _load() is not None


class NativeContext:
    """A C++ mirror of an OpLog's merge-relevant state (graph, agent runs,
    op runs). Rebuilt lazily when the oplog grows."""

    def __init__(self, oplog) -> None:
        lib = _load()
        assert lib is not None
        self._lib = lib
        self._ptr = lib.dt_ctx_new()
        self._built_len = -1
        self._oplog = oplog

    def __del__(self):
        try:
            self._lib.dt_ctx_free(self._ptr)
        except Exception:
            pass

    def sync(self) -> None:
        ol = self._oplog
        if self._built_len == len(ol):
            return
        lib = self._lib
        # Rebuild from scratch (bulk load is cheap: O(n) columnar copies).
        lib.dt_ctx_free(self._ptr)
        self._ptr = lib.dt_ctx_new()
        for name in ol.cg.agent_assignment.agent_names:
            lib.dt_add_agent(self._ptr, name.encode("utf8"))
        g = ol.cg.graph
        starts, ends, shadows, indptr, flat = g.as_arrays()
        if flat.size == 0:
            flat = np.zeros(1, dtype=np.int64)
        lib.dt_load_graph(self._ptr, len(starts),
                          np.ascontiguousarray(starts),
                          np.ascontiguousarray(ends),
                          np.ascontiguousarray(shadows),
                          np.ascontiguousarray(indptr),
                          np.ascontiguousarray(flat))
        gr = ol.cg.agent_assignment.global_runs
        lv0 = np.asarray([r[0] for r in gr], dtype=np.int64)
        lv1 = np.asarray([r[1] for r in gr], dtype=np.int64)
        ag = np.asarray([r[2] for r in gr], dtype=np.int64)
        sq = np.asarray([r[3] for r in gr], dtype=np.int64)
        lib.dt_load_agent_runs(self._ptr, len(gr), lv0, lv1, ag, sq)
        runs = ol.ops.runs
        lv = np.asarray([r.lv for r in runs], dtype=np.int64)
        kind = np.asarray([r.kind for r in runs], dtype=np.uint8)
        fwd = np.asarray([1 if r.fwd else 0 for r in runs], dtype=np.uint8)
        st = np.asarray([r.start for r in runs], dtype=np.int64)
        en = np.asarray([r.end for r in runs], dtype=np.int64)
        cp, arena, arena_chars = content_columns(ol)
        lib.dt_load_ops(self._ptr, len(runs), lv, kind, fwd, st, en, cp)
        lib.dt_load_ins_arena(self._ptr, arena_chars,
                              np.ascontiguousarray(arena))
        self._built_len = len(ol)

    def transform(self, from_frontier: Sequence[int],
                  merge_frontier: Sequence[int]):
        """Returns (lv, len, kind, fwd, pos arrays, final_frontier)."""
        self.sync()
        lib = self._lib
        f = np.asarray(sorted(from_frontier), dtype=np.int64)
        m = np.asarray(sorted(merge_frontier), dtype=np.int64)
        if f.size == 0:
            f = np.zeros(0, dtype=np.int64)
        if m.size == 0:
            m = np.zeros(0, dtype=np.int64)
        n = lib.dt_transform(self._ptr, np.ascontiguousarray(f), len(f),
                             np.ascontiguousarray(m), len(m))
        lv = np.empty(n, dtype=np.int64)
        ln = np.empty(n, dtype=np.int64)
        kind = np.empty(n, dtype=np.uint8)
        fwd = np.empty(n, dtype=np.uint8)
        pos = np.empty(n, dtype=np.int64)
        if n:
            lib.dt_get_out(self._ptr, lv, ln, kind, fwd, pos)
        fbuf = np.empty(16, dtype=np.int64)
        k = lib.dt_get_out_frontier(self._ptr, fbuf, 16)
        if k > 16:
            fbuf = np.empty(k, dtype=np.int64)
            lib.dt_get_out_frontier(self._ptr, fbuf, k)
        frontier = [int(x) for x in fbuf[:k]]
        return lv, ln, kind, fwd, pos, frontier


    def compose_serial(self) -> int:
        """Identity of the current native compose cache (bumped by every
        dt_compose_plan) — the zone packer validates it before packing
        from the cache."""
        return int(self._lib.dt_compose_serial(self._ptr))

    def zone_ins_runs(self, spans):
        """INS sub-runs of the given spans as (lv0, len, cp) int64
        arrays — prepare_zone's table pass in C++; None on unsupported
        input (insert without stored content)."""
        self.sync()
        n = len(spans)
        s0 = np.ascontiguousarray(
            [s for s, _ in spans] or [0], dtype=np.int64)
        s1 = np.ascontiguousarray(
            [e for _, e in spans] or [0], dtype=np.int64)
        # bounded by the zone's own extent, not the whole history: a
        # span of L LVs overlaps at most L runs, and tiny incremental
        # zones must not allocate O(total-history) receive buffers
        span_lvs = sum(e - s for s, e in spans)
        cap = min(len(self._oplog.ops.runs), span_lvs) + n + 1
        lv0 = np.empty(cap, dtype=np.int64)
        ln = np.empty(cap, dtype=np.int64)
        cp = np.empty(cap, dtype=np.int64)
        k = self._lib.dt_zone_ins_runs(self._ptr, n, s0, s1, lv0, ln, cp)
        if k < 0:
            return None
        return lv0[:k], ln[:k], cp[:k]

    def compose_cache_only(self, spans) -> bool:
        """Run the native composer, leaving results ONLY in the ctx
        cache (no Python column round-trip) — the zone packer reads
        them in place. False = unsupported input (caller composes via
        the normal path)."""
        self.sync()
        n = len(spans)
        s0 = np.ascontiguousarray(
            [s for s, _ in spans] or [0], dtype=np.int64)
        s1 = np.ascontiguousarray(
            [e for _, e in spans] or [0], dtype=np.int64)
        return self._lib.dt_compose_plan(self._ptr, n, s0, s1) == 0

    def compose_plan(self, spans):
        """Native zone-engine composer (listmerge/compose.py's hot path in
        C++): compose each entry span into entry-start coordinates.
        Returns a list of per-entry column dicts, or None on unsupported
        input (reverse insert runs) — the caller falls back to Python."""
        self.sync()
        lib = self._lib
        n = len(spans)
        s0 = np.ascontiguousarray([s for s, _ in spans], dtype=np.int64)
        s1 = np.ascontiguousarray([e for _, e in spans], dtype=np.int64)
        if n == 0:
            return []
        if lib.dt_compose_plan(self._ptr, n, s0, s1) != 0:
            return None
        counts = np.empty(n * 5, dtype=np.int64)
        lib.dt_compose_counts(self._ptr, counts)
        counts = counts.reshape(n, 5)
        tq, tc, tb, tdb, tdo = (int(x) for x in counts.sum(axis=0))
        q = np.empty(tq, dtype=np.int64)
        ch_lv = np.empty(tc, dtype=np.int64)
        ch_block = np.empty(tc, dtype=np.int32)
        ch_head = np.empty(tc, dtype=np.uint8)
        ch_kind = np.empty(tc, dtype=np.uint8)
        ch_anchor = np.empty(tc, dtype=np.int64)
        ch_q = np.empty(tc, dtype=np.int32)
        ch_headlv = np.empty(tc, dtype=np.int64)
        ch_orrown = np.empty(tc, dtype=np.int64)
        blk_root_q = np.empty(tb, dtype=np.int32)
        blk_root_lv = np.empty(tb, dtype=np.int64)
        blk_start = np.empty(tb, dtype=np.int32)
        blk_len = np.empty(tb, dtype=np.int32)
        db0 = np.empty(tdb, dtype=np.int64)
        db1 = np.empty(tdb, dtype=np.int64)
        do0 = np.empty(tdo, dtype=np.int64)
        do1 = np.empty(tdo, dtype=np.int64)
        lib.dt_compose_fetch(self._ptr, q, ch_lv, ch_block, ch_head,
                             ch_kind, ch_anchor, ch_q, ch_headlv, ch_orrown,
                             blk_root_q, blk_root_lv, blk_start, blk_len,
                             db0, db1, do0, do1)
        out = []
        oq = oc = ob = odb = odo = 0
        for k in range(n):
            nq, nc, nb, ndb, ndo = (int(x) for x in counts[k])
            out.append({
                "q_cursor": q[oq:oq + nq].tolist(),
                "ch_lv": ch_lv[oc:oc + nc],
                "ch_block": ch_block[oc:oc + nc],
                "ch_head": ch_head[oc:oc + nc].astype(np.int8),
                "ch_kind": ch_kind[oc:oc + nc].astype(np.int8),
                "ch_anchor": ch_anchor[oc:oc + nc],
                "ch_q": ch_q[oc:oc + nc],
                "ch_headlv": ch_headlv[oc:oc + nc],
                "ch_orrown": ch_orrown[oc:oc + nc],
                "blk_root_q": blk_root_q[ob:ob + nb],
                "blk_root_lv": blk_root_lv[ob:ob + nb],
                "blk_start": blk_start[ob:ob + nb],
                "blk_len": blk_len[ob:ob + nb],
                "del_base": list(zip(db0[odb:odb + ndb].tolist(),
                                     db1[odb:odb + ndb].tolist())),
                "del_own": list(zip(do0[odo:odo + ndo].tolist(),
                                    do1[odo:odo + ndo].tolist())),
            })
            oq += nq
            oc += nc
            ob += nb
            odb += ndb
            odo += ndo
        return out

    def encode_full(self, doc_id, user_data, store_ins: bool,
                    compress: bool):
        """Native v1 full-snapshot encode (from_version=[]); None on
        failure (caller falls back to the Python writer)."""
        self.sync()
        lib = self._lib
        did = doc_id.encode("utf8") if doc_id is not None else None
        n = lib.dt_encode_full(
            self._ptr, did, len(did) if did is not None else -1,
            user_data, len(user_data) if user_data is not None else -1,
            1 if store_ins else 0, 1 if compress else 0)
        if n < 0:
            return None
        out = np.empty(n, dtype=np.uint8)
        lib.dt_encode_fetch(self._ptr, out)
        return out.tobytes()

    def encode_patch(self, doc_id, user_data, store_ins: bool,
                     compress: bool, from_version):
        """Native v1 patch encode (encode_from; reference:
        encode_oplog.rs:404-745) — byte-identical to the Python writer.
        None on failure (caller falls back)."""
        self.sync()
        lib = self._lib
        did = doc_id.encode("utf8") if doc_id is not None else None
        f = np.ascontiguousarray(sorted(from_version), dtype=np.int64)
        n = lib.dt_encode_patch(
            self._ptr, did, len(did) if did is not None else -1,
            user_data, len(user_data) if user_data is not None else -1,
            1 if store_ins else 0, 1 if compress else 0, f, len(f))
        if n < 0:
            return None
        out = np.empty(n, dtype=np.uint8)
        lib.dt_encode_fetch(self._ptr, out)
        return out.tobytes()

    def compose_linear(self, spans):
        """Alive own pieces (lv, len arrays) of a linear-history
        composition over an empty base (assemble_prefix's hot loop), or
        None on unsupported input."""
        self.sync()
        lib = self._lib
        s0 = np.ascontiguousarray([s for s, _ in spans], dtype=np.int64)
        s1 = np.ascontiguousarray([e for _, e in spans], dtype=np.int64)
        n = lib.dt_compose_linear(self._ptr, len(spans), s0, s1)
        if n < 0:
            return None
        lv = np.empty(n, dtype=np.int64)
        ln = np.empty(n, dtype=np.int64)
        if n:
            lib.dt_fetch_linear(self._ptr, lv, ln)
        return lv, ln

    def release_tracker(self) -> None:
        """Free the tracker tables retained for dump_tracker/zone_common."""
        self._lib.dt_release_tracker(self._ptr)

    def last_collisions(self) -> int:
        """Colliding concurrent inserts during the last transform
        (reference: has_conflicts_when_merging, src/list/merge.rs:51)."""
        return int(self._lib.dt_last_collisions(self._ptr))

    def zone_common(self):
        """Common-ancestor frontier of the last transform's conflict zone
        (the version whose document the underwater id space tiles)."""
        lib = self._lib
        buf = np.empty(64, dtype=np.int64)
        k = lib.dt_get_zone_common(self._ptr, buf, 64)
        if k > 64:
            buf = np.empty(k, dtype=np.int64)
            lib.dt_get_zone_common(self._ptr, buf, k)
        return [int(x) for x in buf[:k]]

    def dump_tracker(self, keep_underwater: bool = False):
        """Item table of the last transform's tracker, in DOCUMENT order:
        (ids, len, origin_left, origin_right, state, ever) arrays.
        Underwater sentinel rows (ids >= 1<<62) are the pre-zone document
        text (anchor targets for zone items); filtered unless requested."""
        lib = self._lib
        z = np.zeros(0, dtype=np.int64)
        zu = np.zeros(0, dtype=np.uint8)
        n = lib.dt_dump_tracker(self._ptr, 0, z, z, z, z, z, zu)
        ids = np.empty(n, dtype=np.int64)
        ln = np.empty(n, dtype=np.int64)
        ol = np.empty(n, dtype=np.int64)
        orr = np.empty(n, dtype=np.int64)
        st = np.empty(n, dtype=np.int64)
        ev = np.empty(n, dtype=np.uint8)
        if n:
            lib.dt_dump_tracker(self._ptr, n, ids, ln, ol, orr, st, ev)
        if not keep_underwater:
            keep = ids < UNDERWATER
            return (ids[keep], ln[keep], ol[keep], orr[keep], st[keep],
                    ev[keep])
        return (ids, ln, ol, orr, st, ev)

    def dump_del_rows(self):
        """Delete-target rows of the last transform's tracker, sorted by
        op LV: (lv0, lv1, t0, t1, fwd) arrays — op lv0+k deletes item
        t0+k (fwd) or t1-1-k (reversed). Targets are intrinsic to each
        delete op, so the rows are schedule-independent."""
        lib = self._lib
        z = np.zeros(0, dtype=np.int64)
        zu = np.zeros(0, dtype=np.uint8)
        n = lib.dt_dump_del_rows(self._ptr, 0, z, z, z, z, zu)
        lv0 = np.empty(n, dtype=np.int64)
        lv1 = np.empty(n, dtype=np.int64)
        t0 = np.empty(n, dtype=np.int64)
        t1 = np.empty(n, dtype=np.int64)
        fwd = np.empty(n, dtype=np.uint8)
        if n:
            lib.dt_dump_del_rows(self._ptr, n, lv0, lv1, t0, t1, fwd)
        o = np.argsort(lv0, kind="stable")
        return lv0[o], lv1[o], t0[o], t1[o], fwd[o]

    def merge_to_string(self, init: str, from_frontier: Sequence[int],
                        merge_frontier: Sequence[int]):
        """Full native merge: returns (final_doc_str, final_frontier)."""
        self.sync()
        lib = self._lib
        init_arr = np.frombuffer(init.encode("utf-32-le"), dtype=np.int32)
        if init_arr.size == 0:
            init_arr = np.zeros(1, dtype=np.int32)
        f = np.ascontiguousarray(np.asarray(sorted(from_frontier), dtype=np.int64))
        m = np.ascontiguousarray(np.asarray(sorted(merge_frontier), dtype=np.int64))
        n = lib.dt_merge_into_doc(self._ptr, np.ascontiguousarray(init_arr),
                                  len(init), f, len(f), m, len(m))
        out = np.empty(max(int(n), 1), dtype=np.int32)
        lib.dt_get_doc(self._ptr, out)
        doc = out[:n].tobytes().decode("utf-32-le")
        fbuf = np.empty(64, dtype=np.int64)
        k = lib.dt_get_out_frontier(self._ptr, fbuf, 64)
        if k > 64:
            fbuf = np.empty(k, dtype=np.int64)
            lib.dt_get_out_frontier(self._ptr, fbuf, k)
        return doc, [int(x) for x in fbuf[:k]]


# Order mirrors dt_core.cpp's EventCounters / dt_get_counters.
EVENT_COUNTER_NAMES = (
    "integrate_calls", "integrate_scan_iters", "apply_ins_runs",
    "apply_del_runs", "advance_calls", "retreat_calls", "walk_steps",
    "diff_calls")


_codec_lib = False  # False = not probed yet; None = unavailable


def _codec_load():
    """Like _load() but with negative caching and a broad exception guard:
    the codec fast paths sit on hot per-record loops and must degrade to
    the pure-Python implementations on ANY native failure (stale/ABI-
    incompatible .so, missing symbols, failed build) without re-probing
    per call."""
    global _codec_lib
    if _codec_lib is False:
        try:
            lib = _load()
            if lib is not None:
                lib.dt_crc32c  # symbol presence check (stale .so)
                lib.dt_lz4_compress
            _codec_lib = lib
        except Exception:  # noqa: BLE001 - any failure means "no native"
            _codec_lib = None
    return _codec_lib


def crc32c_native(data: bytes, seed: int = 0):
    lib = _codec_load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    return int(lib.dt_crc32c(np.ascontiguousarray(buf), len(data), seed))


def lz4_compress_native(data: bytes):
    lib = _codec_load()
    if lib is None:
        return None
    buf = np.ascontiguousarray(np.frombuffer(data, dtype=np.uint8))
    cap = len(data) + len(data) // 255 + 16
    out = np.zeros(max(1, cap), dtype=np.uint8)
    n = int(lib.dt_lz4_compress(buf, len(data), out, cap))
    if n < 0:  # pragma: no cover - compression expanded past the estimate
        out = np.zeros(-n, dtype=np.uint8)
        n = int(lib.dt_lz4_compress(buf, len(data), out, -n))
    return out[:n].tobytes()


class NativeParseError(Exception):
    """Hard parse/corruption error reported by the native decoder."""


def decode_file_native(data: bytes) -> Optional[dict]:
    """Parse a v1 .dt file with the C++ decoder (fresh-load path only).

    Returns a dict of columns, or None when the file shape needs the
    Python decoder (patch files with a non-empty start version) or the
    native library is unavailable. Raises NativeParseError on corrupt
    input (same failures the Python decoder raises ParseError for)."""
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    h = lib.dt_decode_new(np.ascontiguousarray(buf), len(data))
    try:
        status = lib.dt_dec_status(h)
        if status != 0:
            n = lib.dt_dec_err(h, None, 0)
            msg = ct.create_string_buffer(int(n) + 1)
            lib.dt_dec_err(h, msg, n)
            if status == 1:
                return None
            raise NativeParseError(msg.value.decode("utf8", "replace"))
        counts = np.zeros(10, dtype=np.int64)
        lib.dt_dec_counts(h, counts)
        (n_agents, names_bytes, n_aruns, n_ops, n_graph, n_par,
         ins_bytes, del_bytes, has_doc_id, doc_bytes) = (int(x)
                                                         for x in counts)
        names = np.zeros(max(1, names_bytes), dtype=np.uint8)
        name_lens = np.zeros(max(1, n_agents), dtype=np.int64)
        ins_blob = np.zeros(max(1, ins_bytes), dtype=np.uint8)
        del_blob = np.zeros(max(1, del_bytes), dtype=np.uint8)
        doc_id = np.zeros(max(1, doc_bytes), dtype=np.uint8)
        lib.dt_dec_strings(h, names, name_lens, ins_blob, del_blob, doc_id)
        ar_agent = np.zeros(max(1, n_aruns), dtype=np.int64)
        ar_seq0 = np.zeros(max(1, n_aruns), dtype=np.int64)
        ar_n = np.zeros(max(1, n_aruns), dtype=np.int64)
        lib.dt_dec_agent_runs(h, ar_agent, ar_seq0, ar_n)
        op_lv = np.zeros(max(1, n_ops), dtype=np.int64)
        op_kind = np.zeros(max(1, n_ops), dtype=np.uint8)
        op_start = np.zeros(max(1, n_ops), dtype=np.int64)
        op_end = np.zeros(max(1, n_ops), dtype=np.int64)
        op_fwd = np.zeros(max(1, n_ops), dtype=np.uint8)
        op_known = np.zeros(max(1, n_ops), dtype=np.uint8)
        op_clen = np.zeros(max(1, n_ops), dtype=np.int64)
        lib.dt_dec_ops(h, op_lv, op_kind, op_start, op_end, op_fwd,
                       op_known, op_clen)
        g_start = np.zeros(max(1, n_graph), dtype=np.int64)
        g_end = np.zeros(max(1, n_graph), dtype=np.int64)
        g_off = np.zeros(n_graph + 1, dtype=np.int64)
        g_par = np.zeros(max(1, n_par), dtype=np.int64)
        lib.dt_dec_graph(h, g_start, g_end, g_off, g_par)

        names_b = names.tobytes()[:names_bytes]
        agent_names = []
        k = 0
        for i in range(n_agents):
            ln = int(name_lens[i])
            agent_names.append(names_b[k:k + ln].decode("utf8"))
            k += ln
        return {
            "doc_id": (doc_id.tobytes()[:doc_bytes].decode("utf8")
                       if has_doc_id else None),
            "agent_names": agent_names,
            "agent_runs": (ar_agent[:n_aruns], ar_seq0[:n_aruns],
                           ar_n[:n_aruns]),
            "ops": (op_lv[:n_ops], op_kind[:n_ops], op_start[:n_ops],
                    op_end[:n_ops], op_fwd[:n_ops], op_known[:n_ops],
                    op_clen[:n_ops]),
            "ins_blob": ins_blob.tobytes()[:ins_bytes].decode("utf8"),
            "del_blob": del_blob.tobytes()[:del_bytes].decode("utf8"),
            "graph": (g_start[:n_graph], g_end[:n_graph], g_off,
                      g_par[:n_par]),
        }
    finally:
        lib.dt_decode_free(h)


def native_counters() -> Optional[dict]:
    """Process-global merge-kernel event counters from the C++ engine
    (SURVEY §5 structured counters; always on)."""
    lib = _load()
    if lib is None:
        return None
    buf = np.zeros(len(EVENT_COUNTER_NAMES), dtype=np.uint64)
    k = lib.dt_get_counters(buf, len(buf))
    return {n: int(buf[i])
            for i, n in enumerate(EVENT_COUNTER_NAMES[:int(k)])}


def reset_native_counters() -> None:
    lib = _load()
    if lib is not None:
        lib.dt_reset_counters()


def get_native_ctx(oplog) -> "NativeContext":
    """The oplog's cached NativeContext (created on first use)."""
    ctx = getattr(oplog, "_native_ctx", None)
    if ctx is None:
        ctx = NativeContext(oplog)
        oplog._native_ctx = ctx
    return ctx


def graph_rebuild_native(g_start, g_end, g_off, g_par):
    """Batch-apply graph.py push + _advance_known_run semantics to the
    decoder's graph rows in C++: (starts, ends, shadows, parents CSR,
    children CSR, roots, version) or None when native is unavailable or
    the rows are malformed (caller falls back to per-row push)."""
    lib = _load()
    if lib is None:
        return None
    n = len(g_start)
    npar = len(g_par)
    a = lambda x: np.ascontiguousarray(x, dtype=np.int64)  # noqa: E731
    one = np.zeros(1, np.int64)
    ms = np.empty(max(n, 1), np.int64)
    me = np.empty(max(n, 1), np.int64)
    msh = np.empty(max(n, 1), np.int64)
    pind = np.empty(n + 1, np.int64)
    pflat = np.empty(max(npar, 1), np.int64)
    cind = np.empty(n + 1, np.int64)
    cflat = np.empty(max(npar, 1), np.int64)
    croot = np.empty(max(n, 1), np.int64)
    crn = np.zeros(1, np.int64)
    ver = np.empty(max(n, 1), np.int64)
    vern = np.zeros(1, np.int64)
    m = lib.dt_graph_rebuild(
        n, a(g_start), a(g_end), a(g_off), a(g_par) if npar else one,
        ms, me, msh, pind, pflat, cind, cflat, croot, crn, ver, vern)
    if m < 0:
        return None
    k = int(m)
    return (ms[:k], me[:k], msh[:k], pind[:k + 1], pflat[:int(pind[k])],
            cind[:k + 1], cflat[:int(cind[k])], croot[:int(crn[0])],
            ver[:int(vern[0])])


def content_columns(oplog):
    """(cp, arena) columns in the exact layout dt_load_ops /
    dt_load_ins_arena expect: per-run insert-arena offset (-1 = no
    content) and the whole INS arena as utf-32 code points. Shared by
    NativeContext.sync and tools/dump_columns so the native loaders'
    arena invariants live in one place."""
    from ..text.op import INS
    runs = oplog.ops.runs
    cp = np.asarray(
        [r.content_pos[0] if r.content_pos is not None else -1
         for r in runs], dtype=np.int64)
    arena_str = oplog.ops._arenas[INS].get((0, oplog.ops.arena_len(INS)))
    arena = np.frombuffer(arena_str.encode("utf-32-le"), dtype=np.int32)
    if arena.size == 0:
        arena = np.zeros(1, dtype=np.int32)
    return cp, arena, len(arena_str)


def merge_native(oplog, init: str, from_frontier, merge_frontier):
    return get_native_ctx(oplog).merge_to_string(init, from_frontier,
                                                 merge_frontier)


def transform_native(oplog, from_frontier, merge_frontier):
    return get_native_ctx(oplog).transform(from_frontier, merge_frontier)
