"""Mesh-aware load shedding + per-tenant noisy-neighbor isolation.

Two independent admission gates, consulted at server ingress BEFORE a
mutation touches the oplog (a shed edit is never durable, so shedding
is a real load shield, not queue-depth theater):

  mesh gate     driven by the SLO burn state of the mesh-facing
                objectives: when `visibility_p99` burns — or the
                per-peer convergence lag (obs/journey.py lag rollup)
                exceeds its threshold — the mesh is falling behind on
                replication, and sheddable classes (bulk, catchup) are
                429'd with a `Retry-After` derived from the burn rate
                BEFORE interactive traffic degrades. A `warning` state
                defers instead of shedding: the work is admitted (and
                counted `deferred`) while the controller pins its
                deadlines to the ceiling.
  tenant gate   per-tenant token buckets refilled at `tenant_rate`
                ops/s. Tenants flagged hot by the top-K attribution
                sketch (obs/attrib.py: one tenant owning more than
                `hot_share` of attributed ops) refill at
                `isolation_factor` of that rate — a noisy neighbor
                exhausts its own bucket and gets 429s while everyone
                else's admission is untouched. The tenant gate applies
                to every class (isolating a tenant IS throttling its
                interactive traffic; the mesh gate alone never is).

Thread-safety: all state here is guarded by the owning controller's
`qos` witness lock — `refresh()` and `admit()` are only called with it
held (see controller.py). The policy itself takes no locks.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Optional, Tuple

from .classes import QosClass, default_classes

# classes a mesh-burn may shed, in shed order (catchup first: its own
# backlog is what anti-entropy retries are FOR; bulk next; interactive
# never — that ordering is the acceptance gate's "shed before
# interactive degrades" invariant)
_MESH_SIGNALS = ("visibility_p99",)


class TokenBucket:
    """Plain token bucket (externally synchronized)."""

    def __init__(self, rate: float, burst: float,
                 now: float = 0.0) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last = float(now)

    def take(self, now: float, n: float = 1.0) -> bool:
        if now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
            self._last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class ShedPolicy:
    def __init__(self, classes: Optional[Dict[str, QosClass]] = None,
                 metrics=None,
                 tenant_rate: float = 400.0,
                 tenant_burst: float = 800.0,
                 hot_share: float = 0.5,
                 isolation_factor: float = 0.25,
                 lag_threshold_s: float = 10.0,
                 clock=time.monotonic) -> None:
        self.classes = classes or default_classes()
        self.metrics = metrics
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = float(tenant_burst)
        self.hot_share = float(hot_share)
        self.isolation_factor = float(isolation_factor)
        self.lag_threshold_s = float(lag_threshold_s)
        self.clock = clock
        self._buckets: Dict[str, TokenBucket] = {}
        self._hot: frozenset = frozenset()
        self._mesh_state = "ok"
        self._mesh_why = ""
        self._retry_after = 0.0

    # ---- control-loop inputs (called from QosController.step) ------------

    def refresh(self, slo_rows: Iterable[dict],
                lag: Optional[Dict[str, dict]] = None,
                hot_tenants: Optional[Iterable[str]] = None) -> None:
        """Recompute the mesh gate from the latest SLO evaluation rows
        (obs/slo.py `evaluate()` dicts) + the per-peer convergence-lag
        rollup, and adopt the attribution pass's hot-tenant set."""
        state, why, burn = "ok", "", 0.0
        for row in slo_rows or ():
            if row.get("name") not in _MESH_SIGNALS:
                continue
            st = row.get("state", "ok")
            if st == "ok":
                continue
            if st == "burning" or state == "ok":
                state = st
                why = row["name"]
            burn = max(burn, float((row.get("fast") or {})
                                   .get("burn", 0.0) or 0.0))
        for peer, row in (lag or {}).items():
            if float(row.get("mean_s", 0.0) or 0.0) > self.lag_threshold_s:
                state, why = "burning", f"convergence_lag:{peer}"
                burn = max(burn, 2.0)
        self._mesh_state = state
        self._mesh_why = why
        # Retry-After from the burn rate: at the fast-window alert
        # threshold (burn ~14.4x) back off ~3.6s, scaling linearly and
        # clamped to [0.25s, 10s] — hotter burn, longer backoff.
        self._retry_after = min(10.0, max(0.25, 0.25 * burn)) \
            if state == "burning" else 0.0
        if hot_tenants is not None:
            hot = frozenset(hot_tenants)
            if hot != self._hot:
                self._hot = hot
                # changed isolation tier => rebuild on next take
                self._buckets.clear()

    def hot_tenants_from_attrib(self, attrib) -> frozenset:
        """Derive the hot-tenant set from the top-K sketch: tenants
        owning more than `hot_share` of attributed per-doc ops."""
        from .classes import tenant_of
        try:
            tops = attrib.top("doc", "ops", 16)
        except (KeyError, AttributeError):
            return frozenset()
        per: Dict[str, float] = {}
        total = 0.0
        for doc, count, _err in tops:
            total += count
            ten = tenant_of(doc)
            if ten is not None:
                per[ten] = per.get(ten, 0.0) + count
        if total <= 0:
            return frozenset()
        return frozenset(t for t, c in per.items()
                         if c / total > self.hot_share)

    # ---- admission gate ---------------------------------------------------

    def admit(self, cls: str, tenant: Optional[str] = None,
              now: Optional[float] = None) -> Tuple[bool, float, str]:
        """(admitted, retry_after_s, reason). reason is "" for a plain
        admit, "deferred" for an admit the caller should count as
        deferred (mesh warning), "mesh_burn"/"tenant" for rejects."""
        spec = self.classes.get(cls)
        sheddable = spec.sheddable if spec is not None else True
        if sheddable and self._mesh_state == "burning":
            if self.metrics is not None:
                self.metrics.bump_class(cls, "shed")
            return False, self._retry_after, f"mesh_burn:{self._mesh_why}"
        if tenant is not None:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                rate = self.tenant_rate * (self.isolation_factor
                                           if tenant in self._hot else 1.0)
                burst = self.tenant_burst * (self.isolation_factor
                                             if tenant in self._hot
                                             else 1.0)
                bucket = self._buckets[tenant] = TokenBucket(
                    rate, burst, now=self.clock() if now is None else now)
            if not bucket.take(self.clock() if now is None else now):
                if self.metrics is not None:
                    self.metrics.bump_class(cls, "shed")
                return False, max(1.0 / max(bucket.rate, 1e-9),
                                  0.05), "tenant"
        if sheddable and self._mesh_state == "warning":
            if self.metrics is not None:
                self.metrics.bump_class(cls, "deferred")
            return True, 0.0, "deferred"
        return True, 0.0, ""

    def snapshot(self) -> dict:
        return {"mesh_state": self._mesh_state,
                "mesh_why": self._mesh_why,
                "retry_after_s": round(self._retry_after, 3),
                "hot_tenants": sorted(self._hot),
                "tenant_buckets": len(self._buckets)}
