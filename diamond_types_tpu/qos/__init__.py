"""Adaptive admission: closed-loop QoS for batching, deadlines, shed.

The subsystem that closes ROADMAP item 1's loop from telemetry back to
admission:

  classes.py     the per-request class taxonomy (interactive / bulk /
                 catchup) + ingress classification and the tenant key
  controller.py  the periodic closed-loop controller publishing
                 per-(shard, class) effective flush deadlines (JiT
                 dynamic-batching law + hysteresis + floors/ceilings)
  shed.py        mesh-aware load shedding (429 + Retry-After from the
                 SLO burn rate) and per-tenant token-bucket isolation
  metrics.py     QosMetrics v1 — per-class counters double-written to
                 the live TimeSeries, rendered as dt_qos_* prom
                 families and stamped into scenario scorecards

Wired through serve/admission.py (per-class deadline lookup + depth
budgets; static trigger byte-identical when detached), serve/
scheduler.py (`attach_qos` + lifecycle), tools/server.py (`--qos`,
ingress classification, /debug/qos, 429 sheds) and workload/runner.py
(lane tagging + the `qos` scorecard block).
"""

from .classes import (QOS_CLASSES, QOS_HEADER, QOS_PRIORITY, QosClass,
                      classify_headers, default_classes, tenant_of)
from .controller import QosController
from .metrics import (QOS_CLASS_KEYS, QOS_CTL_KEYS, QosMetrics,
                      merge_snapshots)
from .shed import ShedPolicy, TokenBucket

__all__ = [
    "QOS_CLASSES", "QOS_HEADER", "QOS_PRIORITY", "QosClass",
    "classify_headers", "default_classes", "tenant_of",
    "QosController",
    "QOS_CLASS_KEYS", "QOS_CTL_KEYS", "QosMetrics", "merge_snapshots",
    "ShedPolicy", "TokenBucket",
]
