"""Closed-loop admission controller: telemetry in, deadlines out.

A periodic controller (own daemon thread) that closes ROADMAP item 1's
loop: it reads per-class arrival rates and flush-latency quantiles
from `Observability.ts`, SLO burn states from `Observability.slo`, the
per-peer convergence-lag rollup from `Observability.journey`, and the
hot-doc attribution sketch — and publishes per-(shard, class)
*effective* flush deadlines that `AdmissionQueue.due()` consults in
place of the static trigger.

The deadline law (Just-in-Time Dynamic Batching, arxiv 1904.07421):
the fused/mesh flush ladder only pays off when pow2 shape buckets are
full, so the marginal wait worth paying is the expected time for the
arrival process to deliver the docs still missing from the fullest
bucket:

  gap        = flush_docs - fullest_bucket_fill        (docs missing)
  fill_time  = gap / (class arrival rate per shard)
  target     = clamp(fill_time, floor, ceiling)   if fill_time fits
               floor                              otherwise

Light load (rate ~ 0): fill_time is unreachable, target drops to the
floor — lone docs flush immediately instead of paying the static
deadline for occupancy nobody needs. Heavy load: the size trigger
fires first and the deadline is moot. The interesting middle is where
stretching fills buckets. Guards stack on top of the law:

  * SLO guard — a class whose objective is non-ok is pinned to its
    floor (counted `floors`): latency SLOs always beat occupancy.
  * interactive latency budget — interactive's target is additionally
    capped at `ceiling - flush_p99` so queue wait + flush together fit
    inside the static deadline.
  * mesh-warning deferral — sheddable classes are pinned to their
    ceiling (counted `ceilings`) while the shed policy is in warning:
    maximum batching for the traffic we are deliberately deprioritizing.
  * hysteresis — targets are EMA-damped (`alpha`) and only re-published
    when they move more than `deadband` relative, so the deadline
    cannot thrash on a noisy rate estimate (decisions counted
    stretched/shrunk/held).

Locking: the controller owns the new `qos` witness rung, deliberately
BELOW `global` in the canonical order (qos(8) -> global(10)): `step()`
takes the qos lock first and may then take the scheduler's global lock
to read queue fill. The hot admission path never takes the qos lock —
`effective_deadline()` reads an immutable table published by atomic
reference swap, so `due()` under the global lock stays lock-free with
respect to the controller.
"""

from __future__ import annotations

import threading
import time
from contextlib import nullcontext
from typing import Dict, Optional, Tuple

from ..analysis.witness import make_lock
from .classes import QosClass, default_classes, with_base
from .metrics import QosMetrics
from .shed import ShedPolicy


class QosController:
    def __init__(self, classes: Optional[Dict[str, QosClass]] = None,
                 interval_s: float = 0.25,
                 alpha: float = 0.4,
                 deadband: float = 0.1,
                 rate_window_s: float = 5.0,
                 shed_opts: Optional[dict] = None,
                 clock=time.monotonic) -> None:
        self.classes = classes
        self.interval_s = float(interval_s)
        self.alpha = float(alpha)
        self.deadband = float(deadband)
        self.rate_window_s = float(rate_window_s)
        self.clock = clock
        self.metrics = QosMetrics()
        self.shed = ShedPolicy(classes=classes, metrics=self.metrics,
                               clock=clock, **(shed_opts or {}))
        self._qos_lock = make_lock("qos.controller", "qos")
        self.obs = None
        self.queue = None
        self._queue_lock = None
        self.n_shards = 1
        # published effective-deadline table: (shard, cls) -> seconds.
        # IMMUTABLE once published; replaced wholesale by step() so hot
        # paths read it without the qos lock.
        self._table: Dict[Tuple[int, str], float] = {}
        self._damped: Dict[Tuple[int, str], float] = {}
        self._forced_mesh: Optional[Tuple[str, float]] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---- wiring -----------------------------------------------------------

    def bind(self, queue, queue_lock=None,
             n_shards: Optional[int] = None) -> None:
        """Attach to a scheduler's AdmissionQueue (MergeScheduler.
        attach_qos calls this). Derives the class taxonomy from the
        queue's static deadline unless one was given explicitly."""
        self.queue = queue
        self._queue_lock = queue_lock
        self.n_shards = int(n_shards if n_shards is not None
                            else queue.n_shards)
        if self.classes is None:
            self.classes = default_classes(queue.flush_deadline_s)
        else:
            self.classes = with_base(self.classes,
                                     queue.flush_deadline_s)
        self.shed.classes = self.classes
        for cls, spec in self.classes.items():
            self.metrics.set_deadline(cls, spec.deadline_s)

    def attach_obs(self, obs) -> None:
        self.obs = obs
        if obs is not None:
            self.metrics.ts = getattr(obs, "ts", None)

    # ---- hot-path reads (lock-free) ---------------------------------------

    def effective_deadline(self, shard: int, cls: str) -> float:
        t = self._table
        v = t.get((shard, cls))
        if v is not None:
            return v
        spec = (self.classes or {}).get(cls)
        return spec.deadline_s if spec is not None else 0.05

    def depth_budget(self, cls: str, max_pending: int) -> int:
        spec = (self.classes or {}).get(cls)
        share = spec.depth_share if spec is not None else 1.0
        return max(int(max_pending * share), 1)

    # ---- admission gate ---------------------------------------------------

    def admit(self, cls: str, tenant: Optional[str] = None,
              now: Optional[float] = None) -> Tuple[bool, float, str]:
        """Ingress shed gate (tools/server consults this BEFORE the
        mutation touches the oplog). Returns (admitted, retry_after_s,
        reason); see ShedPolicy.admit."""
        with self._qos_lock:
            return self.shed.admit(cls, tenant=tenant, now=now)

    def force_mesh_state(self, state: Optional[str],
                         retry_after: float = 1.0) -> None:
        """Test/debug override pinning the shed policy's mesh gate
        (None releases it). Survives controller steps — refresh()
        re-applies the forced state after every telemetry read."""
        with self._qos_lock:
            self._forced_mesh = (state, retry_after) if state else None
            if state:
                self.shed._mesh_state = state
                self.shed._mesh_why = "forced"
                self.shed._retry_after = retry_after

    # ---- control loop -----------------------------------------------------

    def _bucket_fill(self, shard: int) -> int:
        q = self.queue
        if q is None:
            return 0
        return q.bucket_fill(shard)

    def step(self, now: Optional[float] = None) -> dict:
        """One control-loop iteration: read telemetry, refresh the
        shed gate, recompute + publish the deadline table. Returns the
        decisions taken (for tests and /debug/qos)."""
        now = self.clock() if now is None else now
        obs = self.obs
        with self._qos_lock:
            ts = getattr(obs, "ts", None) if obs is not None else None
            slo = getattr(obs, "slo", None) if obs is not None else None
            rows = slo.evaluate() if slo is not None else []
            states = {r.get("name"): r.get("state", "ok") for r in rows}
            journey = getattr(obs, "journey", None) \
                if obs is not None else None
            lag = journey.lag_summary() if journey is not None else None
            attrib = getattr(obs, "attrib", None) \
                if obs is not None else None
            hot = self.shed.hot_tenants_from_attrib(attrib) \
                if attrib is not None else None
            self.shed.refresh(rows, lag=lag, hot_tenants=hot)
            if self._forced_mesh is not None:
                st, ra = self._forced_mesh
                self.shed._mesh_state = st
                self.shed._mesh_why = "forced"
                self.shed._retry_after = ra
            mesh_state = self.shed._mesh_state
            flush_p99 = ts.quantile("serve.flush", 0.99, window_s=30.0) \
                if ts is not None else 0.0
            flush_docs = self.queue.flush_docs if self.queue is not None \
                else 8
            fills = []
            guard = self._queue_lock if self._queue_lock is not None \
                else nullcontext()
            with guard:
                for shard in range(self.n_shards):
                    fills.append(self._bucket_fill(shard))
            decisions = {"stretched": 0, "shrunk": 0, "held": 0,
                         "floors": 0, "ceilings": 0}
            table: Dict[Tuple[int, str], float] = {}
            for cls, spec in (self.classes or {}).items():
                lam = (ts.rate(f"qos.admitted.{cls}",
                               window_s=self.rate_window_s)
                       if ts is not None else 0.0)
                lam_shard = lam / max(self.n_shards, 1)
                cls_state = states.get(spec.objective, "ok")
                for shard in range(self.n_shards):
                    gap = max(flush_docs - fills[shard], 1)
                    if lam_shard > 1e-9:
                        fill_time = gap / lam_shard
                        target = spec.clamp(fill_time) \
                            if fill_time <= spec.ceiling_s \
                            else spec.floor_s
                    else:
                        target = spec.floor_s
                    if cls_state != "ok":
                        target = spec.floor_s
                        decisions["floors"] += 1
                    elif spec.sheddable and mesh_state == "warning":
                        target = spec.ceiling_s
                        decisions["ceilings"] += 1
                    if cls == "interactive" and flush_p99 > 0:
                        target = spec.clamp(
                            min(target, spec.ceiling_s - flush_p99))
                    key = (shard, cls)
                    prev = self._damped.get(key, spec.deadline_s)
                    damped = prev + self.alpha * (target - prev)
                    self._damped[key] = damped
                    published = self._table.get(key, spec.deadline_s)
                    if abs(damped - published) \
                            > self.deadband * max(published, 1e-9):
                        table[key] = damped
                        decisions["stretched" if damped > published
                                  else "shrunk"] += 1
                    else:
                        table[key] = published
                        decisions["held"] += 1
            self._table = table
            for cls in (self.classes or {}):
                per = [table[(s, cls)] for s in range(self.n_shards)]
                if per:
                    self.metrics.set_deadline(cls, sum(per) / len(per))
            self.metrics.bump_ctl("steps")
            for k, n in decisions.items():
                if n:
                    self.metrics.bump_ctl(k, n)
            return decisions

    # ---- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.step()
                except Exception:   # pragma: no cover - defensive
                    # the controller must never take admission down
                    # with it; a failed step keeps the last table
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="qos-controller")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    # ---- exposition -------------------------------------------------------

    def export(self) -> dict:
        """The /metrics + /debug/qos document: metrics snapshot plus
        the live controller/shed state. prom (obs/prom.py) renders the
        `classes` and `controller` keys as dt_qos_* families."""
        snap = self.metrics.snapshot()
        snap["enabled"] = True
        snap["running"] = self._thread is not None
        snap["interval_s"] = self.interval_s
        snap["n_shards"] = self.n_shards
        snap["shed"] = self.shed.snapshot()
        snap["specs"] = {
            cls: {"base_s": spec.deadline_s, "floor_s": spec.floor_s,
                  "ceiling_s": spec.ceiling_s,
                  "depth_share": spec.depth_share,
                  "objective": spec.objective,
                  "sheddable": spec.sheddable}
            for cls, spec in (self.classes or {}).items()}
        return snap
