"""QoS metrics: per-class admission counters + controller decisions.

Same contract as ServeMetrics (serve/metrics.py): a declared key
surface fixed at module scope, double-written into the live TimeSeries
when one is attached, rendered by obs/prom.py as zero-filled
`dt_qos_*{class}` families, and stamped into scenario scorecards as
the `qos` block. The metrics-schema-drift lint rule imports these
tuples directly, so a key bumped here that is not declared below is a
lint error, not a silently-unexported counter.

Schema versions:
  v1  per-class admitted/shed/deferred counters + deadline_s gauge;
      controller decision counters (steps/stretched/shrunk/held/
      floors/ceilings).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional

from .classes import QOS_CLASSES

# per-class admission counters (prom: dt_qos_<key>_total{class})
QOS_CLASS_KEYS = ("admitted", "shed", "deferred")

# controller decision counters (prom: dt_qos_controller_total{decision})
QOS_CTL_KEYS = ("steps", "stretched", "shrunk", "held", "floors",
                "ceilings")


class QosMetrics:
    SCHEMA_VERSION = 1

    def __init__(self, classes: Iterable[str] = QOS_CLASSES) -> None:
        self._lock = threading.Lock()
        self._classes = tuple(classes)
        self._counts: Dict[str, Dict[str, int]] = {
            c: {k: 0 for k in QOS_CLASS_KEYS} for c in self._classes}
        self._deadline_s: Dict[str, float] = {c: 0.0
                                              for c in self._classes}
        self._ctl: Dict[str, int] = {k: 0 for k in QOS_CTL_KEYS}
        # live-telemetry double-write target (obs.TimeSeries); set by
        # QosController.attach_obs. Series: qos.<key>.<class> — the
        # controller's arrival-rate estimator reads qos.admitted.<cls>
        # back out of this same table, closing the loop.
        self.ts = None

    def bump_class(self, cls: str, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[cls][key] += n
        ts = self.ts
        if ts is not None:
            ts.inc(f"qos.{key}.{cls}", n)

    def bump_ctl(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._ctl[key] += n
        ts = self.ts
        if ts is not None:
            ts.inc(f"qos.ctl.{key}", n)

    def set_deadline(self, cls: str, seconds: float) -> None:
        with self._lock:
            self._deadline_s[cls] = float(seconds)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "schema_version": self.SCHEMA_VERSION,
                "classes": {
                    c: {**self._counts[c],
                        "deadline_s": round(self._deadline_s[c], 6)}
                    for c in self._classes},
                "controller": dict(self._ctl),
            }


def merge_snapshots(snaps: Iterable[Optional[dict]]) -> Optional[dict]:
    """Sum per-class counters across servers (scorecard aggregation);
    deadline gauges take the max (the most-stretched server is the one
    the gate cares about). None snaps (qos-disabled servers) are
    skipped; all-None yields None so the scorecard block is omitted
    rather than fabricated."""
    out: Optional[dict] = None
    for snap in snaps:
        if not snap:
            continue
        if out is None:
            out = {"schema_version": snap.get("schema_version", 1),
                   "classes": {}, "controller": {}}
        for c, row in (snap.get("classes") or {}).items():
            dst = out["classes"].setdefault(
                c, {**{k: 0 for k in QOS_CLASS_KEYS}, "deadline_s": 0.0})
            for k in QOS_CLASS_KEYS:
                dst[k] += int(row.get(k, 0))
            dst["deadline_s"] = max(dst["deadline_s"],
                                    float(row.get("deadline_s", 0.0)))
        for k, v in (snap.get("controller") or {}).items():
            out["controller"][k] = out["controller"].get(k, 0) + int(v)
    return out
