"""Per-request QoS class taxonomy for the admission path.

Every mutation entering the serve tier carries one of three classes:

  interactive   a human editing session's keystrokes — the latency-
                sensitive class. Its flush deadline may only ever be
                TIGHTENED by the controller (ceiling = the static
                trigger), so adaptive batching can never push the
                interactive p99 past what the static trigger allowed.
  bulk          import/migration traffic — throughput-sensitive,
                latency-tolerant. The controller stretches its
                deadline (up to `ceiling_s`) to fill pow2 shape
                buckets, and it is the FIRST class shed when the mesh
                burns.
  catchup       anti-entropy / replication catch-up writes — the
                continuous-ingest class ("Formal Foundations of
                Continuous Graph Processing" framing): deprioritizable
                behind user traffic, but with a hard deadline ceiling
                so a loaded host still converges (catchup can be
                deferred, never starved).

Classification happens once, at server ingress (`tools/server.py`):
an explicit `X-DT-QoS` header wins; `X-DT-Replication` (host-targeted
anti-entropy) is heuristically `catchup`; everything else defaults to
`interactive`. Proxied writes re-send the header so the owner admits
under the original class. The class rides `AdmissionQueue` items from
there; per-tenant subclassing is the tenant dimension (`tenant_of`)
used by the shed policy's token buckets, not a fourth class.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, Optional

QOS_HEADER = "X-DT-QoS"

# canonical class names, in priority order (smaller index = more
# urgent; a coalescing re-submit keeps the more urgent class)
QOS_CLASSES = ("interactive", "bulk", "catchup")

QOS_PRIORITY = {name: i for i, name in enumerate(QOS_CLASSES)}


@dataclass(frozen=True)
class QosClass:
    """One class's admission contract. `deadline_s` is the static/base
    flush deadline; the controller publishes an *effective* deadline in
    [floor_s, ceiling_s] around it. `depth_share` bounds how much of a
    shard's `max_pending` this class may occupy (per-class queue-depth
    budget); `objective` names the SLO objective whose burn state
    guards this class (non-ok => the controller pins the class to its
    floor); `sheddable` marks classes the mesh-burn shed policy may
    429."""

    name: str
    deadline_s: float
    floor_s: float
    ceiling_s: float
    depth_share: float
    objective: str
    sheddable: bool

    def clamp(self, deadline_s: float) -> float:
        return min(max(deadline_s, self.floor_s), self.ceiling_s)


def default_classes(base_deadline_s: float = 0.05) -> Dict[str, QosClass]:
    """The default taxonomy, scaled from the queue's static flush
    deadline so a scheduler built with a non-default trigger keeps the
    same relative contract. Interactive's ceiling IS the static
    deadline: with the controller attached, interactive work can only
    flush earlier than the static trigger would have, never later."""
    b = float(base_deadline_s)
    return {
        "interactive": QosClass(
            "interactive", deadline_s=b, floor_s=b / 10.0, ceiling_s=b,
            depth_share=1.0, objective="flush_p99", sheddable=False),
        "bulk": QosClass(
            "bulk", deadline_s=5.0 * b, floor_s=b, ceiling_s=40.0 * b,
            depth_share=0.5, objective="queue_wait_p99", sheddable=True),
        "catchup": QosClass(
            "catchup", deadline_s=10.0 * b, floor_s=b,
            ceiling_s=100.0 * b, depth_share=0.25,
            objective="visibility_p99", sheddable=True),
    }


def with_base(classes: Dict[str, QosClass],
              base_deadline_s: float) -> Dict[str, QosClass]:
    """Rescale a taxonomy's interactive rung onto a queue's actual
    static deadline (bind-time adjustment; other classes keep their
    absolute contracts unless they came from default_classes)."""
    spec = classes.get("interactive")
    if spec is None or spec.deadline_s == base_deadline_s:
        return classes
    out = dict(classes)
    out["interactive"] = replace(
        spec, deadline_s=base_deadline_s,
        floor_s=min(spec.floor_s, base_deadline_s / 10.0),
        ceiling_s=base_deadline_s)
    return out


def classify_headers(headers) -> str:
    """Ingress classification: explicit `X-DT-QoS` header wins (unknown
    values fall back to interactive — a typo must not accidentally
    deprioritize a user edit); a host-targeted anti-entropy push
    (`X-DT-Replication`) is catchup."""
    explicit = headers.get(QOS_HEADER)
    if explicit:
        name = explicit.strip().lower()
        if name in QOS_PRIORITY:
            return name
    if headers.get("X-DT-Replication") is not None:
        return "catchup"
    return "interactive"


def tenant_of(doc_id: Optional[str]) -> Optional[str]:
    """The tenant namespace of a doc id under the workload grammar
    ("t{tenant}-..."), or None for ids outside it. This is the key the
    shed policy's per-tenant token buckets isolate on."""
    if not doc_id:
        return None
    head, sep, _rest = doc_id.partition("-")
    if sep and len(head) > 1 and head[0] == "t" and head[1:].isdigit():
        return head
    return None
