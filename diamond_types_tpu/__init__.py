"""diamond_types_tpu — a TPU-native rebuild of the diamond-types text CRDT.

A ground-up redesign of the capabilities of the reference Rust implementation
(jarrodhroberson/diamond-types): an append-only operation log over a causal
DAG ("time DAG"), branches as (version, content) checkpoints, and a merge
engine that transforms concurrent positional edits into a linear, replayable
stream.

Architecture (TPU-first, see SURVEY.md §7):
  - Host tier: columnar causal graph + op storage (numpy-backed), binary
    wire format, sync protocol. A C++ native core mirrors the hot host paths.
  - Device tier (JAX/XLA): batched merge kernels — conflict zones lowered to
    dense span tables, vmapped across documents, sharded over a device Mesh.

Public API mirrors the reference's stable list API (reference:
src/list/mod.rs:66-145): `OpLog`, `Branch`, `ListCRDT`.
"""

from .causalgraph.graph import Graph, ROOT, DiffFlag
from .causalgraph.agent import AgentAssignment
from .causalgraph.causal_graph import CausalGraph
from .core.frontier import frontier_from, frontier_eq
from .text.oplog import OpLog
from .text.branch import Branch
from .text.crdt import ListCRDT, merge_oplogs

__version__ = "0.1.0"


def load(data: bytes) -> OpLog:
    """Load a v1-format (.dt) oplog."""
    from .encoding.decode import load_oplog
    return load_oplog(data)


def save(oplog: OpLog, patch_since=None) -> bytes:
    """Encode an oplog (full snapshot, or a patch since a version)."""
    from .encoding.encode import ENCODE_FULL, ENCODE_PATCH, encode_oplog
    if patch_since is None:
        return encode_oplog(oplog, ENCODE_FULL)
    return encode_oplog(oplog, ENCODE_PATCH, from_version=patch_since)


__all__ = [
    "Graph", "ROOT", "DiffFlag", "AgentAssignment", "CausalGraph",
    "OpLog", "Branch", "ListCRDT", "merge_oplogs", "load", "save",
]
