"""diamond_types_tpu — a TPU-native rebuild of the diamond-types text CRDT.

A ground-up redesign of the capabilities of the reference Rust implementation
(jarrodhroberson/diamond-types): an append-only operation log over a causal
DAG ("time DAG"), branches as (version, content) checkpoints, and a merge
engine that transforms concurrent positional edits into a linear, replayable
stream.

Architecture (TPU-first, see SURVEY.md §7):
  - Host tier: columnar causal graph + op storage (numpy-backed), binary
    wire format, sync protocol. A C++ native core mirrors the hot host paths.
  - Device tier (JAX/XLA): batched merge kernels — conflict zones lowered to
    dense span tables, vmapped across documents, sharded over a device Mesh.

Public API mirrors the reference's stable list API (reference:
src/list/mod.rs:66-145): `OpLog`, `Branch`, `ListCRDT`.
"""

from .causalgraph.graph import Graph, ROOT, DiffFlag
from .causalgraph.agent import AgentAssignment
from .causalgraph.causal_graph import CausalGraph
from .core.frontier import frontier_from, frontier_eq
from .text.oplog import OpLog
from .text.branch import Branch
from .text.crdt import ListCRDT

__version__ = "0.1.0"

__all__ = [
    "Graph", "ROOT", "DiffFlag", "AgentAssignment", "CausalGraph",
    "OpLog", "Branch", "ListCRDT",
    "frontier_from", "frontier_eq",
]
