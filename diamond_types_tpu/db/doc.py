"""Experimental multi-CRDT documents: JSON-ish trees of Map / Register / Text.

Capability mirror of the reference's experimental OpLog/Branch layer
(reference: src/lib.rs:279-284 CRDTKind {Map, Register, Collection, Text},
src/oplog.rs — map_keys MV-registers, texts, tie_break_mv at oplog.rs:361-385;
src/branch.rs — checkout to a value tree with `conflicts_with` surfaced).

Model:
  * One causal graph orders every op in the document.
  * CRDTs are identified by the LV that created them; the root map is
    ROOT_CRDT (-1).
  * Map ops: set (map_id, key) to a CreateValue — a primitive or a fresh
    child CRDT. Each (map, key) is a multi-value register: the heads
    (dominator set) are all visible; the *active* value is chosen by the
    deterministic agent tie-break (max by (agent name, seq)), identical on
    every peer.
  * Text CRDTs reuse the full list merge engine.

Delta sync: `ops_since(version)` / `merge_ops(delta)` exchange JSON-safe op
payloads keyed by remote versions (capability of the reference's
SerializedOps, src/oplog.rs:489-611).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..causalgraph.causal_graph import CausalGraph
from ..listmerge.transform import TransformedOps
from ..text.op import DEL, INS, OpStore
from ..utils.rope import Rope

ROOT_CRDT = -1

KIND_MAP = "map"
KIND_REGISTER = "register"
KIND_TEXT = "text"
KIND_COLLECTION = "collection"

# CreateValue encodings (JSON-safe):
#   ("prim", value)        — None / bool / int / float / str
#   ("crdt", kind)         — create a new child CRDT of `kind`


class Doc:
    """The multi-CRDT oplog + checkout functions."""

    def __init__(self) -> None:
        self.cg = CausalGraph()
        # (crdt_id, key) -> list of (lv, CreateValue); heads tracked lazily
        self.map_keys: Dict[Tuple[int, str], List[Tuple[int, Any]]] = {}
        # text crdt id -> (OpStore, version list of that text's ops)
        self.texts: Dict[int, OpStore] = {}
        # LV -> ("map", crdt, key) | ("text", crdt) for remote re-export
        self.op_index: Dict[int, Tuple] = {}

    def get_or_create_agent_id(self, name: str) -> int:
        return self.cg.get_or_create_agent(name)

    @property
    def version(self) -> List[int]:
        return list(self.cg.version)

    # --- local edits -------------------------------------------------------

    def _map_set_internal(self, lv: int, crdt: int, key: str, value) -> None:
        self.map_keys.setdefault((crdt, key), []).append((lv, value))
        self.op_index[lv] = ("map", crdt, key)

    def map_set(self, agent: int, map_id: int, key: str, value) -> int:
        """Set a primitive value. Returns the op LV."""
        lv = self.cg.assign_local_op(agent, 1)[0]
        self._map_set_internal(lv, map_id, key, ("prim", value))
        return lv

    def map_create_crdt(self, agent: int, map_id: int, key: str, kind: str) -> int:
        """Create a child CRDT under a map key; returns its CRDT id (the LV)."""
        lv = self.cg.assign_local_op(agent, 1)[0]
        self._map_set_internal(lv, map_id, key, ("crdt", kind))
        if kind == KIND_TEXT:
            self.texts[lv] = OpStore()
        return lv

    def text_insert(self, agent: int, text_id: int, pos: int, content: str) -> int:
        store = self.texts[text_id]
        span = self.cg.assign_local_op(agent, len(content))
        store.push_op(span[0], INS, pos, pos + len(content), True, content)
        for v in range(span[0], span[1]):
            self.op_index[v] = ("text", text_id)
        return span[1] - 1

    def text_delete(self, agent: int, text_id: int, start: int, end: int) -> int:
        store = self.texts[text_id]
        span = self.cg.assign_local_op(agent, end - start)
        store.push_op(span[0], DEL, start, end, True, None)
        for v in range(span[0], span[1]):
            self.op_index[v] = ("text", text_id)
        return span[1] - 1

    # --- checkout ----------------------------------------------------------

    def _register_heads(self, entries: List[Tuple[int, Any]]) -> List[Tuple[int, Any]]:
        lvs = [lv for (lv, _) in entries]
        doms = set(self.cg.graph.find_dominators(sorted(lvs)))
        return [(lv, v) for (lv, v) in entries if lv in doms]

    def _register_resolve(self, heads: List[Tuple[int, Any]]) -> Tuple[int, Any]:
        """Deterministic winner (reference: oplog.rs:361-385 tie_break_mv)."""
        aa = self.cg.agent_assignment

        def sort_key(item):
            agent, seq = aa.local_to_agent_version(item[0])
            return (aa.get_agent_name(agent), seq)

        return max(heads, key=sort_key)

    def checkout_text(self, text_id: int) -> str:
        """Project the causal graph onto this text's op spans, then transform
        within the mini-DAG (reference: TextInfo::with_xf_iter,
        src/listmerge/merge.rs:954-987)."""
        from ..causalgraph.subgraph import subgraph
        from ..core.span import merge_spans
        store = self.texts[text_id]
        if not store.runs:
            return ""
        spans = merge_spans((r.lv, r.lv + len(r)) for r in store.runs)
        sub, proj = subgraph(self.cg.graph, spans, self.version)
        rope = Rope()
        xf = TransformedOps(sub, self.cg.agent_assignment, store, [], proj)
        for _lv, op, pos in xf:
            if pos is None:
                continue
            if op.kind == INS:
                content = store.get_run_content(op)
                rope.insert(pos, content if op.fwd else content[::-1])
            else:
                rope.delete(pos, len(op))
        return str(rope)

    def checkout_map(self, map_id: int) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for (crdt, key), entries in self.map_keys.items():
            if crdt != map_id:
                continue
            heads = self._register_heads(entries)
            lv, val = self._register_resolve(heads)
            out[key] = self._materialize(lv, val)
            if len(heads) > 1:
                out.setdefault("_conflicts", {})[key] = [
                    self._materialize(l, v) for (l, v) in heads
                    if l != lv]
        return out

    def _materialize(self, lv: int, val) -> Any:
        tag, payload = val
        if tag == "prim":
            return payload
        kind = payload
        if kind == KIND_TEXT:
            return self.checkout_text(lv)
        if kind in (KIND_MAP, KIND_COLLECTION):
            return self.checkout_map(lv)
        if kind == KIND_REGISTER:
            return None  # bare registers hold their value via map semantics
        raise ValueError(kind)

    def checkout(self) -> Dict[str, Any]:
        return self.checkout_map(ROOT_CRDT)

    # --- delta sync (SerializedOps equivalent) ------------------------------

    def ops_since(self, version: Sequence[int]) -> str:
        """JSON delta of everything not in `version`'s history
        (reference: src/oplog.rs:489 ops_since -> SerializedOps)."""
        _only_old, only_new = self.cg.graph.diff(version, self.cg.version)
        aa = self.cg.agent_assignment
        rows = []
        for (lo, hi) in only_new:
            pos = lo
            while pos < hi:
                agent, seq, n = aa.local_span_to_agent_span(pos, hi - pos)
                # split on graph runs so parents stay simple
                gi = self.cg.graph.find_idx(pos)
                n = min(n, self.cg.graph.ends[gi] - pos)
                parents = self.cg.graph.parents_at(pos)
                rparents = self.cg.local_to_remote_frontier(list(parents))
                # op payloads for [pos, pos+n)
                payloads = []
                v = pos
                while v < pos + n:
                    kind_entry = self.op_index[v]
                    if kind_entry[0] == "map":
                        _, crdt, key = kind_entry
                        val = next(val for (lv, val)
                                   in self.map_keys[(crdt, key)] if lv == v)
                        payloads.append(["map", self._crdt_ref(crdt), key, val])
                        v += 1
                    else:
                        _, crdt = kind_entry
                        store = self.texts[crdt]
                        run = store.runs[store.find_idx(v)]
                        take = min(run.lv + len(run), pos + n) - v
                        piece = store._slice_run(run, v - run.lv,
                                                 v - run.lv + take)
                        payloads.append([
                            "text", self._crdt_ref(crdt),
                            "ins" if piece.kind == INS else "del",
                            piece.start, piece.end, piece.fwd,
                            store.get_run_content(piece)])
                        v += take
                rows.append({
                    "agent": aa.get_agent_name(agent), "seq": seq,
                    "parents": rparents, "len": n, "ops": payloads,
                })
                pos += n
        return json.dumps(rows)

    def _crdt_ref(self, crdt: int):
        if crdt == ROOT_CRDT:
            return None
        agent, seq = self.cg.agent_assignment.local_to_agent_version(crdt)
        return [self.cg.agent_assignment.get_agent_name(agent), seq]

    def _crdt_deref(self, ref) -> int:
        if ref is None:
            return ROOT_CRDT
        agent = self.cg.agent_assignment.try_get_agent(ref[0])
        assert agent is not None
        return self.cg.agent_assignment.agent_version_to_lv(agent, ref[1])

    def merge_ops(self, delta: str) -> None:
        """Ingest a delta; already-known ops dedup via the causal graph
        (reference: src/oplog.rs:568 merge_ops)."""
        for row in json.loads(delta):
            agent = self.get_or_create_agent_id(row["agent"])
            parents = self.cg.remote_to_local_frontier(row["parents"])
            span = self.cg.merge_and_assign(parents, agent, row["seq"],
                                            row["len"])
            if span[1] == span[0]:
                continue  # fully known
            skip = row["len"] - (span[1] - span[0])
            lv = span[0]
            consumed = 0
            for payload in row["ops"]:
                if payload[0] == "map":
                    _, ref, key, val = payload
                    if consumed >= skip:
                        self._map_set_internal(lv, self._crdt_deref(ref), key,
                                               tuple(val))
                        if val[0] == "crdt" and val[1] == KIND_TEXT:
                            self.texts.setdefault(lv, OpStore())
                        lv += 1
                    consumed += 1
                else:
                    _, ref, kind_s, start, end, fwd, content = payload
                    n = end - start
                    crdt = self._crdt_deref(ref)
                    use = max(0, (consumed + n) - max(consumed, skip))
                    drop = n - use
                    if use > 0:
                        kind = INS if kind_s == "ins" else DEL
                        if drop:
                            from ..text.op import sub_op_loc
                            start, end = sub_op_loc(kind, start, end, fwd,
                                                    drop, n)
                            if content is not None:
                                content = content[drop:]
                        store = self.texts.setdefault(crdt, OpStore())
                        store.push_op(lv, kind, start, end, fwd, content)
                        for v in range(lv, lv + use):
                            self.op_index[v] = ("text", crdt)
                        lv += use
                    consumed += n
