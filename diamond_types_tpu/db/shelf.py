"""Shelf: a tiny state-based last-writer-wins JSON CRDT.

Capability mirror of the reference's `shelf` crate (reference:
crates/shelf/src/lib.rs:1-30): each value carries a version counter; merge
takes the higher version, recursing into dicts; ties resolve by comparing the
JSON encoding (deterministic on every peer).
"""

from __future__ import annotations

import json
from typing import Any, Tuple

Shelf = Tuple[Any, int]  # (value, version)


def new_shelf(value: Any = None) -> Shelf:
    return (value, 0)


def set_value(shelf: Shelf, value: Any) -> Shelf:
    return (value, shelf[1] + 1)


def set_key(shelf: Shelf, key: str, value: Any) -> Shelf:
    d, ver = shelf
    assert isinstance(d, dict)
    child = d.get(key, new_shelf())
    d = dict(d)
    d[key] = set_value(child, value)
    return (d, ver)


def merge(a: Shelf, b: Shelf) -> Shelf:
    av, an = a
    bv, bn = b
    if isinstance(av, dict) and isinstance(bv, dict) and an == bn:
        out = dict(av)
        for k, sub in bv.items():
            out[k] = merge(out[k], sub) if k in out else sub
        return (out, an)
    if an != bn:
        return a if an > bn else b
    # Same version, non-mergeable values: deterministic JSON tie-break.
    return a if json.dumps(av, sort_keys=True) >= json.dumps(bv, sort_keys=True) else b


def get(shelf: Shelf) -> Any:
    v = shelf[0]
    if isinstance(v, dict):
        return {k: get(sub) for k, sub in v.items()}
    return v
