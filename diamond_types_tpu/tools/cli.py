"""Command-line tool for .dt files.

Capability mirror of the reference dt-cli (reference:
crates/dt-cli/src/main.rs:34-166 — create/cat/log/version/set/repack,
export.rs, git.rs git-import, dot.rs graphviz export).

Usage: python -m diamond_types_tpu.tools.cli <command> [...]
"""

from __future__ import annotations

import argparse
import difflib
import json
import os
import subprocess
import sys
import time
import uuid

from ..encoding.decode import load_oplog
from ..encoding.encode import ENCODE_FULL, EncodeOptions, encode_oplog
from ..text.op import DEL, INS
from ..text.oplog import OpLog


def _read_oplog(path: str) -> OpLog:
    with open(path, "rb") as f:
        return load_oplog(f.read())


def _write_oplog(path: str, ol: OpLog, opts: EncodeOptions = ENCODE_FULL) -> None:
    with open(path, "wb") as f:
        f.write(encode_oplog(ol, opts))


def _rand_agent() -> str:
    return uuid.uuid4().hex[:12]


def _apply_diff(ol: OpLog, agent: int, parents, old: str, new: str):
    """Apply old->new as insert/delete ops (reference: dt-cli set / git.rs)."""
    sm = difflib.SequenceMatcher(a=old, b=new, autojunk=False)
    # Apply from the end so earlier positions stay valid.
    version = list(parents)
    for tag, i1, i2, j1, j2 in reversed(sm.get_opcodes()):
        if tag == "equal":
            continue
        if tag in ("replace", "delete") and i2 > i1:
            version = [ol.add_delete_at(agent, version, i1, i2, old[i1:i2])]
        if tag in ("replace", "insert") and j2 > j1:
            version = [ol.add_insert_at(agent, version, i1, new[j1:j2])]
    return version


def cmd_create(args) -> int:
    if os.path.exists(args.filename) and not args.force:
        print(f"{args.filename} exists (use --force)", file=sys.stderr)
        return 1
    ol = OpLog()
    if args.content is not None:
        agent = ol.get_or_create_agent_id(args.agent or _rand_agent())
        ol.add_insert_at(agent, [], 0, args.content)
    _write_oplog(args.filename, ol)
    return 0


def cmd_cat(args) -> int:
    ol = _read_oplog(args.filename)
    version = json.loads(args.version) if args.version else ol.version
    out = ol.checkout(version).snapshot()
    if args.output:
        with open(args.output, "w") as f:
            f.write(out)
    else:
        sys.stdout.write(out)
    return 0


def cmd_log(args) -> int:
    ol = _read_oplog(args.filename)
    if args.history:
        for (lv0, lv1, parents, agent, seq) in ol.cg.iter_entries():
            name = ol.cg.agent_assignment.get_agent_name(agent)
            print(json.dumps({"span": [lv0, lv1], "parents": list(parents),
                              "agent": name, "seq": seq}))
        return 0
    if args.transformed:
        for (span, op, content) in ol.iter_xf_operations():
            if op is None:
                continue
            row = {"kind": "ins" if op.kind == INS else "del",
                   "start": op.start, "end": op.end, "fwd": op.fwd}
            if content is not None:
                row["content"] = content
            print(json.dumps(row))
        return 0
    for run in ol.ops.runs:
        row = {"lv": run.lv, "kind": "ins" if run.kind == INS else "del",
               "start": run.start, "end": run.end, "fwd": run.fwd}
        c = ol.ops.get_run_content(run)
        if c is not None:
            row["content"] = c
        print(json.dumps(row))
    return 0


def cmd_version(args) -> int:
    ol = _read_oplog(args.filename)
    print(json.dumps(ol.cg.local_to_remote_frontier(ol.version)))
    return 0


def cmd_set(args) -> int:
    ol = _read_oplog(args.filename)
    agent = ol.get_or_create_agent_id(args.agent or _rand_agent())
    old = ol.checkout_tip().snapshot()
    new = args.content if args.content is not None else sys.stdin.read()
    _apply_diff(ol, agent, ol.version, old, new)
    _write_oplog(args.filename, ol)
    return 0


def cmd_repack(args) -> int:
    ol = _read_oplog(args.filename)
    before = os.path.getsize(args.filename)
    _write_oplog(args.filename, ol)
    after = os.path.getsize(args.filename)
    print(f"{before} -> {after} bytes")
    return 0


def cmd_export(args) -> int:
    """Cross-CRDT benchmark JSON export (reference: dt-cli export.rs)."""
    ol = _read_oplog(args.filename)
    txns = []
    for (lv0, lv1, parents, agent, seq) in ol.cg.iter_entries():
        name = ol.cg.agent_assignment.get_agent_name(agent)
        patches = []
        for piece in ol.ops.iter_range((lv0, lv1)):
            content = ol.ops.get_run_content(piece) or ""
            if piece.kind == INS:
                patches.append([piece.start, 0, content])
            else:
                patches.append([piece.start, len(piece), ""])
        txns.append({
            "parents": [list(p) for p in
                        (ol.cg.local_to_remote_frontier(list(parents)))],
            "agent": name, "seqStart": seq, "patches": patches,
        })
    doc = {"kind": "concurrent", "endContent": ol.checkout_tip().snapshot(),
           "txns": txns}
    json.dump(doc, sys.stdout)
    return 0


def cmd_dot(args) -> int:
    """Graphviz export of the causal graph (reference: dt-cli dot.rs,
    src/causalgraph/dot.rs)."""
    ol = _read_oplog(args.filename)
    g = ol.cg.graph
    print("digraph dt {")
    print('  rankdir="BT";')
    for i in range(len(g)):
        label = f"{g.starts[i]}..{g.ends[i] - 1}"
        print(f'  n{i} [label="{label}"];')
        if not g.parents[i]:
            print(f"  n{i} -> root;")
        for p in g.parents[i]:
            print(f"  n{i} -> n{g.find_idx(p)};")
    print("}")
    return 0


def cmd_git_import(args) -> int:
    """Replay a file's git history into a DT doc (reference: dt-cli git.rs):
    each commit becomes an edit run by its author, parented on its git
    parents' versions — reproducing real high-fanout causal DAGs."""
    repo = args.repo or "."
    path = args.path

    log = subprocess.run(
        ["git", "-C", repo, "log", "--follow", "--reverse",
         "--format=%H %P", "--", path],
        capture_output=True, text=True, check=True).stdout
    commits = []
    for line in log.splitlines():
        parts = line.split()
        commits.append((parts[0], parts[1:]))

    ol = OpLog()
    versions = {}   # commit hash -> (frontier, content)
    known = set(h for h, _ in commits)
    for h, parents in commits:
        parents = [p for p in parents if p in known and p in versions]
        author = subprocess.run(
            ["git", "-C", repo, "show", "-s", "--format=%ae", h],
            capture_output=True, text=True, check=True).stdout.strip()
        blob = subprocess.run(
            ["git", "-C", repo, "show", f"{h}:{path}"],
            capture_output=True, text=True).stdout
        if not parents:
            base_frontier, base_content = [], ""
        elif len(parents) == 1:
            base_frontier, base_content = versions[parents[0]]
        else:
            merged = []
            for p in parents:
                merged = ol.cg.graph.version_union(merged, versions[p][0])
            base_frontier = merged
            base_content = ol.checkout(merged).snapshot()
        agent = ol.get_or_create_agent_id(author or "unknown")
        v = _apply_diff(ol, agent, base_frontier, base_content, blob)
        versions[h] = (v if v != list(base_frontier) else base_frontier, blob)

    _write_oplog(args.out, ol)
    final = ol.checkout_tip().snapshot()
    print(f"imported {len(commits)} commits, {len(ol)} ops -> {args.out} "
          f"({os.path.getsize(args.out)} bytes); final doc {len(final)} chars")
    return 0


def cmd_serve_bench(args) -> int:
    """Replay a trace corpus through the serve/ merge scheduler on N
    simulated shards, byte-parity-gated against the single-engine merge
    (see serve/driver.py). Exits nonzero on any parity mismatch."""
    if not args.real_device:
        # simulated shards: pin the CPU platform BEFORE any backend
        # init and force a virtual device count covering the shards
        # (same discipline as __graft_entry__.dryrun_multichip — the
        # site hooks can otherwise block on a wedged accelerator tunnel)
        import re
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{max(args.shards, 2)}").strip()
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except ImportError:
            pass
    from ..serve.driver import run_serve_bench
    kw = dict(shards=args.shards, docs=args.docs, txns=args.txns,
              engine=args.engine, mode=args.mode, corpus=args.corpus,
              flush_docs=args.flush_docs,
              flush_deadline_s=args.flush_deadline,
              max_pending=args.max_pending,
              max_sessions=args.max_sessions, seed=args.seed,
              fused=args.fused, flush_workers=args.workers,
              warmup=args.warmup, steady_rounds=args.steady_rounds,
              mesh_window=args.mesh_window, telemetry=args.telemetry,
              journey=args.journey,
              device_plan=args.device_plan, pallas=args.pallas,
              steer=args.steer, device_stage=args.device_stage)
    if args.dry_run:
        # CI smoke preset: host engine, tiny workload, no jax needed
        kw.update(shards=2, docs=4, txns=6, engine="host",
                  place_on_devices=False)
    report = run_serve_bench(**kw)
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            json.dump(report, f, indent=1)
    if args.json:
        print(json.dumps(report))
    else:
        m = report["metrics"]
        print(f"serve-bench: {report['config']['docs']} docs / "
              f"{report['config']['shards']} shards "
              f"({report['config']['engine']} engine, "
              f"{report['config']['mode']} mode, "
              f"fused={'on' if report['config'].get('fused') else 'off'}): "
              f"{report['total_ops']} ops in {report['wall_s']}s "
              f"({report['ops_per_sec']} ops/s), "
              f"occupancy {m['batch_occupancy']}, "
              f"fused calls {report['fused_device_calls']} "
              f"@ {report['fused_occupancy']} docs/call, "
              f"{report['device_calls_per_window']} device calls/"
              f"window, "
              f"jit hit rate "
              f"{report.get('jit_hit_rate') if report.get('jit_hit_rate') is not None else 'n/a'}"
              + (f" (steady {report['steady_jit_hit_rate']})"
                 if report.get("steady_jit_hit_rate") is not None
                 else "")
              + f", staged {report.get('staged_bytes_per_window', 0)} "
              f"B/window, "
              f"parity {'OK' if report['parity_ok'] else 'MISMATCH'}, "
              + ("slo OK" if report["slo_ok"] else
                 "slo BURNING " + ",".join(report["slo"]["burning"])))
    # a bench that converges byte-for-byte but burned its latency
    # budget is still a failing bench — slo_ok rides the exit code
    return 0 if (report["parity_ok"] and report["slo_ok"]) else 1


def cmd_replicate_soak(args) -> int:
    """N in-process sync servers in one fault-injected replication
    mesh: drive edits through drops/partitions, heal, reconcile, and
    gate on byte-identical convergence (see replicate/soak.py)."""
    from ..replicate.soak import run_replicate_soak
    report = run_replicate_soak(
        servers=args.servers, docs=args.docs, rounds=args.rounds,
        edits_per_round=args.edits_per_round, seed=args.seed,
        drop_rate=args.drop_rate, dup_rate=args.dup_rate,
        partition_rounds=args.partition_rounds,
        reconcile_rounds=args.reconcile_rounds,
        lease_ttl_s=args.lease_ttl, serve_shards=args.serve_shards,
        crash=args.crash, asym=args.asym, churn=args.churn,
        witness=args.witness, progress=args.progress)
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            json.dump(report, f, indent=1)
    if args.json:
        print(json.dumps(report))
    else:
        print(f"replicate-soak: {report['config']['servers']} servers / "
              f"{report['config']['docs']} docs, "
              f"{report['edits_applied']} edits through "
              f"{report['faults']['drops']} drops + "
              f"{report['faults']['partition_blocks']} partition blocks "
              f"in {report['wall_s']}s: "
              f"{'CONVERGED' if report['converged'] else 'DIVERGED'}"
              + (f" after {report['converged_after_reconcile_rounds']} "
                 f"reconcile rounds"
                 if report["converged_after_reconcile_rounds"] else "")
              + (f", {report['crashes']} crash-restarts" if
                 report["crashes"] else "")
              + (", split-brain: "
                 + ("NONE" if report["zero_split_brain"]
                    else ",".join(report["split_brain"])))
              + ((", lock-witness: "
                  + ("ACYCLIC" if report["lock_witness"]["acyclic"]
                     else "CYCLIC " + ";".join(
                         report["lock_witness"]["cycles"]))
                  + f" ({report['lock_witness']['edge_count']} edges, "
                  f"{report['lock_witness']['acquires']} acquires)")
                 if "lock_witness" in report else ""))
    return 0 if (report["converged"] and report["zero_split_brain"]
                 and report.get("lock_witness",
                                {}).get("acyclic", True)) else 1


def cmd_rebalance_soak(args) -> int:
    """Flash-crowd elastic-mesh soak: a hot doc saturates its owner,
    the SLO burns, and the rebalancer must migrate the doc (epoch-
    fenced handoff + placement override), absorb a mid-run join, roll
    back a seeded failed migration, and return the SLO to ok — all
    without operator action (see replicate/rebalance_soak.py).

    With --split-hot-doc, runs the writer-group arm instead: the
    rebalancer promotes the hot doc to a 2-writer group under
    sustained burn (>= 2x admission, member accepting locally), then
    member-crash and asymmetric-partition demotions must drain back to
    one writer cleanly with zero acked-loss and zero split-brain."""
    from ..replicate.rebalance_soak import run_rebalance_soak
    if args.split_hot_doc:
        from ..replicate.rebalance_soak import run_split_soak
        report = run_split_soak(servers=args.servers, seed=args.seed,
                                progress=args.progress)
        if args.metrics_out:
            with open(args.metrics_out, "w") as f:
                json.dump(report, f, indent=1)
        if args.json:
            print(json.dumps(report))
        else:
            s, g = report["single_writer"], report["writer_group"]
            print(f"rebalance-soak --split-hot-doc: "
                  f"{report['config']['servers']} servers, "
                  f"hot doc {report['hot_doc']}: "
                  f"single {s['acked']} acked "
                  f"({s['rate_per_s']}/s) -> group {g['acked']} "
                  f"acked ({g['rate_per_s']}/s), "
                  f"speedup {report['speedup']}x, "
                  f"member-crash demote "
                  + ("OK" if report["member_crash"]
                     and all(report["member_crash"].values())
                     else "BROKEN")
                  + ", partition-minority demote "
                  + ("OK" if report["partition_minority"]
                     and all(report["partition_minority"].values())
                     else "BROKEN")
                  + f", acked-loss: {len(report['lost_markers'])}"
                  + ", split-brain: "
                  + ("NONE" if report["zero_split_brain"]
                     else ",".join(report["split_brain"]))
                  + f" in {report['wall_s']}s: "
                  + ("CONVERGED" if report["converged"]
                     else "DIVERGED")
                  + (" OK" if report["ok"] else " FAILED"))
        return 0 if report["ok"] else 1
    report = run_rebalance_soak(
        servers=args.servers, docs=args.docs, seed=args.seed,
        capacity=args.capacity, crowd_boost=args.crowd_boost,
        flash_crowd=args.flash_crowd, join=args.join,
        inject_abort=args.inject_abort, progress=args.progress)
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            json.dump(report, f, indent=1)
    if args.json:
        print(json.dumps(report))
    else:
        journey = " -> ".join(
            s for i, s in enumerate(report["slo_states"])
            if i == 0 or s != report["slo_states"][i - 1]) or "ok"
        print(f"rebalance-soak: {report['config']['servers']}+"
              f"{1 if report['joined'] else 0} servers / "
              f"{report['config']['docs']} docs, "
              f"{report['edits_applied']} edits, slo {journey}, "
              f"{len(report['migrations'])} migrations"
              + (f", join absorbed" if report["joined"]
                 and report["join_absorbed"] else "")
              + (", abort rollback "
                 + ("OK" if report["abort_rollback_ok"] else "BROKEN")
                 if report["abort_rollback_ok"] is not None else "")
              + ", split-brain: "
              + ("NONE" if report["zero_split_brain"]
                 else ",".join(report["split_brain"]))
              + f" in {report['wall_s']}s: "
              + ("CONVERGED" if report["converged"] else "DIVERGED")
              + (" OK" if report["ok"] else " FAILED"))
    return 0 if report["ok"] else 1


def cmd_storage_soak(args) -> int:
    """Churn docs through an undersized residency tier (cold snapshot
    store -> warm hydrator -> scheduler) with seeded fault injection —
    crash-restart, crash-mid-compaction, torn tails, wholesale
    corruption, slow disk — and gate on byte-identical re-hydration,
    exact quarantine containment, zero flush leaks and bounded
    cold-start p99 (see storage/soak.py)."""
    from ..storage.soak import run_storage_soak
    report = run_storage_soak(
        docs=args.docs, warm=args.warm, rounds=args.rounds,
        edits_per_round=args.edits_per_round, shards=args.shards,
        seed=args.seed, compact_every=args.compact_every,
        churn=args.churn, crash=args.crash, slow=args.slow,
        data_dir=args.data_dir, p99_budget_s=args.p99_budget,
        progress=args.progress)
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            json.dump(report, f, indent=1)
    if args.json:
        print(json.dumps(report))
    else:
        cold = report["cold_start"]
        wit = report["lock_witness"]
        print(f"storage-soak: {report['config']['docs']} docs / "
              f"{report['config']['warm']} warm slots, "
              f"{report['edits']} edits, "
              f"{report['rehydrations']} re-hydrations "
              f"({report['byte_mismatches']} byte mismatches), "
              f"quarantine {'EXACT' if report['quarantine_match'] else 'MISMATCH'} "
              f"({len(report['quarantined'])} docs, "
              f"{report['quarantine_leaks']} flush leaks), "
              f"cold-start p99 {cold['p99'] * 1e3:.1f}ms"
              f"{' OK' if report['p99_ok'] else ' OVER BUDGET'}"
              + (f", {report['crashes']} crash-restarts, "
                 f"{report['compaction_kills']} compaction kills, "
                 f"{report['torn_tails']} torn tails"
                 if report["config"]["crash"] else "")
              + ", lock-witness "
              + ("ACYCLIC" if wit["acyclic"] and not wit["violation_count"]
                 else "VIOLATED")
              + f" in {report['wall_s']}s: "
              + ("OK" if report["ok"] else "FAILED"
                 + (f" ({report['error']})" if "error" in report else "")))
    return 0 if report["ok"] else 1


def cmd_read_bench(args) -> int:
    """Two-server follower-read A/B bench: Zipf-skewed readers across
    both nodes, control phase (max_staleness=0: every follower read
    proxies to the owner) vs follower phase (bounded staleness served
    locally), with client-side verification of both the staleness
    bound and the read-your-writes token (see read/bench.py)."""
    from ..read.bench import run_read_bench
    report = run_read_bench(
        docs=args.docs, readers=args.readers,
        reads_per_reader=args.reads_per_reader, seed=args.seed,
        zipf_s=args.zipf_s, max_staleness_s=args.max_staleness,
        min_version_every=args.min_version_every,
        lease_ttl_s=args.lease_ttl, serve_shards=args.serve_shards,
        doc_bytes=args.doc_bytes,
        min_speedup=args.min_speedup, progress=args.progress)
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            json.dump(report, f, indent=1)
    if args.json:
        print(json.dumps(report))
    else:
        c, fo = report["control"], report["follower"]
        print(f"read-bench: {report['config']['docs']} docs / "
              f"{report['config']['readers']} readers x "
              f"{report['config']['reads_per_reader']} reads, "
              f"{report['writes']} writes riding along: "
              f"control {c['reads_per_s']} reads/s "
              f"({c['proxied']} proxied), "
              f"follower {fo['reads_per_s']} reads/s "
              f"({fo['local']} local, max staleness "
              f"{fo['max_observed_staleness_s'] * 1e3:.0f}ms), "
              f"speedup {report['speedup']}x, "
              f"{report['violations']} contract violations, "
              f"{report['errors']} errors in {report['wall_s']}s: "
              + ("OK" if report["ok"] else "FAILED"))
    return 0 if report["ok"] else 1


def wire_bench(seed: int = 7, n_ops: int = 2000, agents: int = 8,
               docs: int = 64) -> dict:
    """Wire-frame codec micro-benchmark: a deterministic churn op tape
    (unicode-heavy inserts/deletes, churning agent names) measured
    through each frame codec against its JSON twin. Returns the row
    `cli wire-bench` prints and bench.py ingests alongside serve_sched
    (encode/decode ops/sec + bytes-on-the-wire ratios)."""
    import random
    import time as _time
    from ..causalgraph.summary import summarize_versions
    from ..encoding.encode import ENCODE_FULL, encode_oplog
    from ..text.oplog import OpLog
    from ..wire.frames import (FRAME_DOCS, FRAME_OPS, FRAME_PATCH,
                               FRAME_SUMMARY, decode_frame, decode_docs,
                               decode_ops, decode_summary, encode_docs,
                               encode_frame, encode_ops, encode_summary)
    rng = random.Random(f"wire-bench:{seed}")
    alphabet = "etaoin shrdluéß世界\U0001f600"

    # ---- churn tape: edit bodies exactly as the proxy channel sees them
    reqs, doc_len = [], 0
    for i in range(n_ops):
        agent = f"t0s{i % agents}g{i // 97}"
        if doc_len > 8 and rng.random() < 0.3:
            start = rng.randrange(doc_len - 4)
            end = min(doc_len, start + 1 + rng.randrange(4))
            ops = [{"kind": "del", "start": start, "end": end}]
            doc_len -= end - start
        else:
            text = "".join(rng.choice(alphabet)
                           for _ in range(1 + rng.randrange(8)))
            pos = rng.randrange(doc_len + 1)
            ops = [{"kind": "ins", "pos": pos, "text": text}]
            doc_len += len(text)
        reqs.append({"agent": agent, "version": [[agent, max(i - 1, 0)]],
                     "ops": ops})

    t0 = _time.perf_counter()
    frames = [encode_frame(FRAME_OPS, encode_ops(r), compress=True)
              for r in reqs]
    t_enc = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    back = [decode_ops(decode_frame(f)[1]) for f in frames]
    t_dec = _time.perf_counter() - t0
    if back != reqs:
        raise AssertionError("wire-bench: OPS tape did not round-trip")
    json_bytes = sum(len(json.dumps(r).encode("utf8")) for r in reqs)
    frame_bytes = sum(len(f) for f in frames)
    row = {"tape": {"n_ops": n_ops, "agents": agents, "seed": seed},
           "ops": {
               "encode_per_sec": round(n_ops / max(t_enc, 1e-9)),
               "decode_per_sec": round(n_ops / max(t_dec, 1e-9)),
               "json_bytes": json_bytes, "frame_bytes": frame_bytes,
               "ratio": round(json_bytes / max(frame_bytes, 1), 2)}}

    # ---- summary frame: replay the tape into an oplog, frame its
    # version summary (what every anti-entropy handshake exchanges)
    ol = OpLog()
    for r in reqs:
        a = ol.get_or_create_agent_id(r["agent"])
        frontier = list(ol.version)
        op = r["ops"][0]
        if op["kind"] == "ins":
            ol.add_insert_at(a, frontier, op["pos"], op["text"])
        else:
            ol.add_delete_at(a, frontier, op["start"], op["end"], None)
    summary = summarize_versions(ol.cg)
    sj = json.dumps(summary).encode("utf8")
    t0 = _time.perf_counter()
    sf = encode_frame(FRAME_SUMMARY, encode_summary(summary),
                      compress=True)
    t_senc = _time.perf_counter() - t0
    if decode_summary(decode_frame(sf)[1]) != summary:
        raise AssertionError("wire-bench: summary did not round-trip")
    row["summary"] = {"agents": len(summary),
                      "json_bytes": len(sj), "frame_bytes": len(sf),
                      "ratio": round(len(sj) / max(len(sf), 1), 2),
                      "encode_s": round(t_senc, 6)}

    # ---- patch frame: the full encode under the lz4 envelope
    patch = encode_oplog(ol, ENCODE_FULL)
    pf = encode_frame(FRAME_PATCH, patch, compress=True)
    row["patch"] = {"raw_bytes": len(patch), "frame_bytes": len(pf),
                    "ratio": round(len(patch) / max(len(pf), 1), 2)}

    # ---- docs listing frame: the steady-state anti-entropy preamble
    listing = {"self": "127.0.0.1:8001", "docs": {
        f"t{d % 4}-doc{d:03d}": {
            "lease": {"holder": f"127.0.0.1:{8001 + d % 3}",
                      "epoch": 1 + d % 5, "state": "active",
                      "ttl_s": 0.9},
            "frontier": [[f"t0s{d % agents}g{d % 7}", d]],
        } for d in range(docs)}}
    lj = json.dumps(listing).encode("utf8")
    lf = encode_frame(FRAME_DOCS, encode_docs(listing), compress=True)
    rt = decode_docs(decode_frame(lf)[1])
    if rt["docs"] != listing["docs"] or rt["self"] != listing["self"]:
        raise AssertionError("wire-bench: docs listing did not "
                             "round-trip")
    row["docs"] = {"n_docs": docs, "json_bytes": len(lj),
                   "frame_bytes": len(lf),
                   "ratio": round(len(lj) / max(len(lf), 1), 2)}
    return row


def cmd_wire_bench(args) -> int:
    """Wire-frame codec micro-benchmark (see wire_bench)."""
    row = wire_bench(seed=args.seed, n_ops=args.ops,
                     agents=args.agents, docs=args.docs)
    print(json.dumps(row, indent=1 if args.json else None))
    return 0


def cmd_dt_lint(args) -> int:
    """Concurrency invariant lint (analysis/): lock-order violations,
    unsorted multi-lock acquisition, device dispatch under the
    global/oplog lock, unfenced doc-state mutation on write paths, and
    jit-purity checks. Exit 0 = clean tree (the tier-1 gate)."""
    from ..analysis import lint as _lint
    report = _lint.run_lint(paths=args.paths or None,
                            disable=args.disable)
    _lint.publish_report(report)
    if args.json:
        print(_lint.render_json(report))
    else:
        print(_lint.render_human(report))
    if args.fail_on == "error":
        return 1 if report["errors"] else 0
    return 0 if report["ok"] else 1


def _render_explore_human(rep: dict) -> str:
    head = (f"dt-explore {rep['scenario']}: depth {rep['depth']} "
            f"states {rep['states']} "
            f"(dedup {rep['dedup_hits']}, sleep {rep['sleep_skips']}) "
            f"{rep['states_per_s']} states/s "
            f"{'complete' if rep['complete'] else 'TRUNCATED'}"
            + (f" mutation={rep['mutation']}" if rep['mutation'] else "")
            + (": OK" if rep["ok"] else ": VIOLATION"))
    lines = [head]
    for v in rep["violations"]:
        lines.append(f"  {v['invariant']}: {v['message']}")
        trace = " -> ".join(
            a["op"] + "(" + ",".join(
                str(a[k]) for k in ("node", "peer", "doc") if k in a)
            + ")" for a in v["minimized_trace"])
        lines.append(f"  minimized trace ({len(v['minimized_trace'])} "
                     f"steps): {trace or '<initial state>'}")
    return "\n".join(lines)


def cmd_dt_explore(args) -> int:
    """Protocol model checker (analysis/explore/): exhaustively
    enumerate scheduler interleavings of the real lease/quorum/fencing
    code to a bounded depth, checking safety invariants at every state.
    Exit 0 = no violation reachable within the bounds (or, with
    --mutate, every seeded protocol mutation detected)."""
    from ..analysis import explore as _explore
    if args.mutate:
        results = []
        ok = True
        for name, m in sorted(_explore.MUTATIONS.items()):
            depth = args.depth if args.depth is not None else m.depth
            rep = _explore.explore(m.scenario, depth=depth,
                                   seed=args.seed,
                                   max_states=args.max_states,
                                   mutation=m)
            v0 = rep["violations"][0] if rep["violations"] else None
            detected = v0 is not None and v0["invariant"] in m.expect
            ok = ok and detected
            results.append({
                "mutation": name, "scenario": m.scenario,
                "depth": depth, "expect": list(m.expect),
                "detected": detected,
                "invariant": v0["invariant"] if v0 else None,
                "minimized_trace": v0["minimized_trace"] if v0 else None,
                "states": rep["states"], "wall_s": rep["wall_s"],
            })
        doc = {"mode": "mutate", "ok": ok,
               "detected": sum(1 for r in results if r["detected"]),
               "total": len(results), "results": results}
        if args.json:
            print(json.dumps(doc, indent=1))
        else:
            for r in results:
                steps = (len(r["minimized_trace"])
                         if r["minimized_trace"] is not None else 0)
                print(f"dt-explore --mutate {r['mutation']} "
                      f"({r['scenario']}, depth {r['depth']}): "
                      + (f"DETECTED {r['invariant']} "
                         f"({steps}-step trace, {r['states']} states)"
                         if r["detected"] else
                         f"MISSED (expected one of {r['expect']})"))
            print(f"dt-explore: {doc['detected']}/{doc['total']} "
                  f"mutations detected: "
                  + ("OK" if ok else "FAILED"))
        return 0 if ok else 1
    names = [args.scenario] if args.scenario \
        else sorted(_explore.SCENARIOS)
    inv = tuple(args.invariant) if args.invariant else None
    reports = []
    ok = True
    for name in names:
        try:
            rep = _explore.explore(
                name, depth=args.depth if args.depth is not None else 4,
                seed=args.seed, max_states=args.max_states,
                invariants=inv)
        except KeyError:
            print(f"dt-explore: unknown scenario {name!r} "
                  f"(have: {', '.join(sorted(_explore.SCENARIOS))})",
                  file=sys.stderr)
            return 2
        except ValueError as e:
            print(f"dt-explore: {e}", file=sys.stderr)
            return 2
        _explore.publish_report(rep)
        reports.append(rep)
        ok = ok and rep["ok"]
        if not args.json:
            print(_render_explore_human(rep))
    if args.json:
        print(json.dumps(
            reports if len(reports) > 1 else reports[0], indent=1))
    return 0 if ok else 1


def cmd_obs_report(args) -> int:
    """One-shot observability report for a running server: scrape
    GET /metrics + GET /debug/events and print a human summary of
    endpoint/flush/handoff latencies, fencing activity and the tail of
    the flight-recorder ring (obs/)."""
    import urllib.request
    base = args.url.rstrip("/")
    if "://" not in base:
        base = "http://" + base
    with urllib.request.urlopen(f"{base}/metrics",
                                timeout=args.timeout) as r:
        doc = json.loads(r.read())
    try:
        with urllib.request.urlopen(f"{base}/debug/events",
                                    timeout=args.timeout) as r:
            events = json.loads(r.read())
    except (OSError, ValueError):
        events = {"events": []}
    if args.json:
        print(json.dumps({"metrics": doc, "events": events}))
        return 0

    def _fmt_hist(name, snap, labels=None):
        lb = " ".join(f"{k}={v}" for k, v in sorted((labels or {})
                                                    .items()))
        print(f"  {name:<28s} {lb:<28s} n={snap.get('count', 0):<7d} "
              f"p50={snap.get('p50', 0) * 1e3:8.3f}ms "
              f"p90={snap.get('p90', 0) * 1e3:8.3f}ms "
              f"p99={snap.get('p99', 0) * 1e3:8.3f}ms "
              f"max={snap.get('max', 0) * 1e3:8.3f}ms")

    obs = doc.get("obs") or {}
    print("== latencies ==")
    for name, rows in sorted((obs.get("http") or {}).items()):
        for row in rows:
            _fmt_hist(name, row, row.get("labels"))
    serve = doc.get("serve") or {}
    for name, snap in sorted((serve.get("latencies") or {}).items()):
        _fmt_hist(f"serve.{name}", snap)
    repl = doc.get("replication") or {}
    for name, snap in sorted((repl.get("latencies") or {}).items()):
        _fmt_hist(f"repl.{name}", snap)

    if repl:
        fencing = repl.get("fencing") or {}
        quorum = repl.get("quorum") or {}
        print("== fencing / quorum ==")
        print("  " + " ".join(f"{k}={v}"
                              for k, v in sorted(fencing.items())))
        print("  " + " ".join(f"{k}={v}"
                              for k, v in sorted(quorum.items())))

    trace = obs.get("trace") or {}
    if trace:
        print("== tracing ==")
        print("  " + " ".join(f"{k}={v}"
                              for k, v in sorted(trace.items())))

    tail = (events.get("events") or [])[-args.events:]
    print(f"== events (last {len(tail)} of "
          f"{events.get('recorded', 0)}) ==")
    for ev in tail:
        rest = {k: v for k, v in ev.items()
                if k not in ("seq", "t", "kind")}
        print(f"  [{ev.get('seq', '?'):>5}] {ev.get('kind', '?'):<24s} "
              + " ".join(f"{k}={v}" for k, v in sorted(rest.items())))
    return 0


def cmd_obs_watch(args) -> int:
    """Live one-screen telemetry loop for a running server: poll
    GET /debug/slo + GET /debug/hot + GET /metrics (JSON) + the
    flight-recorder cursor (GET /debug/events?since=) and render a
    compact rates / burn-rates / hot-docs / new-events report each
    round. ``--rounds`` bounds the loop for scripts and tests;
    the default polls until interrupted."""
    import urllib.request
    base = args.url.rstrip("/")
    if "://" not in base:
        base = "http://" + base

    def _get(path):
        with urllib.request.urlopen(base + path,
                                    timeout=args.timeout) as r:
            return json.loads(r.read())

    since = 0
    rounds_done = 0
    rc = 0
    while True:
        try:
            doc = _get("/metrics")
            slo = _get("/debug/slo")
            hot = _get("/debug/hot")
            events = _get(f"/debug/events?since={since}")
        except (OSError, ValueError) as e:
            print(f"obs-watch: scrape failed: {e}", file=sys.stderr)
            return 1
        try:
            incidents = _get("/debug/incidents")
        except (OSError, ValueError):
            incidents = None    # pre-incident server: panel omitted
        tail = events.get("events") or []
        if tail:
            since = max(ev.get("seq", since) for ev in tail)

        if args.json:
            print(json.dumps({"slo": slo, "hot": hot,
                              "events": tail,
                              "timeseries": (doc.get("obs") or {})
                              .get("timeseries"),
                              "journey": (doc.get("obs") or {})
                              .get("journey"),
                              "devprof": (doc.get("obs") or {})
                              .get("devprof"),
                              "qos": doc.get("qos"),
                              "incidents": incidents,
                              "scenario": (doc.get("obs") or {})
                              .get("scenario")}))
        else:
            ts = (doc.get("obs") or {}).get("timeseries") or {}
            print(f"== obs-watch round {rounds_done + 1} "
                  f"(recorded={ts.get('recorded', 0)}) ==")
            scen = (doc.get("obs") or {}).get("scenario")
            if scen:
                # scenario panel: fed by the workload runner's
                # published snapshot (obs/scorecard.publish_scenario)
                print(f"== scenario {scen.get('name', '?')} ==")
                print(f"  phase={scen.get('phase', '?'):<10s} "
                      f"tick={scen.get('tick', 0)}/"
                      f"{scen.get('ticks', 0)} "
                      f"t={scen.get('virtual_t', 0)}s "
                      f"writes={scen.get('writes', 0)} "
                      f"reads={scen.get('reads', 0)} "
                      f"errors={scen.get('errors', 0)}")
                print(f"  {scen.get('verdict', '')}")
            series = ts.get("series") or {}
            for name, row in sorted(series.items()):
                print(f"  {name:<28s} "
                      f"rate60={row.get('rate_60s', 0):10.2f}/s "
                      f"p50={(row.get('p50_300s') or 0) * 1e3:8.2f}ms "
                      f"p99={(row.get('p99_300s') or 0) * 1e3:8.2f}ms")
            print("== slo ==")
            for o in slo.get("objectives") or []:
                fast = o.get("fast") or {}
                slow = o.get("slow") or {}
                print(f"  {o.get('name', '?'):<24s} "
                      f"{o.get('state', '?'):<8s} "
                      f"burn fast={fast.get('burn', 0):7.2f} "
                      f"slow={slow.get('burn', 0):7.2f} "
                      f"(bad {fast.get('bad', 0)}/{fast.get('total', 0)})")
            qos = doc.get("qos")
            if qos:
                # adaptive-admission panel: per-class effective
                # deadlines + admit/shed/defer counters and the mesh
                # shed gate (the /debug/qos document, inlined here via
                # the /metrics qos block)
                shed = qos.get("shed") or {}
                why = shed.get("mesh_why") or ""
                print(f"== qos (mesh={shed.get('mesh_state', 'ok')}"
                      + (f" {why}" if why else "")
                      + (" hot=" + ",".join(shed.get("hot_tenants"))
                         if shed.get("hot_tenants") else "") + ") ==")
                for cls, row in sorted((qos.get("classes") or {})
                                       .items()):
                    dl_ms = row.get("deadline_s", 0) * 1e3
                    print(f"  {cls:<14s} deadline={dl_ms:8.2f}ms "
                          f"admitted={row.get('admitted', 0):<8d} "
                          f"shed={row.get('shed', 0):<6d} "
                          f"deferred={row.get('deferred', 0)}")
                ctl = qos.get("controller") or {}
                print("  ctl " + " ".join(
                    f"{k}={ctl.get(k, 0)}"
                    for k in ("steps", "stretched", "shrunk", "held",
                              "floors", "ceilings")))
            if incidents is not None:
                # incident panel: open bundles by kind + the newest
                # bundle id (fetch the full bundle with dt-incidents)
                by_kind = incidents.get("by_kind") or {}
                kinds = " ".join(f"{k}={v}"
                                 for k, v in sorted(by_kind.items())
                                 if v)
                print(f"== incidents (open={incidents.get('open', 0)} "
                      f"total={incidents.get('total', 0)}"
                      + (f" last={incidents.get('last_id')}"
                         if incidents.get("last_id") else "")
                      + ") ==")
                if kinds:
                    print(f"  {kinds}")
                for row in (incidents.get("incidents") or [])[:5]:
                    mark = " " if row.get("acknowledged") else "!"
                    print(f"  [{mark}] {row.get('id', '?'):<16s} "
                          f"{row.get('kind', '?'):<12s} "
                          f"{row.get('series', '?')}")
            print("== hot docs ==")
            for kind, block in sorted((hot.get("doc") or {}).items()):
                tops = (block.get("top") or [])[:args.top]
                if not tops:
                    continue
                row = " ".join(f"{k}={c:.0f}" for k, c, _e in tops)
                print(f"  {kind:<14s} {row}")
            jo = (doc.get("obs") or {}).get("journey") or {}
            if jo.get("enabled"):
                print(f"== convergence (tracked={jo.get('tracked', 0)} "
                      f"dropped={jo.get('dropped', 0)}) ==")
                stages = jo.get("stages") or {}
                print("  " + " ".join(f"{s}={c}"
                                      for s, c in stages.items()))
                for peer, row in sorted(
                        (jo.get("convergence") or {}).items()):
                    print(f"  lag {peer:<22s} n={row.get('n', 0):<6d} "
                          f"mean={row.get('mean_s', 0) * 1e3:8.2f}ms "
                          f"max={row.get('max_s', 0) * 1e3:8.2f}ms")
            dp = (doc.get("obs") or {}).get("devprof") or {}
            jit = dp.get("jit_cache") or {}
            if dp.get("enabled") and jit:
                # one row per jit family — the PR-13 device-resident
                # tail transform (`xform`) and Pallas replay rung
                # (`pallas`) surface here next to micro/tip/fused
                print("== device (jit cache) ==")
                for fam, row in sorted(jit.items()):
                    h, m = row.get("hits", 0), row.get("misses", 0)
                    rate = h / (h + m) if (h + m) else 0.0
                    print(f"  {fam:<14s} hits={h:<8d} misses={m:<6d} "
                          f"hit_rate={rate:6.3f}")
                fused = dp.get("fused") or {}
                win = dp.get("mesh_window") or {}
                print(f"  fused calls={fused.get('device_calls', 0)} "
                      f"occ={fused.get('occupancy', 0)} "
                      f"dev_frac={fused.get('device_fraction', 0)}; "
                      f"window dispatches={win.get('dispatches', 0)} "
                      f"docs/dispatch="
                      f"{win.get('docs_per_dispatch', 0)}")
            print(f"== events (+{len(tail)} new, cursor {since}) ==")
            for ev in tail[-args.events:]:
                rest = {k: v for k, v in ev.items()
                        if k not in ("seq", "t", "kind")}
                print(f"  [{ev.get('seq', '?'):>5}] "
                      f"{ev.get('kind', '?'):<24s} "
                      + " ".join(f"{k}={v}"
                                 for k, v in sorted(rest.items())))
        if not slo.get("ok", True):
            rc = 1
        if incidents is not None and incidents.get("open", 0) > 0:
            rc = 1    # an unacknowledged incident is an alert
        rounds_done += 1
        if args.rounds and rounds_done >= args.rounds:
            return rc
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return rc


def cmd_dt_trace(args) -> int:
    """Assemble one (or more) cross-host traces: fan out over
    ``--peers``, fetch each host's local spans for the trace id
    (GET /debug/trace/<id>), estimate per-host clock offsets from the
    request round trip, and merge everything into a single waterfall
    + critical path (obs/assemble.py). With no trace ids, list the
    primary host's recent sampled traces (GET /debug/traces)."""
    import urllib.request
    from ..obs.assemble import aggregate, assemble_trace, render_human
    hosts = [args.url] + [h for h in
                          (args.peers.split(",") if args.peers else [])
                          if h.strip()]
    bases = []
    for h in hosts:
        h = h.strip().rstrip("/")
        if "://" not in h:
            h = "http://" + h
        if h not in bases:
            bases.append(h)

    def _get(base, path):
        t_send = time.monotonic()
        with urllib.request.urlopen(base + path,
                                    timeout=args.timeout) as r:
            body = json.loads(r.read())
        return body, t_send, time.monotonic()

    if not args.trace_ids:
        try:
            body, _ts, _tr = _get(bases[0], "/debug/traces")
        except (OSError, ValueError) as e:
            print(f"dt-trace: index fetch failed: {e}", file=sys.stderr)
            return 1
        rows = body.get("traces") or []
        if args.json:
            print(json.dumps(body))
        else:
            print(f"== recent traces on {body.get('host', bases[0])} "
                  f"({len(rows)}) ==")
            for row in rows:
                print(f"  {row.get('trace', '?'):<18s} "
                      f"{row.get('root', '?'):<24s} "
                      f"{(row.get('dur_s') or 0) * 1e3:9.2f}ms "
                      f"spans={row.get('spans', 0)}")
        return 0

    reports = []
    rc = 0
    for tid in args.trace_ids:
        fetches = []
        for base in bases:
            try:
                body, t_send, t_recv = _get(base,
                                            f"/debug/trace/{tid}")
            except (OSError, ValueError) as e:
                # a down peer degrades the assembly (its spans go
                # missing / orphaned), it must not kill the command
                print(f"dt-trace: {base} fetch failed: {e}",
                      file=sys.stderr)
                continue
            fetches.append({"host": body.get("host", base),
                            "now": body.get("now"),
                            "spans": body.get("spans") or [],
                            "t_send": t_send, "t_recv": t_recv})
        rep = assemble_trace(tid, fetches)
        reports.append(rep)
        if rep.get("root") is None:
            rc = 1
    agg = aggregate(reports) if len(reports) > 1 else None
    if args.json:
        out = {"traces": reports}
        if agg is not None:
            out["aggregate"] = agg
        print(json.dumps(out))
    else:
        for i, rep in enumerate(reports):
            print(render_human(rep, agg if i == len(reports) - 1
                               else None))
    return rc


def cmd_dt_incidents(args) -> int:
    """Incident-bundle browser with dt-trace's peer fan-out. With no
    ids: list every host's incident index (`--tail` instead follows
    the indexes and prints bundles as they open). With ids: fetch each
    bundle from whichever host holds it (GET /debug/incidents/<id>)
    and print the evidence — recorder tail, SLO burn rates, hot docs,
    convergence lag, trace ids. rc=1 when a requested id resolves on
    no host."""
    import urllib.error
    import urllib.request
    hosts = [args.url] + [h for h in
                          (args.peers.split(",") if args.peers else [])
                          if h.strip()]
    bases = []
    for h in hosts:
        h = h.strip().rstrip("/")
        if "://" not in h:
            h = "http://" + h
        if h not in bases:
            bases.append(h)

    def _get(base, path):
        with urllib.request.urlopen(base + path,
                                    timeout=args.timeout) as r:
            return json.loads(r.read())

    def _indexes():
        out = []
        for base in bases:
            try:
                out.append((base, _get(base, "/debug/incidents")))
            except (OSError, ValueError) as e:
                # a down peer degrades the listing, never kills it
                print(f"dt-incidents: {base} fetch failed: {e}",
                      file=sys.stderr)
        return out

    def _print_index(base, idx):
        print(f"== incidents on {idx.get('host', base)} "
              f"(open={idx.get('open', 0)} "
              f"total={idx.get('total', 0)}) ==")
        for row in idx.get("incidents") or []:
            mark = " " if row.get("acknowledged") else "!"
            print(f"  [{mark}] {row.get('id', '?'):<16s} "
                  f"{row.get('kind', '?'):<12s} "
                  f"{row.get('series', '?'):<32s} "
                  f"t={row.get('t', 0):.1f}")

    if args.tail:
        # follow mode: poll every index and print bundles newly opened
        # since the previous round (per-host seen-id cursor)
        seen = {}
        rounds_done = 0
        while True:
            for base, idx in _indexes():
                known = seen.setdefault(base, set())
                for row in reversed(idx.get("incidents") or []):
                    if row["id"] in known:
                        continue
                    known.add(row["id"])
                    if args.json:
                        print(json.dumps({"host": idx.get("host", base),
                                          **row}))
                    else:
                        print(f"{idx.get('host', base)}  "
                              f"{row.get('id', '?'):<16s} "
                              f"{row.get('kind', '?'):<12s} "
                              f"{row.get('series', '?')}")
            rounds_done += 1
            if args.rounds and rounds_done >= args.rounds:
                return 0
            try:
                time.sleep(args.interval)
            except KeyboardInterrupt:
                return 0

    if not args.incident_ids:
        idxs = _indexes()
        if args.json:
            print(json.dumps({"hosts": [dict(idx, base=base)
                                        for base, idx in idxs]}))
        else:
            for base, idx in idxs:
                _print_index(base, idx)
        return 0 if idxs else 1

    rc = 0
    for iid in args.incident_ids:
        bundle, src = None, None
        for base in bases:
            try:
                bundle = _get(base, f"/debug/incidents/{iid}")
                src = base
                break
            except urllib.error.HTTPError as e:
                e.close()    # 404 here just means "not this host"
            except (OSError, ValueError) as e:
                print(f"dt-incidents: {base} fetch failed: {e}",
                      file=sys.stderr)
        if bundle is None:
            print(f"dt-incidents: {iid} not found on any host",
                  file=sys.stderr)
            rc = 1
            continue
        if args.json:
            print(json.dumps({"host": src, **bundle}))
            continue
        print(f"== {bundle.get('id')} {bundle.get('kind')} "
              f"series={bundle.get('series')} (from {src}) ==")
        print("  detail: " + json.dumps(bundle.get("detail") or {}))
        ctx = bundle.get("context")
        if ctx:
            print("  context: " + json.dumps(ctx))
        for row in bundle.get("slo") or []:
            print(f"  slo {row.get('name', '?'):<24s} "
                  f"{row.get('state', '?'):<8s} "
                  f"fast={row.get('fast_burn', 0):.2f} "
                  f"slow={row.get('slow_burn', 0):.2f}")
        lag = bundle.get("convergence_lag") or {}
        for peer, row in sorted(lag.items()):
            print(f"  lag {peer:<22s} n={row.get('n', 0)} "
                  f"max={row.get('max_s', 0) * 1e3:.1f}ms")
        traces = [t for t in bundle.get("traces") or [] if t]
        if traces:
            print("  traces: " + " ".join(traces)
                  + "   (assemble with dt-trace)")
        tail = bundle.get("recorder_tail") or []
        print(f"  recorder tail ({len(tail)} events):")
        for ev in tail[-args.events:]:
            rest = {k: v for k, v in ev.items()
                    if k not in ("seq", "t", "kind")}
            print(f"    [{ev.get('seq', '?'):>5}] "
                  f"{ev.get('kind', '?'):<24s} "
                  + " ".join(f"{k}={v}"
                             for k, v in sorted(rest.items())))
    return rc


def cmd_scenario(args) -> int:
    """Declarative workload harness (workload/): `scenario list`
    prints the registry; `scenario run --name X` drives the scenario
    through serve+replicate+read against the live SLO engine and
    emits its versioned scorecard (exit 0 iff the run converged with
    SLOs intact and zero transport errors)."""
    from ..workload import SCENARIOS, get_scenario, run_scenario
    if args.action == "list":
        for name in sorted(SCENARIOS):
            sc = SCENARIOS[name]
            mark = " [slow]" if sc.slow else ""
            print(f"{name:<16s}{mark:>7s}  {sc.description}")
        return 0
    if args.resume:
        # the scenario (and its qos/incident toggles) ride inside the
        # checkpoint; --name is neither needed nor honored
        card = run_scenario(None, resume_dir=args.resume,
                            data_dir=args.data_dir,
                            progress=args.progress,
                            stop_after_ticks=args.stop_after_ticks)
    else:
        if not args.name:
            print("scenario run: --name is required "
                  "(see `scenario list`)", file=sys.stderr)
            return 2
        try:
            sc = get_scenario(args.name)
        except ValueError as e:
            print(f"scenario: {e}", file=sys.stderr)
            return 2
        if args.seed is not None:
            import dataclasses
            sc = dataclasses.replace(sc, seed=args.seed)
        card = run_scenario(sc, data_dir=args.data_dir,
                            progress=args.progress, qos=args.qos,
                            incidents=args.incidents,
                            checkpoint_every_s=args.checkpoint_every,
                            stop_after_ticks=args.stop_after_ticks)
    print(json.dumps(card, indent=1 if args.json else None))
    if card.get("aborted"):
        # deliberate mid-run kill (--stop-after-ticks): the checkpoint
        # under resume_dir is the product, not a failure
        return 0
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(card, indent=1) + "\n")
    return 0 if card["ok"] else 1


def cmd_scorecard_diff(args) -> int:
    """Compare two scenario scorecards metric-by-metric against the
    per-metric tolerance bands (obs/scorecard.py). Always prints the
    diff; with --gate the exit code is non-zero iff any gated metric
    moved in its bad direction past its band — the one-diff
    regression check BASELINE.md scenario rows hang off."""
    from ..obs.scorecard import diff_scorecards, render_diff
    with open(args.old) as f:
        old = json.load(f)
    with open(args.new) as f:
        new = json.load(f)
    diff = diff_scorecards(old, new)
    print(json.dumps(diff) if args.json else render_diff(diff))
    if args.gate and not diff["ok"]:
        return 1
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dt-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("create", help="create a new .dt file")
    c.add_argument("filename")
    c.add_argument("--content")
    c.add_argument("--agent")
    c.add_argument("-f", "--force", action="store_true")
    c.set_defaults(fn=cmd_create)

    c = sub.add_parser("cat", help="print the document contents")
    c.add_argument("filename")
    c.add_argument("-o", "--output")
    c.add_argument("--version", help="JSON list of LVs to check out at")
    c.set_defaults(fn=cmd_cat)

    c = sub.add_parser("log", help="print the operation log")
    c.add_argument("filename")
    c.add_argument("--transformed", action="store_true")
    c.add_argument("--history", action="store_true")
    c.set_defaults(fn=cmd_log)

    c = sub.add_parser("version", help="print the current remote version")
    c.add_argument("filename")
    c.set_defaults(fn=cmd_version)

    c = sub.add_parser("set", help="set contents (reads stdin by default)")
    c.add_argument("filename")
    c.add_argument("--content")
    c.add_argument("--agent")
    c.set_defaults(fn=cmd_set)

    c = sub.add_parser("repack", help="re-encode the file compactly")
    c.add_argument("filename")
    c.set_defaults(fn=cmd_repack)

    c = sub.add_parser("export", help="cross-CRDT benchmark JSON export")
    c.add_argument("filename")
    c.set_defaults(fn=cmd_export)

    c = sub.add_parser("dot", help="graphviz export of the causal graph")
    c.add_argument("filename")
    c.set_defaults(fn=cmd_dot)

    c = sub.add_parser("git-import", help="replay a file's git history")
    c.add_argument("path", help="file path within the repo")
    c.add_argument("--repo", help="git repo root (default .)")
    c.add_argument("--out", required=True, help="output .dt file")
    c.set_defaults(fn=cmd_git_import)

    c = sub.add_parser(
        "serve-bench",
        help="replay a workload through the sharded merge scheduler")
    c.add_argument("--shards", type=int, default=4)
    c.add_argument("--docs", type=int, default=8)
    c.add_argument("--txns", type=int, default=None,
                   help="rounds to replay (default: whole corpus)")
    c.add_argument("--engine", choices=("device", "host"),
                   default="device")
    c.add_argument("--mode", choices=("trace", "concurrent", "flash"),
                   default="trace",
                   help="flash = flash-crowd tape whose per-window op "
                   "bursts thrash the jit shape classes (the "
                   "shape-steering A/B tape)")
    c.add_argument("--corpus", help="crdt-testdata JSON trace file "
                   "(default: synthetic trace)")
    c.add_argument("--flush-docs", type=int, default=4)
    c.add_argument("--flush-deadline", type=float, default=0.02)
    c.add_argument("--max-pending", type=int, default=64)
    c.add_argument("--max-sessions", type=int, default=4)
    c.add_argument("--seed", type=int, default=7)
    c.add_argument("--fused", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="fused vmapped bucket flush (--no-fused = the "
                   "serial per-doc zone-session path, for speedup "
                   "comparisons)")
    c.add_argument("--workers", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="per-shard flush worker threads "
                   "(--no-workers = inline serial pump)")
    c.add_argument("--mesh-window",
                   action=argparse.BooleanOptionalAction,
                   default=False,
                   help="mesh flush windows: every due shard's bucket "
                   "replayed in ONE shard_map dispatch per window "
                   "(default: one device call per shard)")
    c.add_argument("--device-plan",
                   action=argparse.BooleanOptionalAction,
                   default=False,
                   help="device-resident tail transform: resolve "
                   "concurrent merge positions on device "
                   "(tpu/xform.py) instead of the host tracker walk; "
                   "per-doc host fallback on any guard trip")
    c.add_argument("--pallas",
                   action=argparse.BooleanOptionalAction,
                   default=False,
                   help="Pallas step-kernel replay rung at the top of "
                   "the flush ladder (pallas -> mesh -> fused -> "
                   "per-doc -> host)")
    c.add_argument("--steer",
                   action=argparse.BooleanOptionalAction,
                   default=True,
                   help="batch-shape steering: snap each window's "
                   "(b, n) onto the nearest warmed jit shape class "
                   "(tpu/steer.py; --no-steer = raw pow2 classes, "
                   "the PR-20 A/B control arm)")
    c.add_argument("--device-stage",
                   action=argparse.BooleanOptionalAction,
                   default=True,
                   help="device-resident mesh staging + donated-"
                   "buffer window arenas (parallel/arena.py; "
                   "--no-device-stage = host-numpy staging every "
                   "window, the PR-20 A/B control arm)")
    c.add_argument("--warmup", action="store_true",
                   help="pre-compile the fused jit kernels before "
                   "feeding (keeps compiles off the flush path)")
    c.add_argument("--steady-rounds", type=int, default=0,
                   help="extra lockstep rounds against resident "
                   "sessions after the continuous feed — the fused "
                   "occupancy measurement (see serve/driver.py)")
    c.add_argument("--telemetry",
                   action=argparse.BooleanOptionalAction,
                   default=True,
                   help="live windowed telemetry + SLO burn-rate "
                   "engine (--no-telemetry = the overhead-A/B "
                   "control arm; SLO verdict then trivially passes)")
    c.add_argument("--journey",
                   action=argparse.BooleanOptionalAction,
                   default=True,
                   help="edit-to-visibility journey stamps "
                   "(obs/journey.py; --no-journey = the overhead-A/B "
                   "control arm)")
    c.add_argument("--parity", action="store_true",
                   help="explicit parity gate (parity is always "
                   "checked; this just documents the intent in CI "
                   "invocations)")
    c.add_argument("--json", action="store_true",
                   help="print the full JSON report")
    c.add_argument("--metrics-out", help="write the JSON report here")
    c.add_argument("--dry-run", action="store_true",
                   help="tiny host-engine smoke preset (CI)")
    c.add_argument("--real-device", action="store_true",
                   help="skip the CPU-simulation env pinning")
    c.set_defaults(fn=cmd_serve_bench)

    c = sub.add_parser(
        "replicate-soak",
        help="fault-injected N-server replication convergence soak")
    c.add_argument("--servers", type=int, default=3)
    c.add_argument("--docs", type=int, default=4)
    c.add_argument("--rounds", type=int, default=20)
    c.add_argument("--edits-per-round", type=int, default=4)
    c.add_argument("--seed", type=int, default=7)
    c.add_argument("--drop-rate", type=float, default=0.15)
    c.add_argument("--dup-rate", type=float, default=0.05)
    c.add_argument("--partition-rounds", type=int, default=6,
                   help="rounds the server0<->server1 link stays cut")
    c.add_argument("--reconcile-rounds", type=int, default=12)
    c.add_argument("--lease-ttl", type=float, default=1.0)
    c.add_argument("--serve-shards", type=int, default=0,
                   help="attach the host-engine merge scheduler with "
                   "N shards on every server (ownership-gated)")
    c.add_argument("--crash", action="store_true",
                   help="crash-restart two nodes mid-run (journal "
                   "recovery + rejoining fence)")
    c.add_argument("--asym", action="store_true",
                   help="one-way partitions + jittered slow link + "
                   "clock skew")
    c.add_argument("--churn", action="store_true",
                   help="join an extra node mid-run, then leave it")
    c.add_argument("--witness", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="runtime lock witness during the soak: record "
                   "held-while-acquiring edges and gate on an acyclic "
                   "lock-order graph (default: on for --crash/--churn "
                   "chaos runs)")
    c.add_argument("--progress", action="store_true")
    c.add_argument("--json", action="store_true")
    c.add_argument("--metrics-out")
    c.set_defaults(fn=cmd_replicate_soak)

    c = sub.add_parser(
        "rebalance-soak",
        help="flash-crowd elastic-mesh soak: SLO-driven hot-doc "
        "rebalancing with mid-run scale-out, seeded migration abort, "
        "and zero-split-brain / convergence gates")
    c.add_argument("--servers", type=int, default=3)
    c.add_argument("--docs", type=int, default=8)
    c.add_argument("--seed", type=int, default=7)
    c.add_argument("--capacity", type=int, default=5,
                   help="held-lease count a host serves without "
                   "latency penalty in the soak's load model")
    c.add_argument("--crowd-boost", type=int, default=3,
                   help="extra load the flash crowd puts on whichever "
                   "host currently owns the hot doc")
    c.add_argument("--flash-crowd", action="store_true",
                   help="run the full acceptance journey: ok -> "
                   "burning -> rebalance -> ok (without it only the "
                   "healthy phase runs)")
    c.add_argument("--join", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="join a fresh host on the first non-ok SLO "
                   "evaluation and require it to absorb load")
    c.add_argument("--inject-abort",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="aim one migration at an unreachable target "
                   "and require a clean rollback")
    c.add_argument("--split-hot-doc", action="store_true",
                   help="writer-group arm: promote the hot doc to a "
                   "2-writer group (>= 2x write admission), then "
                   "member-crash and asymmetric-partition demotions "
                   "must drain back to one writer with zero "
                   "acked-loss / split-brain")
    c.add_argument("--progress", action="store_true")
    c.add_argument("--json", action="store_true")
    c.add_argument("--metrics-out")
    c.set_defaults(fn=cmd_rebalance_soak)

    c = sub.add_parser(
        "storage-soak",
        help="fault-injected tiered-residency soak: churn docs "
        "through an undersized warm tier and gate on byte-identical "
        "re-hydration")
    c.add_argument("--docs", type=int, default=120)
    c.add_argument("--warm", type=int, default=12,
                   help="warm-tier capacity (deliberately << --docs: "
                   "eviction pressure is the point)")
    c.add_argument("--rounds", type=int, default=8)
    c.add_argument("--edits-per-round", type=int, default=48)
    c.add_argument("--shards", type=int, default=2)
    c.add_argument("--seed", type=int, default=7)
    c.add_argument("--compact-every", type=int, default=16,
                   help="per-doc WAL patch records before a baseline "
                   "fold (low = many compactions under churn)")
    c.add_argument("--churn", action="store_true",
                   help="force extra evictions-to-snapshot every round "
                   "beyond what warm-tier pressure already causes")
    c.add_argument("--crash", action="store_true",
                   help="inject crash-restart, crash-mid-compaction "
                   "(every fsync point), torn tails and wholesale "
                   "corruption")
    c.add_argument("--slow", action="store_true",
                   help="seeded slow-disk delays on load (exercises "
                   "the per-attempt timeout / retry ladder)")
    c.add_argument("--data-dir",
                   help="home directory for the doc snapshot files "
                   "(default: a fresh temp dir, removed afterwards)")
    c.add_argument("--p99-budget", type=float, default=0.5,
                   help="cold-start p99 gate in seconds")
    c.add_argument("--progress", action="store_true")
    c.add_argument("--json", action="store_true")
    c.add_argument("--metrics-out")
    c.set_defaults(fn=cmd_storage_soak)

    c = sub.add_parser(
        "read-bench",
        help="two-server follower-read A/B bench: bounded-staleness "
        "local reads vs owner-only proxying, with client-side "
        "staleness + read-your-writes verification")
    c.add_argument("--docs", type=int, default=3)
    c.add_argument("--readers", type=int, default=6)
    c.add_argument("--reads-per-reader", type=int, default=120)
    c.add_argument("--seed", type=int, default=7)
    c.add_argument("--zipf-s", type=float, default=1.2,
                   help="Zipf skew of the reader doc distribution")
    c.add_argument("--max-staleness", type=float, default=2.0,
                   help="staleness bound (seconds) the follower phase "
                   "requests on every read")
    c.add_argument("--min-version-every", type=int, default=4,
                   help="send the doc's latest write token as "
                   "X-DT-Min-Version on every Nth read (0 = never)")
    c.add_argument("--lease-ttl", type=float, default=30.0)
    c.add_argument("--serve-shards", type=int, default=1,
                   help="attach the host-engine merge scheduler with "
                   "N shards on both servers (leases activate through "
                   "its admit gate, so the bench needs at least 1)")
    c.add_argument("--doc-bytes", type=int, default=16384,
                   help="approximate seeded checkout size per doc")
    c.add_argument("--min-speedup", type=float, default=None,
                   help="fail unless follower/control aggregate read "
                   "throughput clears this ratio")
    c.add_argument("--progress", action="store_true")
    c.add_argument("--json", action="store_true")
    c.add_argument("--metrics-out")
    c.set_defaults(fn=cmd_read_bench)

    c = sub.add_parser(
        "wire-bench",
        help="wire-frame codec micro-benchmark: churn op tape through "
        "each frame codec vs its JSON twin (throughput + wire-byte "
        "ratios; the row bench.py ingests)")
    c.add_argument("--seed", type=int, default=7)
    c.add_argument("--ops", type=int, default=2000,
                   help="length of the churn op tape")
    c.add_argument("--agents", type=int, default=8,
                   help="concurrently-churning agent names")
    c.add_argument("--docs", type=int, default=64,
                   help="doc count for the listing-frame measurement")
    c.add_argument("--json", action="store_true",
                   help="pretty-print the row")
    c.set_defaults(fn=cmd_wire_bench)

    c = sub.add_parser(
        "dt-lint",
        help="concurrency invariant lint: lock order, device dispatch "
        "under the global/oplog lock, fencing, jit purity")
    c.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the repo's "
                   "concurrency-bearing packages)")
    c.add_argument("--fail-on", choices=("warn", "error"),
                   default="warn",
                   help="exit nonzero on any violation (warn, the "
                   "default) or only on severity=error findings")
    c.add_argument("--disable", action="append", default=[],
                   metavar="RULE",
                   help="disable a rule by name (repeatable)")
    c.add_argument("--json", action="store_true",
                   help="print the full JSON report")
    c.set_defaults(fn=cmd_dt_lint)

    c = sub.add_parser(
        "dt-explore",
        help="protocol model checker: exhaustively explore scheduler "
        "interleavings of the real lease/quorum/fencing code and check "
        "safety invariants at every state")
    c.add_argument("--scenario",
                   help="explore one scenario by name — handoff, "
                   "crash-recovery, renewal, tiebreak, migration, "
                   "writer-group (default: all)")
    c.add_argument("--depth", type=int, default=None,
                   help="interleaving depth bound (default 4; under "
                   "--mutate each mutation's own catch depth)")
    c.add_argument("--seed", type=int, default=0,
                   help="tie-break seed for the action visit order")
    c.add_argument("--invariant", action="append", default=[],
                   metavar="NAME",
                   help="check only this invariant (repeatable; "
                   "default: the scenario's full set)")
    c.add_argument("--max-states", type=int, default=200_000,
                   help="state-count safety valve; exceeding it marks "
                   "the run incomplete")
    c.add_argument("--mutate", action="store_true",
                   help="adequacy harness: apply each seeded protocol "
                   "mutation and require the explorer to catch it; "
                   "exit 0 only if every mutation is detected")
    c.add_argument("--json", action="store_true",
                   help="print the full JSON report(s)")
    c.set_defaults(fn=cmd_dt_explore)

    c = sub.add_parser(
        "obs-report",
        help="scrape a server's /metrics + /debug/events and print a "
        "human latency / fencing / flight-recorder summary")
    c.add_argument("url", help="server base URL (host:port is enough)")
    c.add_argument("--events", type=int, default=20,
                   help="flight-recorder tail length to print")
    c.add_argument("--timeout", type=float, default=5.0)
    c.add_argument("--json", action="store_true",
                   help="print the raw scraped JSON instead")
    c.set_defaults(fn=cmd_obs_report)

    c = sub.add_parser(
        "obs-watch",
        help="live telemetry loop: poll /debug/slo + /debug/hot + "
        "/metrics + the flight-recorder cursor and render a compact "
        "rates / burn-rates / hot-docs report each round")
    c.add_argument("url", help="server base URL (host:port is enough)")
    c.add_argument("--interval", type=float, default=2.0,
                   help="seconds between polls")
    c.add_argument("--rounds", type=int, default=0,
                   help="stop after N polls (0 = until interrupted)")
    c.add_argument("--top", type=int, default=5,
                   help="hot-doc keys to show per kind")
    c.add_argument("--events", type=int, default=10,
                   help="new flight-recorder events to print per round")
    c.add_argument("--timeout", type=float, default=5.0)
    c.add_argument("--json", action="store_true",
                   help="one JSON line per round instead")
    c.set_defaults(fn=cmd_obs_watch)

    c = sub.add_parser(
        "dt-trace",
        help="cross-host trace assembly: fetch one trace's spans from "
        "every peer, align clocks off the request RTT, and print the "
        "merged waterfall + critical path")
    c.add_argument("url", help="primary server base URL")
    c.add_argument("trace_ids", nargs="*",
                   help="trace ids to assemble (none: list the "
                   "primary host's recent sampled traces)")
    c.add_argument("--peers", default="",
                   help="comma-separated peer base URLs to include "
                   "in the fan-out")
    c.add_argument("--timeout", type=float, default=5.0)
    c.add_argument("--json", action="store_true",
                   help="print the assembled report(s) as JSON")
    c.set_defaults(fn=cmd_dt_trace)

    c = sub.add_parser(
        "scenario",
        help="declarative workload harness: run a registered scenario "
        "(serve+replicate+read against the live SLO engine) and emit "
        "its versioned scorecard, or list the registry")
    c.add_argument("action", choices=("run", "list"))
    c.add_argument("--name",
                   help="registered scenario name (see `scenario list`)")
    c.add_argument("--seed", type=int, default=None,
                   help="override the scenario's registered seed")
    c.add_argument("--out",
                   help="also write the scorecard JSON to this file")
    c.add_argument("--data-dir",
                   help="bank-lane home directory (default: a fresh "
                   "temp dir, removed afterwards)")
    c.add_argument("--progress", action="store_true")
    c.add_argument("--qos", dest="qos", action="store_true",
                   default=True,
                   help="attach the adaptive-admission QoS controller "
                   "to every scenario server (default)")
    c.add_argument("--no-qos", dest="qos", action="store_false",
                   help="static admission — the A/B control arm for "
                   "scorecard-diff against an adaptive run")
    c.add_argument("--incidents", dest="incidents",
                   action="store_true", default=True,
                   help="arm the incident engine's anomaly detector "
                   "on every scenario server (default)")
    c.add_argument("--no-incidents", dest="incidents",
                   action="store_false",
                   help="detector off — the overhead A/B control arm")
    c.add_argument("--checkpoint-every", type=float, default=0.0,
                   metavar="VIRT_S",
                   help="long-run mode: persist a runner-state "
                   "checkpoint (tape cursor, session frontiers, rng, "
                   "incident index) every N virtual seconds under a "
                   "kept run dir; resume with --resume")
    c.add_argument("--resume", default=None, metavar="DIR",
                   help="resume a checkpointed run: reboot the "
                   "servers on their journaled dirs and replay the "
                   "tape from the cursor (the scenario rides inside "
                   "the checkpoint)")
    c.add_argument("--stop-after-ticks", type=int, default=None,
                   metavar="N",
                   help="force-checkpoint after tick N and tear the "
                   "mesh down crash-style (the scripted mid-run kill "
                   "for soak drills; exit 0 with an aborted marker)")
    c.add_argument("--json", action="store_true",
                   help="pretty-print the scorecard")
    c.set_defaults(fn=cmd_scenario)

    c = sub.add_parser(
        "dt-incidents",
        help="incident-bundle browser: list every host's auto-captured "
        "incident index, show full evidence bundles by id, or --tail "
        "new bundles as they open (peer fan-out like dt-trace)")
    c.add_argument("url", help="primary server base URL")
    c.add_argument("incident_ids", nargs="*",
                   help="bundle ids to show (none: list the indexes)")
    c.add_argument("--peers", default="",
                   help="comma-separated peer base URLs to include "
                   "in the fan-out")
    c.add_argument("--tail", action="store_true",
                   help="follow mode: poll the indexes and print "
                   "bundles as they open")
    c.add_argument("--interval", type=float, default=2.0,
                   help="seconds between --tail polls")
    c.add_argument("--rounds", type=int, default=0,
                   help="stop --tail after N polls (0 = until "
                   "interrupted)")
    c.add_argument("--events", type=int, default=15,
                   help="recorder-tail events to print per bundle")
    c.add_argument("--timeout", type=float, default=5.0)
    c.add_argument("--json", action="store_true",
                   help="print bundles/indexes as JSON")
    c.set_defaults(fn=cmd_dt_incidents)

    c = sub.add_parser(
        "scorecard-diff",
        help="compare two scenario scorecards against per-metric "
        "tolerance bands; --gate exits non-zero on regression")
    c.add_argument("old", help="baseline scorecard JSON file")
    c.add_argument("new", help="candidate scorecard JSON file")
    c.add_argument("--gate", action="store_true",
                   help="exit non-zero when any gated metric moved in "
                   "its bad direction past its tolerance band")
    c.add_argument("--json", action="store_true",
                   help="print the diff as JSON")
    c.set_defaults(fn=cmd_scorecard_diff)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
