"""Static HTML/JS for the browser demo client and merge visualizer.

Capability mirror of the reference's browser tier (reference:
wiki/client/dt_doc.ts:40-171 — a live collaborative editor against the sync
server; vis/src/App.svelte — the merge/DAG visualizer). The reference's
client runs the CRDT itself via WASM; this client is the reference's OTHER
documented integration mode — a plain positional ("dumb") client speaking
operational transform (reference README.md:31-33: "interoperable with
positional updates ... via operational transform"), so the browser needs no
CRDT at all: it sends positional edits tagged with the version it saw and
catches up by applying server-computed traversal ops (text/ot.py).

Positions on the wire are CODE POINTS everywhere: JS strings are UTF-16,
so both clients diff/apply over Array.from code-point arrays and convert
the cursor at the boundary (the reference ships wchar conversion for the
same split; here the conversion lives client-side, pinned by the astral
end-to-end tests in tests/test_server.py).
"""

INDEX_HTML = """<!doctype html>
<meta charset="utf-8"><title>diamond-types-tpu</title>
<style>
 body{font:15px system-ui;margin:3em auto;max-width:40em;color:#222}
 input{font:inherit;padding:.3em}</style>
<h1>diamond-types-tpu sync server</h1>
<p>Open a document (creates it if missing):</p>
<form onsubmit="go();return false">
 <input id="d" placeholder="doc id" value="note">
 <button>edit</button>
 <button type=button onclick="vis()">visualize</button>
 <button type=button onclick="crdt()">crdt peer</button>
</form>
<p style="font-size:13px;color:#777">"edit" is the positional dumb
client (server-side OT); "crdt peer" runs the full CRDT in your browser
— it edits offline and merges locally.</p>
<script>
 const f=()=>document.getElementById('d').value.trim()||'note';
 function go(){location.href='/edit/'+encodeURIComponent(f())}
 function vis(){location.href='/vis/'+encodeURIComponent(f())}
 function crdt(){location.href='/crdt/'+encodeURIComponent(f())}
</script>
"""

EDITOR_HTML = """<!doctype html>
<meta charset="utf-8"><title>edit: __DOC__</title>
<style>
 body{font:15px system-ui;margin:2em auto;max-width:46em;color:#222}
 textarea{width:100%;height:24em;font:14px/1.5 ui-monospace,monospace;
          padding:1em;box-sizing:border-box;border:1px solid #bbb;
          border-radius:6px}
 #st{color:#777;font-size:13px;margin-top:.5em}
 a{color:#06c}
</style>
<h2>__DOC__ <a href="/vis/__DOC__" style="font-size:14px">DAG</a></h2>
<textarea id="t" spellcheck="false" disabled>loading…</textarea>
<div id="st">connecting…</div>
<script>
const DOC = "__DOC__";
const AGENT = "web-" + Math.random().toString(36).slice(2, 8);
const ta = document.getElementById("t"), st = document.getElementById("st");
let version = null, shadow = "", inflight = false, queue = [];
let pollFails = 0;

const api = (path, body) => fetch(`/doc/${DOC}/${path}`, {
  method: "POST", body: JSON.stringify(body)}).then(r => r.json());

// Positions on the wire are CODE POINTS (the server's unit — the
// reference's wchar_conversion exists because JS strings are UTF-16:
// diffing on raw string indices would drift past any astral char and
// could split surrogate pairs). Diff over code-point arrays instead.
const cpOf = (s, units) => {     // UTF-16 index -> code-point position
  let n = 0;
  for (let k = 0; k < units; n++) k += s.codePointAt(k) > 0xFFFF ? 2 : 1;
  return n;
};
const unitOf = (s, cp) => {      // code-point position -> UTF-16 index
  let k = 0;
  for (let n = 0; n < cp && k < s.length; n++)
    k += s.codePointAt(k) > 0xFFFF ? 2 : 1;
  return k;
};

// Single-edit diff: common prefix/suffix between shadow and textarea.
function diffOps(oldS, newS) {
  if (oldS === newS) return [];
  const a = Array.from(oldS), b = Array.from(newS);
  let p = 0, oe = a.length, ne = b.length;
  while (p < oe && p < ne && a[p] === b[p]) p++;
  while (oe > p && ne > p && a[oe - 1] === b[ne - 1]) { oe--; ne--; }
  const ops = [];
  if (oe > p) ops.push({kind: "del", start: p, end: oe});
  if (ne > p) ops.push({kind: "ins", pos: p, text: b.slice(p, ne).join("")});
  return ops;
}

function applyTraversal(text, op, cursorUnits) {
  const chars = Array.from(text);
  let cur = cpOf(text, cursorUnits);
  let pos = 0;
  const out = [];
  for (const c of op) {
    if (typeof c === "number") {
      for (let i = 0; i < c; i++) out.push(chars[pos + i]);
      pos += c;
    } else if (typeof c === "string") {
      const ins = Array.from(c);
      if (out.length <= cur) cur += ins.length;
      out.push(...ins);
    } else {
      if (out.length < cur) cur = Math.max(out.length, cur - c.d);
      pos += c.d;
    }
  }
  const full = out.join("") + chars.slice(pos).join("");
  return [full, unitOf(full, cur)];
}

function onInput() {
  const ops = diffOps(shadow, ta.value);
  if (ops.length) { queue.push(...ops); shadow = ta.value; pump(); }
}

async function pump() {
  if (inflight || !queue.length) return;
  inflight = true;
  const batch = queue.splice(0);
  try {
    const r = await api("edit", {agent: AGENT, version, ops: batch});
    version = r.version;
    st.textContent = `saved · version ${JSON.stringify(version)}`;
  } catch (e) {
    st.textContent = "edit failed (retrying): " + e;
    queue.unshift(...batch);
    inflight = false;
    setTimeout(pump, 1500);   // back off instead of hammering the server
    return;
  }
  inflight = false;
  pump();
}

async function poll() {
  if (!inflight && !queue.length) {
    const v0 = version;
    try {
      // long-poll: the server holds the request until new ops arrive
      // (braid-subscription equivalent), so remote edits appear promptly
      const r = await api("changes", {version: v0, wait: 20});
      // An edit raced the request: its response version superseded v0 and
      // the traversal below would replay our own op. Drop this round.
      if (!inflight && !queue.length && version === v0) {
        if (r.op.length) {
          const [text, cur] = applyTraversal(shadow, r.op,
                                             ta.selectionStart);
          shadow = text; ta.value = text;
          ta.setSelectionRange(cur, cur);
        }
        version = r.version;
        st.textContent = `synced · version ${JSON.stringify(version)}`;
      }
      pollFails = 0;
    } catch (e) { st.textContent = "sync lost: " + e; pollFails++; }
  }
  // fast re-poll after a successful long-poll; back off when the server
  // is unreachable so dead tabs don't hammer it
  setTimeout(poll, pollFails ? Math.min(500 << pollFails, 8000) : 150);
}

(async () => {
  const r = await fetch(`/doc/${DOC}/state`).then(r => r.json());
  version = r.version; shadow = r.text;
  ta.value = r.text; ta.disabled = false; ta.focus();
  ta.addEventListener("input", onInput);
  st.textContent = "connected as " + AGENT;
  poll();
})();
</script>
"""

VIS_HTML = """<!doctype html>
<meta charset="utf-8"><title>DAG: __DOC__</title>
<style>
 body{font:14px system-ui;margin:1.5em;color:#222}
 #wrap{display:flex;gap:1.5em}
 svg{border:1px solid #ccc;border-radius:6px;background:#fafafa}
 #side{max-width:26em}
 pre{background:#f4f4f4;padding:.8em;border-radius:6px;white-space:pre-wrap}
 .run{cursor:pointer}
 .run:hover rect{stroke:#06c;stroke-width:2}
</style>
<h2>causal graph: __DOC__ <a href="/edit/__DOC__"
 style="font-size:14px">editor</a></h2>
<div id="wrap">
 <svg id="g" width="640" height="200"></svg>
 <div id="side"><em>click a run to time-travel to that version</em>
  <div id="strip" style="margin:.6em 0">
   <button id="loadStrip" type="button">load history strip</button>
   <input id="scrub" type="range" min="0" max="0" value="0"
    style="display:none;width:100%">
   <span id="stripLabel"></span>
  </div>
  <pre id="txt"></pre></div>
</div>
<script>
const DOC = "__DOC__";
// History strip: ONE request -> the server materializes every snapshot
// in a single batched device call (texts_at_versions); scrubbing is then
// instant and offline.
let STRIP = null;
document.getElementById("loadStrip").addEventListener("click", async () => {
  const r = await fetch(`/doc/${DOC}/history`, {
    method: "POST", body: JSON.stringify({n: 24})});
  STRIP = (await r.json()).snapshots;
  const s = document.getElementById("scrub");
  s.max = STRIP.length - 1; s.value = STRIP.length - 1;
  s.style.display = "block";
  showStrip(STRIP.length - 1);
});
document.getElementById("scrub").addEventListener("input",
  e => showStrip(+e.target.value));
function showStrip(i){
  if (!STRIP || !STRIP[i]) return;
  document.getElementById("stripLabel").textContent =
    `version ${STRIP[i].lv} (${i + 1}/${STRIP.length})`;
  document.getElementById("txt").textContent = STRIP[i].text;
}
const NS = "http://www.w3.org/2000/svg";
fetch(`/doc/${DOC}/graph`).then(r => r.json()).then(g => {
  const svg = document.getElementById("g");
  const agents = [...new Set(g.runs.map(r => r.agent))];
  const laneW = 150, rowH = 38;
  svg.setAttribute("width", Math.max(640, agents.length * laneW + 40));
  svg.setAttribute("height", g.runs.length * rowH + 50);
  const ctr = {};
  agents.forEach((a, i) => {
    const t = document.createElementNS(NS, "text");
    t.setAttribute("x", 20 + i * laneW); t.setAttribute("y", 22);
    t.textContent = a; t.setAttribute("font-weight", "600");
    svg.appendChild(t);
  });
  // A parent LV can point mid-run (editing at a stale version): resolve
  // it to the run containing it, not just run ends.
  const runOf = p => g.runs.findIndex(r => r.start <= p && p < r.end);
  g.runs.forEach((r, i) => {
    const x = 20 + agents.indexOf(r.agent) * laneW, y = 36 + i * rowH;
    ctr[i] = [x + 55, y + 11];
    for (const p of r.parents) {
      const pi = runOf(p);
      if (!(pi in ctr)) continue;
      const [px, py] = ctr[pi];
      const e = document.createElementNS(NS, "path");
      e.setAttribute("d", `M${px},${py}C${px},${y - 8} ${x + 55},${py + 16}` +
                          ` ${x + 55},${y}`);
      e.setAttribute("fill", "none"); e.setAttribute("stroke", "#999");
      svg.appendChild(e);
    }
    const grp = document.createElementNS(NS, "g");
    grp.setAttribute("class", "run");
    const b = document.createElementNS(NS, "rect");
    b.setAttribute("x", x); b.setAttribute("y", y);
    b.setAttribute("width", 110); b.setAttribute("height", 22);
    b.setAttribute("rx", 5); b.setAttribute("fill", "#fff");
    b.setAttribute("stroke", "#888");
    const t = document.createElementNS(NS, "text");
    t.setAttribute("x", x + 6); t.setAttribute("y", y + 15);
    t.setAttribute("font-size", "12");
    t.textContent = `[${r.start}..${r.end})`;
    grp.appendChild(b); grp.appendChild(t);
    grp.addEventListener("click", async () => {
      const resp = await fetch(`/doc/${DOC}/at`, {
        method: "POST", body: JSON.stringify({lv: r.end - 1})});
      document.getElementById("txt").textContent = (await resp.json()).text;
    });
    svg.appendChild(grp);
  });
});
</script>
"""

# In-browser CRDT PEER (reference: wiki/client/dt_doc.ts:40-171 — the
# wiki app runs the full CRDT in the browser via WASM; this page runs a
# compact JS engine instead, since wasm bindings are descoped — Python is
# the binding, SURVEY §7). Unlike EDITOR_HTML's positional "dumb client",
# this client owns a real oplog: it edits OFFLINE, merges remote ops
# LOCALLY with the same YjsMod rules as the Python/C++/device engines
# (integrate, merge.rs:154-278: top-row break / bottom-row skip /
# same-gap right-origin comparison with the scanning rollback, agent-name
# then seq tie-break), and exchanges ORIGINAL ops (position + explicit
# parent versions) with the server — positions are never transformed by
# the server for this client.
CRDT_HTML = """<!doctype html>
<meta charset="utf-8"><title>crdt: __DOC__</title>
<style>
 body{font:15px system-ui;margin:2em auto;max-width:52em;color:#222}
 textarea{width:100%;height:22em;font:14px/1.5 ui-monospace,monospace;
  padding:1em;border:1px solid #bbb;border-radius:8px;box-sizing:border-box}
 #st{color:#667;font-size:13px;margin-top:.5em}
 label{font-size:13px}
</style>
<h2>__DOC__ <span style="font-size:13px;color:#888">(in-browser CRDT
peer)</span></h2>
<textarea id="t" spellcheck="false"></textarea>
<div><label><input type="checkbox" id="off"> work offline</label></div>
<div id="st">starting…</div>
<script>
const DOC = "__DOC__";
const AGENT = "peer-" + Math.random().toString(36).slice(2, 8);
const ta = document.getElementById("t"), st = document.getElementById("st");
const offBox = document.getElementById("off");

// ---- the engine: a unit-op text CRDT ---------------------------------
// ops: [{agent, seq, parents:[[a,s]...], kind:'ins'|'del', pos, ch}]
// GENERATED at import time from diamond_types_tpu/tools/crdt_replay_src.py
// (the same Python source the fuzz + golden-vector suites execute) via
// tools/py2js.py — there is no hand-written copy to drift. Convergence =
// the same YjsMod order as every other engine in this repo; replay is an
// O(n^2) full recompute — fine for interactive docs, and it keeps this
// client auditable against the reference semantics.
__ENGINE_JS__
// ---- client bookkeeping -----------------------------------------------
const eng = {
  ops: [], byKey: new Map(),            // "a:s" -> op index
  nextSeq: 0, unpushed: 0,              // our own op bookkeeping
  frontier: [],                         // [[agent, seq]...] local heads
};

function addOp(op) {
  if (eng.byKey.has(op_key(op.agent, op.seq))) return false;
  eng.byKey.set(op_key(op.agent, op.seq), eng.ops.length);
  eng.ops.push(op);
  return true;
}

function localOp(kind, pos, ch) {
  const op = {agent: AGENT, seq: eng.nextSeq++, parents: eng.frontier,
              kind, pos, ch};
  addOp(op);
  eng.frontier = [[AGENT, op.seq]];
  eng.unpushed++;
  return op;
}

// ---- UI + sync --------------------------------------------------------
let shadow = "";

function onInput() {
  const now = ta.value;
  if (now === shadow) return;
  // Diff over CODE POINTS: positions on the wire are code points, and a
  // raw UTF-16 index loop would push lone surrogate halves as op
  // content for astral chars (which the server rejects).
  const a = Array.from(shadow), b = Array.from(now);
  let p = 0, oe = a.length, ne = b.length;
  while (p < oe && p < ne && a[p] === b[p]) p++;
  while (oe > p && ne > p && a[oe - 1] === b[ne - 1]) { oe--; ne--; }
  // unit deletes: removing [p, oe) one char at a time — each removal
  // shifts the next target into position p, so every unit deletes at p
  for (let x = p; x < oe; x++) localOp("del", p, null);
  for (let x = p; x < ne; x++) localOp("ins", x, b[x]);
  shadow = now;
  st.textContent = "local edit (" + eng.unpushed + " unsynced)";
}

function rerender() {
  const text = replay(eng.ops);
  if (text === null) return;
  const cur = ta.selectionStart;
  shadow = text;
  if (ta.value !== text) {
    ta.value = text;
    ta.setSelectionRange(cur, cur);
  }
}

async function syncOnce() {
  if (offBox.checked) return;
  const have = {};
  for (const op of eng.ops) {
    have[op.agent] = Math.max(have[op.agent] || 0, op.seq + 1);
  }
  const push = [];
  for (const op of eng.ops) {
    if (op.agent === AGENT && op.seq >= eng.nextSeq - eng.unpushed) {
      push.push({agent: op.agent, seq: op.seq, parents: op.parents,
                 kind: op.kind, pos: op.pos,
                 ...(op.kind === "ins" ? {content: op.ch} : {len: 1})});
    }
  }
  try {
    const r = await fetch(`/doc/${DOC}/ops`, {method: "POST",
      body: JSON.stringify({have, push})}).then(r => r.json());
    // ops typed while the request was in flight incremented unpushed
    // AFTER `push` was built — subtract only what this round sent, or
    // the in-flight edits would be orphaned forever
    eng.unpushed -= push.length;
    let fresh = 0;
    for (const row of r.ops) {
      // expand run rows into unit ops (chained parents within the run);
      // CODE POINTS, not UTF-16 units — indexing row.content by unit
      // would split astral chars into lone-surrogate ops with
      // over-counted seqs (ops and positions are code-point-addressed
      // everywhere on the wire)
      const chars = row.kind === "ins" ? Array.from(row.content) : null;
      const units = row.kind === "ins" ? chars.length : row.len;
      for (let u = 0; u < units; u++) {
        // fwd deletes repeat at the span start (each removal shifts the
        // next char in); reverse (backspace) runs walk end-1 downward
        const dpos = row.fwd ? row.pos : row.pos + (units - 1 - u);
        const op = {agent: row.agent, seq: row.seq + u,
          parents: u === 0 ? row.parents : [[row.agent, row.seq + u - 1]],
          kind: row.kind,
          pos: row.kind === "ins" ? row.pos + u : dpos,
          ch: row.kind === "ins" ? chars[u] : null};
        if (addOp(op)) fresh++;
      }
    }
    if (fresh) {
      // remote heads join our frontier
      const f = new Map(eng.frontier.map(([a, s]) => [a, s]));
      for (const [a, s] of r.version) {
        if (a !== AGENT) f.set(a, Math.max(f.get(a) ?? -1, s));
      }
      eng.frontier = [...f.entries()];
      rerender();
    }
    st.textContent = `synced · ${eng.ops.length} ops · ` +
      (offBox.checked ? "offline" : "online");
  } catch (e) {
    st.textContent = "sync failed: " + e;
  }
}

ta.addEventListener("input", onInput);
setInterval(syncOnce, 1200);
syncOnce().then(rerender);
</script>
"""

def _generate_engine_js() -> str:
    """Transpile the single-source engine (crdt_replay_src.py) to the JS
    shipped in the page. Raises UnsupportedConstruct at import time if
    the source leaves the transpilable subset — the generation-time
    assertion that replaced the old sha256 pin (VERDICT r4 #5): the
    emitted JS is never stored, so it cannot be hand-edited out of sync
    with the Python the fuzz/golden suites execute."""
    from . import crdt_replay_src
    from .py2js import transpile_module
    return transpile_module(crdt_replay_src)


_ENGINE_JS = _generate_engine_js()
if "__ENGINE_JS__" not in CRDT_HTML:
    # a real exception, not an assert: under python -O an assert would
    # vanish and the editor page would ship with no engine at all
    raise RuntimeError("CRDT_HTML engine injection marker missing")
CRDT_HTML = CRDT_HTML.replace("__ENGINE_JS__", _ENGINE_JS)


def crdt_engine_js() -> str:
    """The in-browser CRDT ENGINE as shipped — the transpiled output of
    tools/crdt_replay_src.py (the golden conformance fixture pins the
    SOURCE module; regenerate with python -m tests.gen_crdt_golden after
    any engine edit)."""
    return _ENGINE_JS
