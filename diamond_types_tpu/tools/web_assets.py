"""Static HTML/JS for the browser demo client and merge visualizer.

Capability mirror of the reference's browser tier (reference:
wiki/client/dt_doc.ts:40-171 — a live collaborative editor against the sync
server; vis/src/App.svelte — the merge/DAG visualizer). The reference's
client runs the CRDT itself via WASM; this client is the reference's OTHER
documented integration mode — a plain positional ("dumb") client speaking
operational transform (reference README.md:31-33: "interoperable with
positional updates ... via operational transform"), so the browser needs no
CRDT at all: it sends positional edits tagged with the version it saw and
catches up by applying server-computed traversal ops (text/ot.py).

Caveat (demo-scope): JS strings are UTF-16; traversal positions are unicode
chars. Text outside the BMP would need the wchar conversion endpoints
(core/unicount.py) — the reference wiki client has the same split.
"""

INDEX_HTML = """<!doctype html>
<meta charset="utf-8"><title>diamond-types-tpu</title>
<style>
 body{font:15px system-ui;margin:3em auto;max-width:40em;color:#222}
 input{font:inherit;padding:.3em}</style>
<h1>diamond-types-tpu sync server</h1>
<p>Open a document (creates it if missing):</p>
<form onsubmit="go();return false">
 <input id="d" placeholder="doc id" value="note">
 <button>edit</button>
 <button type=button onclick="vis()">visualize</button>
</form>
<script>
 const f=()=>document.getElementById('d').value.trim()||'note';
 function go(){location.href='/edit/'+encodeURIComponent(f())}
 function vis(){location.href='/vis/'+encodeURIComponent(f())}
</script>
"""

EDITOR_HTML = """<!doctype html>
<meta charset="utf-8"><title>edit: __DOC__</title>
<style>
 body{font:15px system-ui;margin:2em auto;max-width:46em;color:#222}
 textarea{width:100%;height:24em;font:14px/1.5 ui-monospace,monospace;
          padding:1em;box-sizing:border-box;border:1px solid #bbb;
          border-radius:6px}
 #st{color:#777;font-size:13px;margin-top:.5em}
 a{color:#06c}
</style>
<h2>__DOC__ <a href="/vis/__DOC__" style="font-size:14px">DAG</a></h2>
<textarea id="t" spellcheck="false" disabled>loading…</textarea>
<div id="st">connecting…</div>
<script>
const DOC = "__DOC__";
const AGENT = "web-" + Math.random().toString(36).slice(2, 8);
const ta = document.getElementById("t"), st = document.getElementById("st");
let version = null, shadow = "", inflight = false, queue = [];
let pollFails = 0;

const api = (path, body) => fetch(`/doc/${DOC}/${path}`, {
  method: "POST", body: JSON.stringify(body)}).then(r => r.json());

// Single-edit diff: common prefix/suffix between shadow and textarea.
function diffOps(oldS, newS) {
  if (oldS === newS) return [];
  let p = 0, oe = oldS.length, ne = newS.length;
  while (p < oe && p < ne && oldS[p] === newS[p]) p++;
  while (oe > p && ne > p && oldS[oe - 1] === newS[ne - 1]) { oe--; ne--; }
  const ops = [];
  if (oe > p) ops.push({kind: "del", start: p, end: oe});
  if (ne > p) ops.push({kind: "ins", pos: p, text: newS.slice(p, ne)});
  return ops;
}

function applyTraversal(text, op, cursor) {
  let pos = 0, out = "", cur = cursor;
  for (const c of op) {
    if (typeof c === "number") { out += text.slice(pos, pos + c); pos += c; }
    else if (typeof c === "string") {
      if (out.length <= cur) cur += c.length;
      out += c;
    } else {
      if (out.length < cur) cur = Math.max(out.length, cur - c.d);
      pos += c.d;
    }
  }
  return [out + text.slice(pos), cur];
}

function onInput() {
  const ops = diffOps(shadow, ta.value);
  if (ops.length) { queue.push(...ops); shadow = ta.value; pump(); }
}

async function pump() {
  if (inflight || !queue.length) return;
  inflight = true;
  const batch = queue.splice(0);
  try {
    const r = await api("edit", {agent: AGENT, version, ops: batch});
    version = r.version;
    st.textContent = `saved · version ${JSON.stringify(version)}`;
  } catch (e) {
    st.textContent = "edit failed (retrying): " + e;
    queue.unshift(...batch);
    inflight = false;
    setTimeout(pump, 1500);   // back off instead of hammering the server
    return;
  }
  inflight = false;
  pump();
}

async function poll() {
  if (!inflight && !queue.length) {
    const v0 = version;
    try {
      // long-poll: the server holds the request until new ops arrive
      // (braid-subscription equivalent), so remote edits appear promptly
      const r = await api("changes", {version: v0, wait: 20});
      // An edit raced the request: its response version superseded v0 and
      // the traversal below would replay our own op. Drop this round.
      if (!inflight && !queue.length && version === v0) {
        if (r.op.length) {
          const [text, cur] = applyTraversal(shadow, r.op,
                                             ta.selectionStart);
          shadow = text; ta.value = text;
          ta.setSelectionRange(cur, cur);
        }
        version = r.version;
        st.textContent = `synced · version ${JSON.stringify(version)}`;
      }
      pollFails = 0;
    } catch (e) { st.textContent = "sync lost: " + e; pollFails++; }
  }
  // fast re-poll after a successful long-poll; back off when the server
  // is unreachable so dead tabs don't hammer it
  setTimeout(poll, pollFails ? Math.min(500 << pollFails, 8000) : 150);
}

(async () => {
  const r = await fetch(`/doc/${DOC}/state`).then(r => r.json());
  version = r.version; shadow = r.text;
  ta.value = r.text; ta.disabled = false; ta.focus();
  ta.addEventListener("input", onInput);
  st.textContent = "connected as " + AGENT;
  poll();
})();
</script>
"""

VIS_HTML = """<!doctype html>
<meta charset="utf-8"><title>DAG: __DOC__</title>
<style>
 body{font:14px system-ui;margin:1.5em;color:#222}
 #wrap{display:flex;gap:1.5em}
 svg{border:1px solid #ccc;border-radius:6px;background:#fafafa}
 #side{max-width:26em}
 pre{background:#f4f4f4;padding:.8em;border-radius:6px;white-space:pre-wrap}
 .run{cursor:pointer}
 .run:hover rect{stroke:#06c;stroke-width:2}
</style>
<h2>causal graph: __DOC__ <a href="/edit/__DOC__"
 style="font-size:14px">editor</a></h2>
<div id="wrap">
 <svg id="g" width="640" height="200"></svg>
 <div id="side"><em>click a run to time-travel to that version</em>
  <div id="strip" style="margin:.6em 0">
   <button id="loadStrip" type="button">load history strip</button>
   <input id="scrub" type="range" min="0" max="0" value="0"
    style="display:none;width:100%">
   <span id="stripLabel"></span>
  </div>
  <pre id="txt"></pre></div>
</div>
<script>
const DOC = "__DOC__";
// History strip: ONE request -> the server materializes every snapshot
// in a single batched device call (texts_at_versions); scrubbing is then
// instant and offline.
let STRIP = null;
document.getElementById("loadStrip").addEventListener("click", async () => {
  const r = await fetch(`/doc/${DOC}/history`, {
    method: "POST", body: JSON.stringify({n: 24})});
  STRIP = (await r.json()).snapshots;
  const s = document.getElementById("scrub");
  s.max = STRIP.length - 1; s.value = STRIP.length - 1;
  s.style.display = "block";
  showStrip(STRIP.length - 1);
});
document.getElementById("scrub").addEventListener("input",
  e => showStrip(+e.target.value));
function showStrip(i){
  if (!STRIP || !STRIP[i]) return;
  document.getElementById("stripLabel").textContent =
    `version ${STRIP[i].lv} (${i + 1}/${STRIP.length})`;
  document.getElementById("txt").textContent = STRIP[i].text;
}
const NS = "http://www.w3.org/2000/svg";
fetch(`/doc/${DOC}/graph`).then(r => r.json()).then(g => {
  const svg = document.getElementById("g");
  const agents = [...new Set(g.runs.map(r => r.agent))];
  const laneW = 150, rowH = 38;
  svg.setAttribute("width", Math.max(640, agents.length * laneW + 40));
  svg.setAttribute("height", g.runs.length * rowH + 50);
  const ctr = {};
  agents.forEach((a, i) => {
    const t = document.createElementNS(NS, "text");
    t.setAttribute("x", 20 + i * laneW); t.setAttribute("y", 22);
    t.textContent = a; t.setAttribute("font-weight", "600");
    svg.appendChild(t);
  });
  // A parent LV can point mid-run (editing at a stale version): resolve
  // it to the run containing it, not just run ends.
  const runOf = p => g.runs.findIndex(r => r.start <= p && p < r.end);
  g.runs.forEach((r, i) => {
    const x = 20 + agents.indexOf(r.agent) * laneW, y = 36 + i * rowH;
    ctr[i] = [x + 55, y + 11];
    for (const p of r.parents) {
      const pi = runOf(p);
      if (!(pi in ctr)) continue;
      const [px, py] = ctr[pi];
      const e = document.createElementNS(NS, "path");
      e.setAttribute("d", `M${px},${py}C${px},${y - 8} ${x + 55},${py + 16}` +
                          ` ${x + 55},${y}`);
      e.setAttribute("fill", "none"); e.setAttribute("stroke", "#999");
      svg.appendChild(e);
    }
    const grp = document.createElementNS(NS, "g");
    grp.setAttribute("class", "run");
    const b = document.createElementNS(NS, "rect");
    b.setAttribute("x", x); b.setAttribute("y", y);
    b.setAttribute("width", 110); b.setAttribute("height", 22);
    b.setAttribute("rx", 5); b.setAttribute("fill", "#fff");
    b.setAttribute("stroke", "#888");
    const t = document.createElementNS(NS, "text");
    t.setAttribute("x", x + 6); t.setAttribute("y", y + 15);
    t.setAttribute("font-size", "12");
    t.textContent = `[${r.start}..${r.end})`;
    grp.appendChild(b); grp.appendChild(t);
    grp.addEventListener("click", async () => {
      const resp = await fetch(`/doc/${DOC}/at`, {
        method: "POST", body: JSON.stringify({lv: r.end - 1})});
      document.getElementById("txt").textContent = (await resp.json()).text;
    });
    svg.appendChild(grp);
  });
});
</script>
"""
