"""Dump an oplog's columnar merge state to the binary format consumed by
native/bench_main.cpp (standalone gprof/perf harness for the C++ engine).

Usage: python -m diamond_types_tpu.tools.dump_columns IN.dt OUT.bin
"""

from __future__ import annotations

import struct
import sys

import numpy as np

# 'DTCOL' + format version; bump when columns change (bench_main.cpp
# checks the same constant)
DUMP_MAGIC = 0x4454434F4C_02


def dump(oplog, path: str) -> None:
    g = oplog.cg.graph
    starts, ends, shadows, indptr, flat = g.as_arrays()
    if flat.size == 0:
        flat = np.zeros(1, dtype=np.int64)
    gr = oplog.cg.agent_assignment.global_runs
    runs = oplog.ops.runs
    with open(path, "wb") as f:
        # magic+version header (checked by bench_main.cpp): a stale dump
        # fed to a newer harness must fail with an actionable message,
        # not a mid-file EOF
        f.write(struct.pack("<q", DUMP_MAGIC))
        names = oplog.cg.agent_assignment.agent_names
        f.write(struct.pack("<q", len(names)))
        for name in names:
            b = name.encode("utf8")
            f.write(struct.pack("<q", len(b)))
            f.write(b)

        def vec(a, dtype):
            a = np.ascontiguousarray(np.asarray(a, dtype=dtype))
            f.write(struct.pack("<q", a.size))
            f.write(a.tobytes())

        vec(starts, np.int64)
        vec(ends, np.int64)
        vec(shadows, np.int64)
        vec(indptr, np.int64)
        vec(flat, np.int64)
        vec([r[0] for r in gr], np.int64)
        vec([r[1] for r in gr], np.int64)
        vec([r[2] for r in gr], np.int64)
        vec([r[3] for r in gr], np.int64)
        vec([r.lv for r in runs], np.int64)
        vec([r.kind for r in runs], np.uint8)
        vec([1 if r.fwd else 0 for r in runs], np.uint8)
        vec([r.start for r in runs], np.int64)
        vec([r.end for r in runs], np.int64)
        # content columns (same layout NativeContext.sync feeds
        # dt_load_ops/dt_load_ins_arena — one shared builder) so the
        # harness can also drive dt_merge_into_doc's assembly path
        from ..native.core import content_columns
        cp, arena, _ = content_columns(oplog)
        vec(cp, np.int64)
        vec(arena, np.int32)
        vec(sorted(oplog.cg.version), np.int64)


def main() -> None:
    from ..encoding.decode import load_oplog
    with open(sys.argv[1], "rb") as f:
        ol = load_oplog(f.read())
    dump(ol, sys.argv[2])
    print(f"dumped {len(ol)} ops -> {sys.argv[2]}")


if __name__ == "__main__":
    main()
