"""THE single source of the in-browser CRDT engine's replay algorithm.

This module is written in a restricted, JS-expressible Python subset and
is BOTH artifacts at once (VERDICT r4 #5):

  * executed directly by the Python test/fuzz/golden-vector suite
    (tests/test_crdt_client_logic.py) — the oracle-blessed conformance
    vectors run against THIS code;
  * transpiled to the JavaScript shipped inside the editor page
    (tools/py2js.py, embedded by tools/web_assets.py at import time) —
    the emitted JS is generated, never stored, so it cannot be
    hand-edited out of sync; the transpiler rejects any construct
    outside the subset at generation time.

Algorithm: unit-op text CRDT replay — topological order with
(agent, seq) ties, ancestor sets, origin resolution against the visible
item list, and the YjsMod integrate state machine with the scanning
rollback (reference: src/listmerge/merge.rs:154-278 integrate,
merge.rs:407-424 origin-right resolution). Convergence therefore matches
every other engine in this repo; replay is a full O(n^2) recompute —
fine for interactive docs, and it keeps the client auditable.

Subset rules (enforced by py2js): no tuples, comprehensions, slices,
generators, f-strings, kwargs or classes; dict records with string-
literal keys only (they become JS object properties); lists via
append/insert/pop/len; loops via range()/direct iteration; bitwise ops
only on sub-30-bit non-negative words (JS bitwise is signed 32-bit);
agent ordering uses plain `<` on strings (JS compares UTF-16 units,
Python code points — identical for BMP agent names, which the server
edge ENFORCES: astral-named agents are rejected at input validation).

Ancestor sets are 30-bit word arrays (anc_add/anc_has below), the same
word-wise representation the pre-single-source JS used — per-keystroke
replay cost stays O(n^2/30), not O(n^2) Set traffic.

Ops: {"agent": str, "seq": int, "parents": [[agent, seq]...],
      "kind": "ins"|"del", "pos": int, "ch": str|None}
"""


def dict_has(d, k):
    return k in d


def op_key(agent, seq):
    return agent + ":" + str(seq)


def replay(ops):
    """Replay every op in causal order; returns the document text, or
    None when a dependency is missing (caller waits for more ops)."""
    n = len(ops)
    by_key = {}
    for i in range(n):
        by_key[op_key(ops[i]["agent"], ops[i]["seq"])] = i

    # topological order, ready set ordered by (agent, seq)
    indeg = []
    for i in range(n):
        indeg.append(0)
    kids = {}
    for i in range(n):
        parents = ops[i]["parents"]
        for p in parents:
            pk = op_key(p[0], p[1])
            if not dict_has(by_key, pk):
                return None           # missing dependency: wait
            j = by_key[pk]
            indeg[i] = indeg[i] + 1
            if not dict_has(kids, j):
                kids[j] = []
            kids[j].append(i)
    ready = []
    for i in range(n):
        if indeg[i] == 0:
            ready.append(i)
    order = []
    while len(ready) > 0:
        # take the (agent, seq)-smallest ready op (explicit scan: the
        # tie-break IS convergence-relevant and must live here, not in
        # a per-language sort shim)
        best = 0
        for r in range(1, len(ready)):
            ra = ops[ready[r]]["agent"]
            ba = ops[ready[best]]["agent"]
            if ra < ba:
                best = r
            elif ra == ba and ops[ready[r]]["seq"] < ops[ready[best]]["seq"]:
                best = r
        i = ready.pop(best)
        order.append(i)
        if dict_has(kids, i):
            for k in kids[i]:
                indeg[k] = indeg[k] - 1
                if indeg[k] == 0:
                    ready.append(k)
    if len(order) != n:
        return None                   # cycle = corrupt input

    # ancestor bitsets (30-bit words): anc[i] = parents union their
    # ancestors
    nw = n // 30 + 1
    anc = []
    for i in range(n):
        row = []
        for w in range(nw):
            row.append(0)
        anc.append(row)
    for idx in range(len(order)):
        i = order[idx]
        for p in ops[i]["parents"]:
            j = by_key[op_key(p[0], p[1])]
            for w in range(nw):
                anc[i][w] = anc[i][w] | anc[j][w]
            anc_add(anc[i], j)

    # items: one per insert op, in document order as built
    items = []

    for idx in range(len(order)):
        i = order[idx]
        op = ops[i]
        if op["kind"] == "del":
            seen = 0
            for x in range(len(items)):
                it = items[x]
                if _visible_at(anc, i, it):
                    if seen == op["pos"]:
                        it["dels"].append(i)
                        break
                    seen = seen + 1
            continue
        # insert: origin-left = visible item at pos-1; cursor after it
        ol_idx = -1
        seen = 0
        if op["pos"] > 0:
            for x in range(len(items)):
                if _visible_at(anc, i, items[x]):
                    seen = seen + 1
                    if seen == op["pos"]:
                        ol_idx = x
                        break
        # origin-right: first non-NotInsertedYet item after the cursor
        # (merge.rs:407-424 — deleted items count, concurrent ones don't)
        orr_idx = len(items)
        for x in range(ol_idx + 1, len(items)):
            if anc_has(anc[i], items[x]["ins"]):
                orr_idx = x
                break
        if orr_idx < len(items):
            my_orr_key = op_key(items[orr_idx]["a"], items[orr_idx]["s"])
        else:
            my_orr_key = "END"
        # integrate (YjsMod, merge.rs:154-278) — the scanning state
        # machine; rollback lands BEFORE the compared item (merge.rs:233
        # clones the cursor before advancing past it)
        dst = ol_idx + 1
        scanning = False
        scan_start = ol_idx + 1
        for x in range(ol_idx + 1, orr_idx):
            o = items[x]
            if o["ol"] < ol_idx:
                break
            if o["ol"] == ol_idx:
                if o["orrKey"] == my_orr_key:
                    ins_here = op["agent"] < o["a"] or \
                        (op["agent"] == o["a"] and op["seq"] < o["s"])
                    if ins_here:
                        break
                    scanning = False
                else:
                    # right-origin document position comparison (END is
                    # farthest; -1 encodes END in orrItem)
                    o_r = o["orrItem"]
                    if o_r == -1:
                        o_r = n + len(items) + 1
                    my_r = orr_idx
                    if orr_idx >= len(items):
                        my_r = n + len(items) + 1
                    if o_r < my_r:
                        if not scanning:
                            scanning = True
                            scan_start = x
                    else:
                        scanning = False
            dst = x + 1
        if scanning:
            dst = scan_start
        if orr_idx >= len(items):
            orr_item = -1
        else:
            orr_item = orr_idx
        item = {"ins": i, "dels": [], "ol": ol_idx, "a": op["agent"],
                "s": op["seq"], "ch": op["ch"], "orrItem": orr_item,
                "orrKey": my_orr_key}
        # inserting shifts stored item indexes at/after dst
        for x in range(len(items)):
            it = items[x]
            if it["ol"] >= dst:
                it["ol"] = it["ol"] + 1
            if it["orrItem"] != -1 and it["orrItem"] >= dst:
                it["orrItem"] = it["orrItem"] + 1
        if item["ol"] >= dst:
            item["ol"] = item["ol"] + 1
        if item["orrItem"] != -1 and item["orrItem"] >= dst:
            item["orrItem"] = item["orrItem"] + 1
        items.insert(dst, item)

    text = ""
    for x in range(len(items)):
        if len(items[x]["dels"]) == 0:
            text = text + items[x]["ch"]
    return text


def anc_add(row, j):
    row[j // 30] = row[j // 30] | (1 << (j % 30))


def anc_has(row, j):
    return ((row[j // 30] >> (j % 30)) & 1) == 1


def _visible_at(anc, i, it):
    if not anc_has(anc[i], it["ins"]):
        return False
    for d in it["dels"]:
        if anc_has(anc[i], d):
            return False
    return True
