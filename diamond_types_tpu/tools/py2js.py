"""Restricted Python -> JavaScript transpiler for the browser CRDT
engine's single source (tools/crdt_replay_src.py; VERDICT r4 #5).

Deliberately TINY and strict: it understands exactly the subset the
source module's docstring promises and raises `UnsupportedConstruct`
on anything else — that raise IS the generation-time assertion that
replaces the old sha256 pin (the emitted JS is produced from the
executed-and-fuzzed Python at import time, never stored, so the two
artifacts cannot drift; an unsupported edit fails the build instead of
silently shipping untested JS).

Semantics mapping (kept 1:1 so the Python tests vouch for the JS):
  dicts with computed keys  -> plain objects (string/number keys)
  dict records (str-literal subscript) -> object properties
  dict_has(d, k)            -> (k in d)
  set() / .add / set_has    -> new Set() / .add / .has
  list append/insert/pop    -> push / splice
  len(x)                    -> x.length  (lists/strings only)
  str(x)                    -> String(x)
  for v in xs               -> for (const v of xs)   (Array and Set)
  a < b on strings          -> JS native compare (UTF-16 units; BMP-
                               equal to Python's code-point compare)
"""

from __future__ import annotations

import ast
import inspect
import json
import textwrap


class UnsupportedConstruct(SyntaxError):
    pass


def _fail(node, why: str):
    raise UnsupportedConstruct(
        f"py2js: {why} (line {getattr(node, 'lineno', '?')})")


_CMPOPS = {ast.Lt: "<", ast.LtE: "<=", ast.Gt: ">", ast.GtE: ">=",
           ast.Eq: "===", ast.NotEq: "!=="}
# Bitwise ops are 1:1 ONLY under the source subset's contract: word
# values < 2^30 and shift amounts < 30 (JS bitwise is signed 32-bit;
# Python ints are unbounded — sub-30-bit words behave identically).
_BINOPS = {ast.Add: "+", ast.Sub: "-", ast.Mult: "*", ast.Mod: "%",
           ast.BitOr: "|", ast.BitAnd: "&", ast.LShift: "<<",
           ast.RShift: ">>"}


class _Emitter(ast.NodeVisitor):
    def __init__(self) -> None:
        self.lines: list[str] = []
        self.indent = 0

    def out(self, s: str) -> None:
        self.lines.append("  " * self.indent + s)

    # ---- expressions -> strings -----------------------------------------

    def expr(self, e: ast.expr) -> str:
        if isinstance(e, ast.Constant):
            v = e.value
            if v is None:
                return "null"
            if v is True:
                return "true"
            if v is False:
                return "false"
            if isinstance(v, str):
                return json.dumps(v)
            if isinstance(v, (int, float)):
                return repr(v)
            _fail(e, f"constant {v!r}")
        if isinstance(e, ast.Name):
            return e.id
        if isinstance(e, ast.Subscript):
            base = self.expr(e.value)
            sl = e.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                return f"{base}.{sl.value}"      # record field
            return f"{base}[{self.expr(sl)}]"
        if isinstance(e, ast.BinOp):
            if isinstance(e.op, ast.FloorDiv):
                # non-negative ints only (the subset's contract)
                return f"Math.floor({self.expr(e.left)} / " \
                       f"{self.expr(e.right)})"
            op = _BINOPS.get(type(e.op))
            if op is None:
                _fail(e, f"operator {type(e.op).__name__}")
            return f"({self.expr(e.left)} {op} {self.expr(e.right)})"
        if isinstance(e, ast.UnaryOp):
            if isinstance(e.op, ast.Not):
                return f"(!{self.expr(e.operand)})"
            if isinstance(e.op, ast.USub):
                return f"(-{self.expr(e.operand)})"
            _fail(e, f"unary {type(e.op).__name__}")
        if isinstance(e, ast.BoolOp):
            op = " && " if isinstance(e.op, ast.And) else " || "
            return "(" + op.join(self.expr(v) for v in e.values) + ")"
        if isinstance(e, ast.Compare):
            if len(e.ops) != 1:
                _fail(e, "chained comparison")
            op = _CMPOPS.get(type(e.ops[0]))
            if op is None:
                _fail(e, f"comparison {type(e.ops[0]).__name__} (use "
                         f"dict_has/set_has for membership)")
            return f"({self.expr(e.left)} {op} " \
                   f"{self.expr(e.comparators[0])})"
        if isinstance(e, ast.Call):
            return self.call(e)
        if isinstance(e, ast.Dict):
            parts = []
            for k, v in zip(e.keys, e.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    _fail(e, "dict literal with non-string-literal key")
                parts.append(f"{k.value}: {self.expr(v)}")
            return "{" + ", ".join(parts) + "}"
        if isinstance(e, ast.List):
            return "[" + ", ".join(self.expr(v) for v in e.elts) + "]"
        _fail(e, f"expression {type(e).__name__}")

    def call(self, e: ast.Call) -> str:
        if e.keywords:
            _fail(e, "keyword arguments")
        args = [self.expr(a) for a in e.args]
        if isinstance(e.func, ast.Name):
            name = e.func.id
            if name == "len" and len(args) == 1:
                return f"{args[0]}.length"
            if name == "str" and len(args) == 1:
                return f"String({args[0]})"
            if name == "set" and not args:
                return "new Set()"
            if name == "range":
                _fail(e, "range() outside a for loop")
            if name == "dict_has" and len(args) == 2:
                return f"({args[1]} in {args[0]})"
            if name == "set_has" and len(args) == 2:
                return f"{args[0]}.has({args[1]})"
            return f"{name}({', '.join(args)})"   # local function call
        if isinstance(e.func, ast.Attribute):
            base = self.expr(e.func.value)
            meth = e.func.attr
            if meth == "append" and len(args) == 1:
                return f"{base}.push({args[0]})"
            if meth == "insert" and len(args) == 2:
                return f"{base}.splice({args[0]}, 0, {args[1]})"
            if meth == "pop" and len(args) == 1:
                return f"{base}.splice({args[0]}, 1)[0]"
            if meth == "pop" and not args:
                return f"{base}.pop()"
            if meth == "add" and len(args) == 1:
                return f"{base}.add({args[0]})"
            _fail(e, f"method .{meth}()")
        _fail(e, "call form")

    # ---- statements ------------------------------------------------------

    def stmt(self, s: ast.stmt) -> None:
        if isinstance(s, ast.Assign):
            if len(s.targets) != 1:
                _fail(s, "multiple assignment targets")
            t = s.targets[0]
            if isinstance(t, ast.Name):
                # Name assignments are handled (with declared-name
                # tracking) by stmt_hoisted — reaching here would bypass
                # the hoisting contract
                _fail(s, "name assignment outside hoisting path")
            elif isinstance(t, ast.Subscript):
                self.out(f"{self.expr(t)} = {self.expr(s.value)};")
            else:
                _fail(s, f"assignment to {type(t).__name__}")
        elif isinstance(s, ast.Expr):
            if isinstance(s.value, ast.Constant):
                return  # docstring / bare literal
            self.out(self.expr(s.value) + ";")
        elif isinstance(s, ast.Return):
            self.out("return" + (f" {self.expr(s.value)}"
                                 if s.value is not None else "") + ";")
        elif isinstance(s, ast.If):
            self.out(f"if ({self.expr(s.test)}) {{")
            self.block(s.body)
            cur = s
            while len(cur.orelse) == 1 and isinstance(cur.orelse[0], ast.If):
                cur = cur.orelse[0]
                self.out(f"}} else if ({self.expr(cur.test)}) {{")
                self.block(cur.body)
            if cur.orelse:
                self.out("} else {")
                self.block(cur.orelse)
            self.out("}")
        elif isinstance(s, ast.While):
            if s.orelse:
                _fail(s, "while-else")
            self.out(f"while ({self.expr(s.test)}) {{")
            self.block(s.body)
            self.out("}")
        elif isinstance(s, ast.For):
            self.for_stmt(s)
        elif isinstance(s, ast.Break):
            self.out("break;")
        elif isinstance(s, ast.Continue):
            self.out("continue;")
        else:
            _fail(s, f"statement {type(s).__name__}")

    def for_stmt(self, s: ast.For) -> None:
        if s.orelse:
            _fail(s, "for-else")
        if not isinstance(s.target, ast.Name):
            _fail(s, "destructuring for target")
        v = s.target.id
        it = s.iter
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "range":
            args = [self.expr(a) for a in it.args]
            if len(args) == 1:
                lo, hi = "0", args[0]
            elif len(args) == 2:
                lo, hi = args
            else:
                _fail(s, "range() step")
            self.out(f"for (var {v} = {lo}; {v} < {hi}; {v}++) {{")
        else:
            # `var`, matching assignment emission: a body assignment to
            # the loop variable must not emit an invalid redeclaration
            # against a `const` loop head
            self.out(f"for (var {v} of {self.expr(it)}) {{")
        self.declared.add(v)
        self.block(s.body)
        self.out("}")

    def block(self, body: list[ast.stmt]) -> None:
        self.indent += 1
        # JS has no block-scoped redeclaration via `let`; hoist by
        # tracking names already declared in this function
        for st in body:
            self.stmt_hoisted(st)
        self.indent -= 1

    # `let x = ...` twice in sibling blocks is legal JS, but a
    # re-assignment in the SAME scope after a previous let must not
    # redeclare. Track per-function declared names.
    def stmt_hoisted(self, s: ast.stmt) -> None:
        if isinstance(s, ast.Assign) and len(s.targets) == 1 \
                and isinstance(s.targets[0], ast.Name):
            # `var`, not `let`: Python assignments are function-scoped,
            # and a first assignment inside a nested block must remain
            # visible after it (let would be block-scoped)
            name = s.targets[0].id
            if name in self.declared:
                self.out(f"{name} = {self.expr(s.value)};")
            else:
                self.declared.add(name)
                self.out(f"var {name} = {self.expr(s.value)};")
            return
        self.stmt(s)

    # ---- functions -------------------------------------------------------

    def func(self, f: ast.FunctionDef) -> None:
        if f.args.posonlyargs or f.args.kwonlyargs or f.args.vararg \
                or f.args.kwarg or f.args.defaults:
            _fail(f, "non-positional function arguments")
        args = ", ".join(a.arg for a in f.args.args)
        self.declared = {a.arg for a in f.args.args}
        self.out(f"function {f.name}({args}) {{")
        self.block(f.body)
        self.out("}")


def transpile_module(module) -> str:
    """Emit the module's functions as JavaScript. Raises
    UnsupportedConstruct on anything outside the subset."""
    tree = ast.parse(textwrap.dedent(inspect.getsource(module)))
    em = _Emitter()
    for node in tree.body:
        if isinstance(node, ast.Expr) and isinstance(node.value,
                                                     ast.Constant):
            continue  # module docstring
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        if not isinstance(node, ast.FunctionDef):
            _fail(node, f"top-level {type(node).__name__}")
        if node.name in ("dict_has", "set_has"):
            # membership shims: emitted as operators at call sites, not
            # as functions (their Python bodies use `in`, which the
            # subset otherwise forbids)
            continue
        em.func(node)
        em.out("")
    return "\n".join(em.lines)
