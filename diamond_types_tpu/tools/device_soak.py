"""Device-session endurance soak — hours of realtime merge-per-edit
traffic against the REAL chip, parity-checked against the host engine
on every sync.

The device benches measure per-call latency over seconds; this harness
measures something they cannot: sustained runtime stability. It drives
a `DeviceZoneSession` (tpu/zone_session.py) with the same 2-agent
continuation shape as the session bench — each agent keeps typing from
its own head — and asserts `sess.text() == oplog.checkout_tip()
.snapshot()` after EVERY sync, so the device state, the sliced-resync
path (capacity growth naturally forces full rebuilds as the document
grows), and the micro-tape continuation are all parity-gated for the
whole run. Worker crashes (the tunneled runtime's failure mode) are
caught, logged, and recovered from by rebuilding the session; a parity
MISMATCH is logged and stops the run (that is a correctness bug, not
an environment event).

Coexistence: pauses while an official `bench.py` run is in flight
(same `.bench_active` mechanism as tools/soak.py) and does NOT hold
the device lock — single probes from device_watcher.py interleave
harmlessly between programs.

Usage:
  python -m diamond_types_tpu.tools.device_soak \
      --corpus friendsforever.dt --hours 3 --log DEVICE_SOAK.jsonl
Stop early: touch .stop_device_soak in the repo root.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time
import traceback

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
_STOP = os.path.join(_REPO_ROOT, ".stop_device_soak")
_BENCH_DATA = "/root/reference/benchmark_data"

_bench_mod = []


def _bench_is_active() -> bool:
    if not _bench_mod:
        try:
            sys.path.insert(0, _REPO_ROOT)
            import bench as _b
            _bench_mod.append(_b)
        except Exception:
            _bench_mod.append(None)
    if _bench_mod[0] is None:
        return False
    return _bench_mod[0].bench_is_active()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--corpus", default="friendsforever.dt")
    p.add_argument("--hours", type=float, default=3.0)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--batch-max", type=int, default=8,
                   help="max edits folded per sync")
    p.add_argument("--max-recovery-failures", type=int, default=5,
                   help="bail out after this many CONSECUTIVE failed "
                   "session rebuilds (the runtime is gone, not flaky)")
    p.add_argument("--log", default=None)
    args = p.parse_args(argv)

    out = open(args.log, "a") if args.log else sys.stdout

    def emit(obj):
        obj["ts"] = round(time.time(), 1)
        out.write(json.dumps(obj, ensure_ascii=False) + "\n")
        out.flush()

    import jax
    from ..encoding.decode import load_oplog
    from ..tpu.zone_session import DeviceZoneSession

    with open(os.path.join(_BENCH_DATA, args.corpus), "rb") as f:
        ol = load_oplog(f.read())
    emit({"event": "soak_start", "corpus": args.corpus,
          "backend": jax.default_backend(), "hours": args.hours,
          "n_ops_start": len(ol)})

    rng = random.Random(args.seed)
    t_build0 = time.time()
    sess = DeviceZoneSession(ol)
    sess.touch()
    emit({"event": "session_built",
          "build_s": round(time.time() - t_build0, 1)})

    # The 2-agent continuation shape needs two agents that each OWN at
    # least one op: heads come from _agent_last_lv, and a None head
    # (agent registered but opless, or a single-agent linear corpus)
    # would crash the first one_edit with a useless traceback hours
    # into an unattended run. Validate up front; a missing SECOND
    # agent is repairable by seeding one op at the tip.
    agents = [a for a in range(len(ol.cg.agent_assignment.agent_names))
              if sess._agent_last_lv(a) is not None][:2]
    if not agents:
        emit({"event": "soak_abort", "fatal": True,
              "why": f"corpus {args.corpus} has no agent with any ops; "
              "cannot derive an editing head (pick a non-empty corpus)"})
        return 1
    heads = {}
    if len(agents) == 1:
        a2 = ol.get_or_create_agent_id("device-soak-2")
        heads[a2] = [ol.add_insert_at(a2, list(ol.version), 0, "q")]
        agents.append(a2)
        emit({"event": "seeded_second_agent", "agent": "device-soak-2",
              "why": "corpus has a single editing agent; the soak's "
              "continuation shape needs two concurrent heads"})
    for a in agents:
        heads.setdefault(a, [sess._agent_last_lv(a)])
    lens = {a: len(ol.checkout(heads[a]).snapshot()) for a in agents}

    def one_edit(a):
        # inserts only: deletes at random positions are covered by the
        # CI fuzz; growth is the POINT here (it forces capacity resyncs)
        pos = rng.randrange(max(lens[a], 1))
        n = rng.randint(1, 4)
        heads[a] = [ol.add_insert_at(a, heads[a], pos, "q" * n)]
        lens[a] += n

    deadline = time.time() + args.hours * 3600
    syncs = edits = crashes = 0
    recovery_failures = 0
    recovering = False
    resyncs0 = sess.resyncs
    t_report = time.time()
    while time.time() < deadline and not os.path.exists(_STOP):
        if _bench_is_active():
            emit({"event": "paused", "why": "bench.py run in flight"})
            time.sleep(30)
            continue
        if recovering:
            # Rebuild WITHOUT appending new edits: every failed rebuild
            # would otherwise grow the oplog, making each retry strictly
            # harder than the last (and the backlog meaningless). Bail
            # once the failures are consecutive enough to mean "the
            # runtime is gone", not "the runtime blipped".
            try:
                sess = DeviceZoneSession(ol)
                sess.touch()
                got = sess.text()
            except Exception:
                recovery_failures += 1
                emit({"event": "recovery_failed",
                      "consecutive": recovery_failures,
                      "max": args.max_recovery_failures,
                      "error": traceback.format_exc(limit=1)
                      .strip().splitlines()[-1][:200]})
                if recovery_failures >= args.max_recovery_failures:
                    emit({"event": "soak_abort", "fatal": True,
                          "why": f"{recovery_failures} consecutive "
                          "session rebuilds failed; giving up",
                          "syncs": syncs, "edits": edits,
                          "crashes": crashes})
                    return 2
                time.sleep(120)
                continue
            recovering = False
            recovery_failures = 0
            emit({"event": "recovered", "syncs": syncs, "edits": edits})
        else:
            k = rng.randint(1, args.batch_max)
            for i in range(k):
                one_edit(agents[(edits + i) % 2])
            edits += k
            try:
                sess.sync()
                got = sess.text()
            except Exception:
                crashes += 1
                emit({"event": "device_crash", "crashes": crashes,
                      "error": traceback.format_exc(limit=1)
                      .strip().splitlines()[-1][:200]})
                # recover: rebuild the whole session (exercises the
                # sliced resync on the grown oplog) after a settle; the
                # recovery loop above owns the retries
                time.sleep(30)
                recovering = True
                continue
        expected = ol.checkout_tip().snapshot()
        if got != expected:
            emit({"event": "PARITY_MISMATCH", "syncs": syncs,
                  "edits": edits, "fatal": True})
            return 1
        syncs += 1
        if time.time() - t_report > 120:
            emit({"event": "progress", "syncs": syncs, "edits": edits,
                  "resyncs": sess.resyncs - resyncs0, "crashes": crashes,
                  "doc_chars": len(expected), "n_ops": len(ol),
                  "elapsed_s": round(time.time() - (deadline -
                                                    args.hours * 3600))})
            t_report = time.time()
    emit({"event": "soak_end", "syncs": syncs, "edits": edits,
          "resyncs": sess.resyncs - resyncs0, "crashes": crashes,
          "parity": "all syncs byte-identical", "n_ops_end": len(ol)})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
