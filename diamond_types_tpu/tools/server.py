"""Collaborative document sync server + client.

Capability mirror of the reference's wiki demo app (reference:
wiki/server/server.ts:1-60 — an HTTP server holding an OpLog per document,
exchanging patches with clients, persisting .dt files with rate-limited
autosave; wiki/client/dt_doc.ts — the client keeping a local OpLog in sync).

Protocol (JSON/binary over HTTP; peer sync is stateless pull/push of
v1-format binary patches, and the browser tier's /changes endpoint
long-polls as the braid-subscription equivalent):

  GET  /doc/{id}            -> current document text
  GET  /doc/{id}/summary    -> version summary JSON
  POST /doc/{id}/pull       body: client's summary JSON
                            -> binary patch from the common version
  POST /doc/{id}/push       body: binary patch -> {"ok": true,
                            "collisions": n | null} — n > 0 when folding
                            the pushed ops into the pre-push document
                            resolved genuinely colliding concurrent
                            inserts (has_conflicts_when_merging)

Browser tier (the reference's "dumb client" OT mode — README.md:31-33;
clients are positional, the server's CRDT does the merging; see
web_assets.py for the pages):

  GET  /                    -> index page
  GET  /edit/{id}           -> collaborative editor (HTML/JS)
  GET  /vis/{id}            -> causal-graph visualizer (HTML/JS)
  GET  /doc/{id}/state      -> {"text": ..., "version": [[agent, seq]...]}
  POST /doc/{id}/edit       body {"agent", "version", "ops": [{kind:"ins",
                            pos, text} | {kind:"del", start, end}]}
                            -> {"version": ...} (ops applied AT that
                            version; concurrent edits merge via the CRDT)
  POST /doc/{id}/changes    body {"version": ..., "wait": seconds?} ->
                            {"op": traversal, "version": ...} — OT
                            catch-up since `version`; with `wait` the
                            request long-polls until new ops arrive or
                            the timeout lapses (the braid-subscription
                            equivalent: the reference wiki server streams
                            patches to subscribed clients)
  GET  /doc/{id}/graph      -> causal DAG runs JSON (visualizer data)
  GET  /metrics             -> {"serve": scheduler metrics | null,
                            "replication": ... | null, "obs": ...} —
                            JSON by default (Cache-Control: no-store);
                            `?format=prom` switches to Prometheus text
                            exposition (text/plain; version=0.0.4) with
                            every counter/gauge/histogram as dt_*
                            metrics (obs/prom.py); an Accept header
                            asking for application/openmetrics-text (or
                            `?format=openmetrics`) gets OpenMetrics 1.0
                            with trace exemplars and the # EOF
                            terminator
  GET  /debug/events        -> {"events": [...], "recorded", "dropped",
                            ...} — the flight recorder's bounded ring
                            of structured events (lease transitions,
                            fencing rejections, circuit opens,
                            evictions, queue-bound violations),
                            oldest-first (obs/recorder.py);
                            `?since=<seq>` returns only events after
                            that seq (incremental tailing)
  GET  /debug/slo           -> obs/slo.py snapshot: per-objective burn
                            rates (fast 5m / slow 1h) + alert states
                            (ok|warning|burning)
  GET  /debug/hot           -> obs/attrib.py snapshot: top-K docs and
                            agents by ops/bytes/device_s/cache_misses
  POST /doc/{id}/at         body {"lv": n} -> {"text": ...} time travel
  POST /doc/{id}/history    body {"n": k} -> {"snapshots": [{"lv",
                            "text"}...]} oldest-first history strip; with
                            DT_SERVER_DEVICE=1 the whole strip is ONE
                            batched device call (texts_at_versions)

Replication tier (--peers host:port,... — diamond_types_tpu/replicate/;
N server instances jointly own the document space):

  GET  /replicate/ping      -> {"ok", "id", "uptime_s", "incarnation",
                            "view_version", "rejoining", "members"}
                            — health probe + membership gossip
                            piggyback (the probe loop is the gossip
                            transport)
  GET  /replicate/docs      -> {"docs": {id: {"lease": {holder, epoch,
                            state, ttl_s} | null}}, "self"} — doc list
                            + piggybacked lease claims (anti-entropy)
  POST /replicate/lease     body {"action": "propose"|"grant"|
                            "activate"|"status", "doc", "epoch",
                            "holder"?, "ttl_s"?} -> {"ok": bool, ...}
                            — the quorum + handoff wire protocol
                            (idempotent); "propose" is the voter-side
                            promise round (quorum.py)
  POST /replicate/join      body {"id", "incarnation"} -> {"ok",
                            "members", "peers"} — dynamic join; the
                            response carries the responder's view so
                            the joiner learns the mesh in one trip
  POST /replicate/leave     body {"id"} -> {"ok"} — explicit removal
                            (the only operation that shrinks the
                            quorum denominator)

  Ownership: rendezvous placement of docs over the membership universe
  (replicate/membership.py) + quorum-backed epoch leases
  (replicate/ownership.py, replicate/quorum.py); mutations (/push,
  /edit, /ops) for a doc owned elsewhere are proxied to the lease
  holder (header X-DT-Proxied stops a second hop; X-DT-Lease-Epoch
  carries the fencing token — a receiver whose per-doc epoch floor has
  passed it answers 409 {"error": "fenced"} instead of merging; an
  unreachable owner degrades to a local accept that anti-entropy
  reconciles). Lease state machine, quorum safety argument and failure
  modes: serve/README.md.

Run: python -m diamond_types_tpu.tools.server --port 8008 --data-dir docs/
     [--serve-shards N] [--peers host:port,host:port,...]
     [--join host:port]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..causalgraph.summary import intersect_with_summary, summarize_versions
from ..encoding.decode import decode_into, load_oplog
from ..encoding.encode import ENCODE_FULL, ENCODE_PATCH, encode_oplog
from ..obs.trace import TRACE_HEADER, parse_header
from ..text.oplog import OpLog
from ..wire.frames import (FRAME_DOCS, FRAME_OPS, FRAME_PATCH,
                           FRAME_SNAPSHOT, FRAME_STATE, FRAME_SUMMARY,
                           WIRE_CTYPE, WIRE_HEADER, WireError,
                           decode_frame, decode_ops, decode_records,
                           decode_summary, encode_docs, encode_frame,
                           encode_state, encode_summary, is_frame)
from ..wire.snapshot import build_snapshot

# Doc ids are filenames (DocStore writes {data_dir}/{id}.dt) and are
# interpolated into the served pages: restrict to a safe charset.
_DOC_ID_RE = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")


class DocStore:
    """In-memory OpLogs with rate-limited autosave to .dt files
    (reference: wiki/server rate-limited save + atomic replace)."""

    def __init__(self, data_dir: Optional[str] = None,
                 save_interval: float = 3.0) -> None:
        self.data_dir = data_dir
        self.save_interval = save_interval
        self.docs: Dict[str, OpLog] = {}
        self.dirty: Dict[str, float] = {}
        # doc -> consecutive flush failures (encode OR disk write);
        # drives exponential backoff so a persistently-unpersistable doc
        # can't spam stderr and burn O(doc) encode work on every flush
        # pass forever (ADVICE r4)
        self.flush_failures: Dict[str, int] = {}
        # Optional sharded merge scheduler (serve/): when attached, every
        # accepted mutation also queues device-merge work for the doc's
        # shard; its pump thread keeps the session banks warm so reads
        # can come off pre-merged state instead of a cold checkout.
        self.scheduler = None
        # Optional replication node (replicate/): peer mesh membership,
        # doc-ownership leases, anti-entropy. Attached via
        # replicate.attach_replication; when present, mutations for
        # docs this host doesn't own are proxied to the lease holder
        # and the scheduler's admit gate keeps merges owner-only.
        self.replica = None
        # Optional observability bundle (obs/): sampled tracer, flight
        # recorder, per-endpoint latency histograms. serve() attaches
        # one; attach_replication forwards it to the ReplicaNode.
        self.obs = None
        # Optional follower-read tier (read/): staleness-bounded local
        # GETs on non-owner replicas + the shared checkout cache.
        # Attached via read.attach_follower_reads (serve
        # --follower-reads); when absent, GETs keep the classic
        # always-local behavior.
        self.reads = None
        from ..analysis.witness import make_lock
        self.lock = make_lock("store.oplog", "oplog")
        # serializes flush passes; deliberately OUTER to the oplog
        # guard (its own `io` rung in the canonical lock order)
        self.io_lock = make_lock("store.io", "io")
        # Long-poll wakeups (one condition per doc; notified on new ops).
        self._conds: Dict[str, threading.Condition] = {}
        self._stop = threading.Event()
        self._flusher: Optional[threading.Thread] = None

    def start_flusher(self) -> None:
        """Run autosave on a background thread so the (lock-holding) encode
        never stalls request handlers (reference: the wiki server's
        rate-limited autosave is a timer, not inline in handlers)."""
        if self.data_dir is None or self._flusher is not None:
            return

        def loop():
            while not self._stop.wait(max(self.save_interval, 0.25)):
                try:
                    self.flush()
                except OSError:  # pragma: no cover - disk full etc.
                    pass

        self._flusher = threading.Thread(target=loop, daemon=True)
        self._flusher.start()

    def stop_flusher(self) -> None:
        self._stop.set()
        if self._flusher is not None:
            self._flusher.join(timeout=2)
            self._flusher = None

    def attach_scheduler(self, scheduler) -> None:
        """Wire a serve.MergeScheduler built with resolve=self.get and
        sync_lock=self.lock (so bank syncs never race handler threads)."""
        self.scheduler = scheduler

    def submit_merge(self, doc_id: str, n_ops: int = 1, trace=None,
                     qos: Optional[str] = None):
        """Queue merge work for the doc's shard. No-op (returns None)
        when no scheduler is attached. Backpressure rejects are the
        scheduler's problem, not the edit's: the edit is already durably
        in the oplog, so a rejected submit only delays warm state — the
        next accepted submit or a read-triggered flush catches it up.
        MUST be called OUTSIDE self.lock (the pump thread takes
        scheduler.lock then self.lock; a caller holding self.lock here
        would invert that order and deadlock). `trace` is an optional
        obs SpanContext linking the queued work back to the HTTP
        request that produced it; `qos` the ingress-classified QoS
        class (qos/classes.py) deciding the work's flush deadline."""
        sched = self.scheduler
        if sched is None:
            return None
        return sched.submit(doc_id, n_ops=n_ops, trace=trace, qos=qos)

    def cond(self, doc_id: str) -> threading.Condition:
        with self.lock:
            c = self._conds.get(doc_id)
            if c is None:
                c = self._conds[doc_id] = threading.Condition()
            return c

    def notify(self, doc_id: str) -> None:
        c = self.cond(doc_id)
        with c:
            c.notify_all()

    def _path(self, doc_id: str) -> Optional[str]:
        if self.data_dir is None:
            return None
        return os.path.join(self.data_dir, doc_id + ".dt")

    def doc_ids(self):
        """Every doc this store knows: in-memory oplogs plus persisted
        .dt files not yet loaded (anti-entropy peers list against this,
        so a restarted server still offers its on-disk docs)."""
        with self.lock:
            ids = set(self.docs)
        if self.data_dir and os.path.isdir(self.data_dir):
            for name in os.listdir(self.data_dir):
                if name.endswith(".dt") and _DOC_ID_RE.match(name[:-3]):
                    ids.add(name[:-3])
        return sorted(ids)

    def get(self, doc_id: str) -> OpLog:
        with self.lock:
            ol = self.docs.get(doc_id)
            if ol is None:
                path = self._path(doc_id)
                if path and os.path.exists(path):
                    with open(path, "rb") as f:
                        ol = load_oplog(f.read())
                else:
                    ol = OpLog()
                    ol.doc_id = doc_id
                self.docs[doc_id] = ol
            return ol

    def mark_dirty(self, doc_id: str) -> None:
        with self.lock:
            now = time.monotonic()
            t = self.dirty.setdefault(doc_id, now)
            if t > now:
                # the doc was in encode-failure backoff; a new edit
                # changed its content, so a prompt retry is worth it
                self.dirty[doc_id] = now

    def flush(self, force: bool = False) -> None:
        if self.data_dir is None:
            return
        os.makedirs(self.data_dir, exist_ok=True)
        now = time.monotonic()
        # io_lock serializes whole flush passes: without it, a flusher
        # stalled mid-write could overwrite a NEWER snapshot written by a
        # concurrent flush(force=True) (e.g. server_close) with its stale
        # blob after the dirty flag was already cleared.
        with self.io_lock:
            # Encode UNDER the store lock (/push and /edit mutate oplogs
            # under it; an encode racing a mutation could crash or persist
            # a torn snapshot); only the disk write happens outside it.
            blobs = []
            with self.lock:
                due = [d for d, t in self.dirty.items()
                       if force or now - t >= self.save_interval]
                for d in due:
                    del self.dirty[d]
                    ol = self.docs.get(d)
                    if ol is None:
                        continue
                    try:
                        blobs.append((d, encode_oplog(ol, ENCODE_FULL)))
                    except Exception:
                        # One unencodable doc (e.g. poisoned before input
                        # validation existed) must not abort the pass and
                        # silently drop OTHER docs' dirty flags; re-mark
                        # it so the failure stays visible to retries —
                        # but with exponential backoff (cap 10 min) and
                        # the full traceback only on the FIRST failure,
                        # so a persistently-broken doc degrades to one
                        # retry per backoff window instead of stderr spam
                        # on every pass.
                        if self._note_flush_failure(d, now, "encode") == 1:
                            import traceback
                            traceback.print_exc()
            # Disk writes get the SAME per-doc failure handling: an
            # ENOSPC/EIO on one doc's tmp file must not abort the loop
            # and silently drop the remaining docs' (already-cleared)
            # dirty flags — an idle doc's edits would otherwise never be
            # persisted again.
            for doc_id, blob in blobs:
                path = self._path(doc_id)
                tmp = path + ".tmp"
                try:
                    with open(tmp, "wb") as f:
                        f.write(blob)
                    os.replace(tmp, path)  # atomic
                    # persistence truly completed: only now is the
                    # consecutive-failure streak over (clearing on encode
                    # success would reset a write-failure backoff every
                    # pass and bring back the per-pass log spam)
                    with self.lock:
                        self.flush_failures.pop(doc_id, None)
                    if self.obs is not None:
                        self.obs.journey.stamp_doc(doc_id,
                                                   "wal_durable")
                except OSError:
                    with self.lock:
                        self._note_flush_failure(doc_id, now, "write")

    def _note_flush_failure(self, d: str, now: float, stage: str) -> int:
        """Record one flush failure for doc `d` (caller holds self.lock
        and is inside the `except` block): bump the consecutive-failure
        counter, re-mark the doc dirty with exponential backoff, and log
        on the first failure / each doubling. Returns the new count."""
        fails = self.flush_failures.get(d, 0) + 1
        self.flush_failures[d] = fails
        e = sys.exc_info()[1]
        if fails == 1:
            print(f"flush: {stage} failed for doc {d!r}: {e!r}",
                  file=sys.stderr)
        elif (fails & (fails - 1)) == 0:  # 2, 4, 8, ...
            # keep the current exception text in the trail: the failure
            # REASON can change between passes (content changes cut the
            # backoff) and the first log line may describe a stale cause
            print(f"flush: {stage} still failing for doc {d!r} "
                  f"({fails} consecutive failures, backing off; "
                  f"latest: {e!r})", file=sys.stderr)
        # exponent bounded: 2**fails would overflow float->int conversion
        # near fails=1025 and kill the flusher thread for the whole server
        backoff = min(max(self.save_interval, 1.0)
                      * (2 ** min(fails, 10)), 600.0)
        if self.dirty.get(d) is None:
            # the write path runs outside self.lock: a handler thread may
            # have mark_dirty'd the doc mid-write (new edit -> prompt
            # retry); that timestamp must win over the backoff re-mark
            self.dirty[d] = now + backoff - self.save_interval
        return fails


def _utf8_clean(s: str) -> bool:
    """JSON happily delivers lone surrogates ("\\ud800"); they pass str
    checks but blow up every later encode (utf-8 wire, utf-32 arenas),
    so one accepted op would poison persistence for the whole store."""
    try:
        s.encode("utf8")
        return True
    except UnicodeEncodeError:
        return False


def _patch_agent_names(data: bytes):
    """Agent names declared by a v1 patch/snapshot blob (CHUNK_AGENTNAMES
    inside CHUNK_FILEINFO), WITHOUT applying the patch — push validation
    must run before decode_into mutates the live oplog."""
    from ..encoding.decode import (Buf, CHUNK_AGENTNAMES, CHUNK_FILEINFO,
                                   MAGIC)
    if data[:8] != MAGIC:
        raise ValueError("bad magic")
    buf = Buf(data, 8)
    buf.next_usize()   # protocol version
    names = []
    while not buf.is_empty():
        ctype, chunk = buf.next_chunk()
        if ctype != CHUNK_FILEINFO:
            continue
        while not chunk.is_empty():
            ct2, c2 = chunk.next_chunk()
            if ct2 == CHUNK_AGENTNAMES:
                while not c2.is_empty():
                    names.append(c2.next_str())
        break
    return names


def _agent_name_ok(s) -> bool:
    """Agent names additionally must be BMP-only: agent ordering is a
    CONVERGENCE tie-break, Python/native compare code points while the
    browser engine's `<` compares UTF-16 units, and the two orders
    diverge exactly on astral characters. The engine's single source
    (tools/crdt_replay_src.py) documents this edge as its precondition;
    this is where it is enforced."""
    if not (isinstance(s, str) and s and _utf8_clean(s)):
        return False
    for ch in s:
        if ord(ch) > 0xFFFF:
            return False
    return True


def _crdt_next_seq(aa, agent: int) -> int:
    nxt = 0
    for (lv0, lv1, ag, seq0) in aa.global_runs:
        if ag == agent:
            nxt = max(nxt, seq0 + (lv1 - lv0))
    return nxt


def _crdt_apply_op(ol: OpLog, op: dict, cache: Optional[dict] = None) -> None:
    """Fold one browser-CRDT op (original position + explicit parents)
    into the oplog; idempotent on (agent, seq) replays. Validation runs
    BEFORE any mutation: a bad op must not leave a half-appended log.

    `cache` (shared across one batch) carries (frontier, doc-length) from
    the previous op: client batches are almost always a linear chain
    (each op's parents = the previous op's result), so only the first op
    pays a full checkout — without it a reconnect pushing hundreds of
    queued ops would run O(ops x history) Branch merges under
    store.lock, stalling every other endpoint."""
    from operator import index as _ix
    name = op["agent"]
    if not _agent_name_ok(name):
        raise ValueError("bad agent name")
    seq = _ix(op["seq"])
    aa = ol.cg.agent_assignment
    # Resolve WITHOUT creating: a rejected op must not leave the agent
    # name registered (rejected-only traffic would otherwise grow the
    # agent table without bound, and the junk names get persisted by the
    # next legitimate flush). The agent is created only at mutation time.
    agent = aa.try_get_agent(name)
    nxt = 0 if agent is None else _crdt_next_seq(aa, agent)
    if seq < nxt:
        return   # already known (client re-push after a dropped response)
    if seq > nxt:
        raise ValueError(f"seq gap: client sent {seq}, log expects {nxt}")
    frontier = list(ol.cg.remote_to_local_frontier(
        [(str(a), _ix(s)) for (a, s) in op.get("parents") or []]))
    # Clients track their frontier as a per-agent max-seq map, so pushed
    # parents may contain dominated heads; store the minimal frontier the
    # rest of the codebase assumes (reference: Frontier is always minimal,
    # src/frontier.rs:23).
    if len(frontier) > 1:
        frontier = list(ol.cg.graph.find_dominators(frontier))
    # Positions are only meaningful against the document AT THE OP'S
    # PARENTS: an out-of-range op accepted here is persisted and poisons
    # every future merge on every peer, so length-check before mutating.
    if cache is not None and cache.get("frontier") == tuple(frontier):
        blen = cache["blen"]
    else:
        blen = len(ol.checkout(frontier))
    if op.get("kind") == "ins":
        pos = _ix(op["pos"])
        content = op.get("content")
        if not (isinstance(content, str) and content
                and _utf8_clean(content)):
            raise ValueError("bad ins content")
        if not 0 <= pos <= blen:
            raise ValueError(f"ins pos {pos} out of range 0..{blen}")
        if agent is None:
            agent = ol.get_or_create_agent_id(name)
        lv = ol.add_insert_at(agent, frontier, pos, content)
        blen += len(content)
    elif op.get("kind") == "del":
        start = _ix(op["pos"])
        n = _ix(op["len"])
        if n < 1 or not 0 <= start or start + n > blen:
            raise ValueError(
                f"del range {start}+{n} out of range 0..{blen}")
        # content=None: deleted text is recoverable from history; a full
        # checkout per unit delete under store.lock would be O(history)
        # per character
        if agent is None:
            agent = ol.get_or_create_agent_id(name)
        lv = ol.add_delete_at(agent, frontier, start, start + n, None)
        blen -= n
    else:
        raise ValueError("bad crdt op kind")
    if cache is not None:
        cache["frontier"] = (lv,)
        cache["blen"] = blen


def _crdt_ops_since(ol: OpLog, have: dict) -> list:
    """Every op whose (agent, seq) is at or past the client's next-seq
    map, as per-RUN JSON rows with original positions + remote parents."""
    from ..text.op import INS
    aa = ol.cg.agent_assignment
    g = ol.cg.graph
    out = []
    for (lv0, lv1, agent, seq0) in aa.global_runs:
        name = aa.agent_names[agent]
        nxt = int(have.get(name, 0))
        want_from = lv0 + max(0, nxt - seq0)
        if want_from >= lv1:
            continue
        for piece in ol.ops.iter_range((want_from, lv1)):
            a2, s2 = aa.local_to_agent_version(piece.lv)
            parents = ol.cg.local_to_remote_frontier(
                g.parents_at(piece.lv))
            row = {"agent": aa.agent_names[a2], "seq": s2,
                   "parents": parents,
                   "kind": "ins" if piece.kind == INS else "del",
                   "pos": piece.start, "fwd": bool(piece.fwd)}
            if piece.kind == INS:
                row["content"] = ol.ops.get_run_content(piece)
            else:
                row["len"] = len(piece)
            out.append(row)
    out.sort(key=lambda r: (r["agent"], r["seq"]))
    return out


def doc_history_strip(ol: OpLog, n: int, tip: Optional[list] = None):
    """Up to `n` historical snapshots of `ol` up to the frozen frontier
    `tip`, oldest-first, as [{"lv", "text"}].

    With DT_SERVER_DEVICE=1 and a conflict zone present, the whole strip
    is materialized by ONE vmapped device call (tpu/plan_kernels.py
    texts_at_versions — the reference can only checkout one version per
    tracker rebuild, src/list/oplog.rs:32). The default path samples host
    checkouts instead: this process serves HTTP, and first-touch JAX
    backend init against a wedged accelerator tunnel would hang the
    handler (the bench isolates device work in watchdogged subprocesses;
    a server cannot)."""
    if len(ol) == 0:
        return []
    tip = list(ol.version) if tip is None else list(tip)
    from ..listmerge.plan2 import compile_plan2
    plan = compile_plan2(ol.cg.graph, [], tip)
    out = []
    n_entries = len(plan.entries)
    if n_entries and os.environ.get("DT_SERVER_DEVICE"):
        from ..native import native_available
        from ..tpu.plan_kernels import texts_at_versions
        if n == 1:   # strip budget fits only the merged-tip snapshot
            return [{"lv": int(max(t for t in tip)),
                     "text": ol.checkout(tip).snapshot()}]
        take = min(n - 1, n_entries)
        idxs = [round(i * (n_entries - 1) / max(take - 1, 1))
                for i in range(take)]
        idxs = sorted(set(idxs))
        source = "native" if native_available() and \
            not os.environ.get("DT_TPU_NO_NATIVE") else "python"
        texts = texts_at_versions(ol, idxs, merge_frontier=tip,
                                  source=source)
        for k, txt in zip(idxs, texts):
            out.append({"lv": int(plan.entries[k].span[1]) - 1,
                        "text": txt})
        # an entry's snapshot is its own causal cone; the strip's last
        # stop is the MERGED tip (all cones joined)
        out.append({"lv": int(max(t for t in tip)),
                    "text": ol.checkout(tip).snapshot()})
        return out
    # host path: sample versions along the LV axis (each checkout is a
    # fast native merge)
    top = max(tip) + 1
    take = min(n, top)
    lvs = sorted({round((i + 1) * top / take) - 1 for i in range(take)})
    for lv in lvs:
        f = ol.cg.graph.find_dominators([lv])
        out.append({"lv": int(lv), "text": ol.checkout(f).snapshot()})
    if out and out[-1]["lv"] == top - 1 and len(tip) > 1:
        out[-1] = {"lv": top - 1, "text": ol.checkout(tip).snapshot()}
    return out


def _parse_frontier_token(tok: str):
    """Parse an `X-DT-Min-Version` header: a JSON remote frontier
    ([[agent, seq], ...]). Raises ValueError/TypeError on any shape
    the read path couldn't evaluate safely."""
    v = json.loads(tok)
    if not isinstance(v, list):
        raise ValueError("token must be a list")
    out = []
    for h in v:
        if not (isinstance(h, (list, tuple)) and len(h) == 2
                and isinstance(h[0], str)):
            raise ValueError("bad frontier head")
        out.append([h[0], int(h[1])])
    return out


class SyncHandler(BaseHTTPRequestHandler):
    store: DocStore = None  # class attr, set by serve()

    def log_message(self, *a):  # quiet
        pass

    def _send(self, code: int, body: bytes, ctype: str = "application/json",
              extra: Optional[dict] = None):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _wire(self):
        """This node's WireChannel, or None when replication is off
        (single-server mode has no mesh transport to account)."""
        node = self.store.replica
        return node.wire if node is not None else None

    def _wire_reply_ok(self) -> bool:
        """May this response be a binary frame? Only when the REQUEST
        advertised `X-DT-Wire` (so the caller decodes frames) AND this
        node's framing is on — a node pinned to JSON behaves like an
        old build end to end, though it still accepts inbound frames."""
        w = self._wire()
        return (w is not None and w.enabled
                and self.headers.get(WIRE_HEADER) is not None)

    def _route(self):
        # query string stripped: GET doc endpoints take contract
        # params (?max_staleness=) that must not leak into the action
        parts = self.path.split("?", 1)[0].strip("/").split("/")
        if len(parts) >= 2 and parts[0] == "doc" and _DOC_ID_RE.match(parts[1]):
            return parts[1], (parts[2] if len(parts) > 2 else "")
        return None, None

    def _endpoint_label(self) -> str:
        """Bounded-cardinality endpoint label for the per-endpoint
        latency histograms: doc ids collapse to the sub-action, unknown
        paths collapse to "other" (a scanner must not mint histogram
        series)."""
        path = self.path.split("?", 1)[0]
        parts = path.strip("/").split("/")
        head = parts[0] if parts else ""
        if head == "":
            return "index"
        if head == "doc":
            sub = parts[2] if len(parts) > 2 else "text"
            return "doc_" + (sub if sub in (
                "summary", "state", "graph", "pull", "push", "edit",
                "changes", "ops", "history", "at", "text",
                "snapshot") else "other")
        if head in ("replicate", "debug") and len(parts) == 2:
            return f"{head}_{parts[1]}"
        if head == "debug" and len(parts) == 3 \
                and parts[1] in ("trace", "incidents"):
            # trace/incident ids must not mint series
            return f"debug_{parts[1]}"
        if head in ("metrics", "edit", "vis", "crdt"):
            return head
        return "other"

    def _trace_ctx(self):
        """SpanContext of this request's http span (None when the
        request wasn't sampled) — threaded into scheduler submits and
        proxy hops so one edit yields one trace."""
        span = getattr(self, "_span", None)
        if span is not None and span.sampled:
            return span.context()
        return None

    def do_GET(self):
        obs = self.store.obs
        t0 = time.monotonic()
        try:
            self._do_get()
        finally:
            if obs is not None:
                obs.hist.observe("http_request", time.monotonic() - t0,
                                 endpoint=self._endpoint_label(),
                                 method="GET")

    def _do_get(self):
        from .web_assets import (CRDT_HTML, EDITOR_HTML, INDEX_HTML,
                                 VIS_HTML)

        if self.path == "/" or self.path == "":
            return self._send(200, INDEX_HTML.encode("utf8"),
                              "text/html; charset=utf-8")
        path = self.path.split("?", 1)[0]
        # segment routing off the query-stripped path: /debug/events
        # and /metrics take query parameters (?since=, ?format=)
        parts = path.strip("/").split("/")
        if path == "/metrics":
            # serve/ scheduler counters (queue depths, flush sizes,
            # occupancy, evictions...) + replicate/ counters (leases,
            # handoffs, anti-entropy, per-peer backoff state) + obs
            # snapshots — JSON for bench/soak scrapers by default,
            # `?format=prom` renders the SAME document as Prometheus
            # text exposition. no-store either way: a cached scrape is
            # a wrong scrape.
            sched = self.store.scheduler
            node = self.store.replica
            obs = self.store.obs
            doc = {"serve": sched.metrics_json() if sched else None,
                   "replication": node.metrics_json() if node else None,
                   "read": self.store.reads.metrics.snapshot()
                   if self.store.reads is not None else None,
                   "qos": sched.qos.export()
                   if sched is not None and sched.qos is not None
                   else None}
            if obs is not None:
                doc["obs"] = obs.snapshot()
            qs = urllib.parse.parse_qs(
                self.path.partition("?")[2], keep_blank_values=True)
            no_store = {"Cache-Control": "no-store"}
            fmt = qs.get("format", [""])[0]
            if fmt in ("prom", "openmetrics"):
                from ..obs.prom import (CONTENT_TYPE,
                                        OPENMETRICS_CONTENT_TYPE,
                                        render_metrics)
                # content negotiation: `?format=openmetrics` forces
                # OpenMetrics 1.0; `?format=prom` honors an Accept
                # header asking for it (how real Prometheus scrapers
                # request exemplar-capable exposition)
                accept = self.headers.get("Accept", "") or ""
                om = (fmt == "openmetrics"
                      or "application/openmetrics-text" in accept)
                text = render_metrics(doc, openmetrics=om)
                ctype = OPENMETRICS_CONTENT_TYPE if om else CONTENT_TYPE
                return self._send(200, text.encode("utf8"), ctype,
                                  extra=no_store)
            return self._send(200, json.dumps(doc).encode("utf8"),
                              extra=no_store)
        if parts[:1] == ["debug"]:
            obs = self.store.obs
            no_store = {"Cache-Control": "no-store"}
            if obs is not None and len(parts) == 2 \
                    and parts[1] == "events":
                # `?since=<seq>` tails the ring incrementally (obs-watch
                # polls this instead of re-downloading every event)
                qs = urllib.parse.parse_qs(
                    self.path.partition("?")[2], keep_blank_values=True)
                rec = obs.recorder
                out = dict(rec.stats())
                try:
                    since = int(qs.get("since", ["0"])[0] or 0)
                except ValueError:
                    return self._send(400, b'{"error": "bad since"}')
                out["since"] = since
                out["events"] = (rec.dump_since(since) if since > 0
                                 else rec.dump())
                return self._send(200, json.dumps(out).encode("utf8"),
                                  extra=no_store)
            if obs is not None and len(parts) == 2 and parts[1] == "slo":
                # live SLO burn rates + alert states (pull-evaluated)
                return self._send(
                    200, json.dumps(obs.slo.snapshot()).encode("utf8"),
                    extra=no_store)
            if obs is not None and len(parts) == 2 and parts[1] == "hot":
                # top-K hot-doc/agent attribution (bounded sketch)
                return self._send(
                    200,
                    json.dumps(obs.attrib.snapshot()).encode("utf8"),
                    extra=no_store)
            if len(parts) == 2 and parts[1] == "qos":
                # adaptive-admission controller state: per-class
                # effective deadlines + counters, shed gate, specs
                sched = self.store.scheduler
                qctl = sched.qos if sched is not None else None
                out = qctl.export() if qctl is not None \
                    else {"enabled": False}
                return self._send(200, json.dumps(out).encode("utf8"),
                                  extra=no_store)
            if obs is not None and parts[1:2] == ["trace"] \
                    and len(parts) == 3:
                # local spans of one trace, plus this host's monotonic
                # "now" — `cli dt-trace` pairs it with its own
                # send/recv timestamps to estimate the clock offset
                # (obs/assemble.py) before merging peers' spans
                node = self.store.replica
                host = node.self_id if node is not None else "local"
                out = {"host": host, "trace": parts[2],
                       "now": round(time.monotonic(), 6),
                       "spans": obs.tracer.find(parts[2])}
                return self._send(200, json.dumps(out).encode("utf8"),
                                  extra=no_store)
            if obs is not None and len(parts) == 2 \
                    and parts[1] == "incidents":
                # incident-bundle index: counts by kind + newest-first
                # rows (cli dt-incidents / obs-watch poll this)
                node = self.store.replica
                host = node.self_id if node is not None else "local"
                out = {"host": host, **obs.incidents.index_json()}
                return self._send(200, json.dumps(out).encode("utf8"),
                                  extra=no_store)
            if obs is not None and parts[1:2] == ["incidents"] \
                    and len(parts) == 3:
                # one full evidence bundle by id (404s after eviction —
                # the persisted JSON under the data dir outlives the
                # in-memory ring)
                bundle = obs.incidents.get(parts[2])
                if bundle is None:
                    return self._send(404, b"{}")
                return self._send(
                    200, json.dumps(bundle, default=str).encode("utf8"),
                    extra=no_store)
            if obs is not None and len(parts) == 2 \
                    and parts[1] == "traces":
                # recent sampled trace index (newest first): the entry
                # point for picking a trace id to assemble
                node = self.store.replica
                host = node.self_id if node is not None else "local"
                out = {"host": host,
                       "now": round(time.monotonic(), 6),
                       "traces": obs.tracer.index()}
                return self._send(200, json.dumps(out).encode("utf8"),
                                  extra=no_store)
            return self._send(404, b"{}")
        if parts and parts[0] == "replicate":
            node = self.store.replica
            if node is None:
                return self._send(404, b"{}")
            if len(parts) == 2 and parts[1] == "ping":
                body = json.dumps(node.ping_json()).encode("utf8")
                # ping IS the gossip transport: its response bytes are
                # the gossip channel's whole volume
                node.wire.account("gossip", sent_bytes=len(body))
                return self._send(200, body)
            if len(parts) == 2 and parts[1] == "docs":
                # doc list + piggybacked lease claims + frontier
                # adverts (anti-entropy round preamble). Re-sent every
                # round, so once deltas stop flowing this listing IS
                # the channel's steady-state cost — frame it.
                listing = node.docs_json()
                body = json.dumps(listing).encode("utf8")
                if self._wire_reply_ok():
                    frame = encode_frame(FRAME_DOCS,
                                         encode_docs(listing),
                                         compress=True)
                    node.wire.account("antientropy",
                                      sent_bytes=len(frame),
                                      json_bytes=len(body), framed=True)
                    return self._send(200, frame, WIRE_CTYPE)
                node.wire.account("antientropy", sent_bytes=len(body))
                return self._send(200, body)
            return self._send(404, b"{}")
        if len(parts) == 2 and parts[0] in ("edit", "vis", "crdt"):
            if not _DOC_ID_RE.match(parts[1]):
                return self._send(404, b"{}")
            page = {"edit": EDITOR_HTML, "vis": VIS_HTML,
                    "crdt": CRDT_HTML}[parts[0]]
            return self._send(200, page.replace("__DOC__", parts[1])
                              .encode("utf8"), "text/html; charset=utf-8")

        doc_id, action = self._route()
        if doc_id is None:
            return self._send(404, b"{}")
        # every checkout-bearing GET is frontier-dependent state: an
        # intermediary cache serving it stale would silently violate
        # the read contract, so all four doc views are no-store
        no_store = {"Cache-Control": "no-store"}
        if action in ("", "state") and self.store.reads is not None:
            return self._read_with_contract(doc_id, action, no_store)
        if action == "snapshot":
            # routed BEFORE store.get: 404ing a doc that was never
            # materialized here must not mint an empty oplog for it
            return self._doc_snapshot(doc_id, no_store)
        ol = self.store.get(doc_id)
        if action == "":
            with self.store.lock:
                text = ol.checkout_tip().snapshot()
                frontier = ol.cg.local_to_remote_frontier(ol.version)
            return self._send(200, text.encode("utf8"),
                              "text/plain; charset=utf-8",
                              extra={**no_store,
                                     "X-DT-Frontier":
                                     json.dumps(frontier)})
        if action == "summary":
            with self.store.lock:
                summary = summarize_versions(ol.cg)
            body = json.dumps(summary).encode("utf8")
            w = self._wire()
            if self._wire_reply_ok():
                frame = encode_frame(FRAME_SUMMARY,
                                     encode_summary(summary),
                                     compress=True)
                w.account("antientropy", sent_bytes=len(frame),
                          json_bytes=len(body), framed=True)
                return self._send(200, frame, WIRE_CTYPE, extra=no_store)
            if w is not None:
                w.account("antientropy", sent_bytes=len(body))
            return self._send(200, body, extra=no_store)
        if action == "state":
            with self.store.lock:
                frontier = ol.cg.local_to_remote_frontier(ol.version)
                body = json.dumps({
                    "text": ol.checkout_tip().snapshot(),
                    "version": frontier})
            return self._send(200, body.encode("utf8"),
                              extra={**no_store,
                                     "X-DT-Frontier":
                                     json.dumps(frontier)})
        if action == "graph":
            with self.store.lock:
                g = ol.cg.graph
                aa = ol.cg.agent_assignment
                runs = []
                for i in range(len(g.starts)):
                    agent, _seq = aa.local_to_agent_version(g.starts[i])
                    runs.append({"start": g.starts[i], "end": g.ends[i],
                                 "parents": list(g.parents[i]),
                                 "agent": aa.get_agent_name(agent)})
            return self._send(200, json.dumps({"runs": runs}).encode("utf8"),
                              extra=no_store)
        return self._send(404, b"{}")

    def _doc_snapshot(self, doc_id: str, no_store: dict):
        """GET /doc/{id}/snapshot — compacted-snapshot frame for
        far-behind peers and cold remote hydration fills. The frame is
        cached per frontier in the node's WireChannel, so a thundering
        herd of cold followers costs one encode. 404 when replication
        or framing is off, or the doc isn't materialized here."""
        node = self.store.replica
        if node is None or not node.wire.enabled:
            return self._send(404, b"{}")
        with self.store.lock:
            ol = self.store.docs.get(doc_id)
            if ol is None:
                return self._send(404, b"{}")
            key = tuple(sorted(map(
                tuple, ol.cg.local_to_remote_frontier(ol.version))))
        hyd = getattr(self.store.scheduler, "hydrator", None)
        tstore = getattr(hyd, "store", None)
        frame = node.wire.cached_snapshot(
            doc_id, key,
            lambda: build_snapshot(ol, store=tstore, doc_id=doc_id,
                                   oplog_lock=self.store.lock))
        node.wire.account("hydrate", sent_bytes=len(frame),
                          framed=True, snapshot=True)
        return self._send(200, frame, WIRE_CTYPE, extra=no_store)

    def _read_with_contract(self, doc_id: str, action: str,
                            no_store: dict):
        """Follower-read path for GET /doc/{id} and /doc/{id}/state:
        parse `?max_staleness=` + `X-DT-Min-Version`, then delegate the
        local/wait/proxy/refuse decision to the attached ReadPath
        (read/path.py). `X-DT-Proxied` marks the owner side of a proxy
        hop — served locally, never re-proxied."""
        from ..read.path import MIN_VERSION_HEADER
        qs = urllib.parse.parse_qs(self.path.partition("?")[2],
                                   keep_blank_values=True)
        raw = qs.get("max_staleness", [None])[0]
        max_staleness = None
        if raw not in (None, ""):
            try:
                max_staleness = float(raw)
            except ValueError:
                return self._send(400, json.dumps(
                    {"error": "bad max_staleness"}).encode("utf8"))
            if max_staleness < 0 or max_staleness != max_staleness:
                return self._send(400, json.dumps(
                    {"error": "bad max_staleness"}).encode("utf8"))
        min_version = None
        tok = self.headers.get(MIN_VERSION_HEADER)
        if tok:
            try:
                min_version = _parse_frontier_token(tok)
            except (ValueError, TypeError):
                return self._send(400, json.dumps(
                    {"error": "bad min_version token"}).encode("utf8"))
        proxied = self.headers.get("X-DT-Proxied") is not None
        res = self.store.reads.read(
            doc_id, "text" if action == "" else "state",
            max_staleness=max_staleness, min_version=min_version,
            forced_local=proxied,
            trace=parse_header(self.headers.get(TRACE_HEADER)))
        if proxied and action == "state" and res.status == 200:
            # owner side of a follower's proxy hop: the mesh leg can be
            # framed (the follower re-inflates JSON for its client);
            # accounted here because this host sends the response bytes
            w = self._wire()
            if w is not None:
                framed = False
                send = res.body
                if self._wire_reply_ok():
                    try:
                        state = json.loads(res.body)
                        frame = encode_frame(
                            FRAME_STATE,
                            encode_state(state["text"], state["version"]),
                            compress=True)
                        if len(frame) < len(res.body):
                            send, framed = frame, True
                    except (ValueError, KeyError, TypeError):
                        pass  # non-JSON body: fall through unframed
                w.account("proxy", sent_bytes=len(send),
                          json_bytes=len(res.body) if framed else None,
                          framed=framed)
                if framed:
                    return self._send(200, send, WIRE_CTYPE,
                                      extra={**no_store, **res.headers})
        return self._send(res.status, res.body, res.ctype,
                          extra={**no_store, **res.headers})

    def do_POST(self):
        # Malformed JSON bodies / missing keys / non-numeric values on any
        # browser endpoint — and corrupt binary patches on /push
        # (ParseError) — are client errors, not handler-thread crashes.
        from ..encoding.decode import ParseError
        obs = self.store.obs
        t0 = time.monotonic()
        if obs is not None:
            # Root (or continued) span for this request: an X-DT-Trace
            # header from a proxying peer or traced client stitches this
            # hop into the caller's trace; otherwise head-sampling here
            # decides for every downstream span (admit, flush, proxy).
            self._span = obs.tracer.start(
                "http." + self._endpoint_label(),
                parent=parse_header(self.headers.get(TRACE_HEADER)),
                attrs={"path": self.path.split("?", 1)[0]})
        try:
            self._do_post()
        except (ValueError, KeyError, TypeError, AttributeError,
                ParseError) as e:
            try:
                self._send(400, json.dumps(
                    {"error": f"bad request: {e.__class__.__name__}"})
                    .encode("utf8"))
            except OSError:
                pass  # client already gone
        finally:
            if obs is not None:
                span = getattr(self, "_span", None)
                if span is not None:
                    span.end()
                obs.hist.observe("http_request", time.monotonic() - t0,
                                 endpoint=self._endpoint_label(),
                                 method="POST")

    def _do_post(self):
        parts = self.path.strip("/").split("/")
        if parts[:1] == ["replicate"]:
            node = self.store.replica
            if node is None or len(parts) != 2 or parts[1] not in (
                    "lease", "join", "leave"):
                return self._send(404, b"{}")
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            handler = {"lease": node.handle_lease_message,
                       "join": node.handle_join,
                       "leave": node.handle_leave}[parts[1]]
            return self._send(200, json.dumps(handler(req))
                              .encode("utf8"))
        doc_id, action = self._route()
        if doc_id is None:
            return self._send(404, b"{}")
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        obs = self.store.obs
        if obs is not None and n and action in ("push", "edit", "ops"):
            # per-doc request-byte attribution (the agent dimension is
            # noted in the JSON handlers once the body names one)
            obs.attrib.note("bytes", doc=doc_id, n=float(n))
        # QoS ingress classification: explicit X-DT-QoS header wins,
        # anti-entropy pushes (X-DT-Replication) are catchup, everything
        # else interactive. Classified BEFORE the ownership proxy so a
        # forwarded mutation keeps its class at the owner. Mutations
        # ONLY: reads (e.g. the `changes` long-poll) never hit the shed
        # gate, so a hot tenant's polling can't drain — or be throttled
        # by — its own write token bucket.
        qos_cls = None
        if action in ("push", "edit", "ops"):
            from ..qos.classes import classify_headers, tenant_of
            qos_cls = classify_headers(self.headers)
        node = self.store.replica
        if node is not None and action in ("push", "edit", "ops"):
            # Fencing check first: a proxied mutation carries the lease
            # epoch its proxier routed by (X-DT-Lease-Epoch). If our
            # per-doc epoch floor has passed it, the routing was based
            # on a superseded lease — refuse with 409 rather than merge
            # under stale ownership (the proxier falls back to a local
            # accept and anti-entropy reconciles).
            claimed = self.headers.get("X-DT-Lease-Epoch")
            if claimed is not None:
                try:
                    claimed_epoch = int(claimed)
                except ValueError:
                    return self._send(400, b'{"error": "bad epoch"}')
                if not node.check_write_fence(doc_id, claimed_epoch):
                    return self._send(409, json.dumps(
                        {"error": "fenced",
                         "max_epoch": node.leases.max_epoch_of(doc_id)}
                        ).encode("utf8"))
            # Mutations belong on the doc's lease holder: proxy them
            # there so device merges run on exactly one host. A request
            # that already hopped once is never re-proxied (two hosts
            # with a split health view would otherwise bounce it
            # forever) and an unreachable owner degrades to a local
            # accept — the edit is durable here, the merge gate keeps
            # device work off this host, anti-entropy reconciles. A
            # writer-group member in good standing (group_accepts)
            # accepts locally too — splitting the hot doc's write path
            # across the group is the feature's whole point.
            target = node.route_mutation(doc_id)
            if target != node.self_id \
                    and not node.group_accepts(doc_id) \
                    and self.headers.get("X-DT-Replication") is None:
                # X-DT-Replication = host-targeted anti-entropy patch:
                # the sender chose THIS host deliberately (usually it
                # IS the owner pushing down to a follower), so routing
                # it back through the ownership proxy would return it
                # to the sender as a 200 no-op. Apply locally instead.
                if self.headers.get("X-DT-Proxied") is not None:
                    node.metrics.bump("proxy", "loops_refused")
                else:
                    relay = node.proxy(target, self.path, body,
                                       doc_id=doc_id,
                                       trace=self._trace_ctx(),
                                       qos=qos_cls)
                    if relay is not None:
                        status, resp = relay
                        return self._send(status, resp)
        if qos_cls is not None:
            # Shed gate — consulted BEFORE the mutation touches the
            # oplog, so a shed is a real load shield (nothing becomes
            # durable that a flush must later pay for). The controller
            # 429s sheddable classes when the mesh burns and any class
            # when its tenant's token bucket is dry; interactive under
            # a healthy mesh always passes.
            sched = self.store.scheduler
            qctl = sched.qos if sched is not None else None
            if qctl is not None:
                admitted, retry_after, reason = qctl.admit(
                    qos_cls, tenant=tenant_of(doc_id))
                if not admitted:
                    return self._send(
                        429,
                        json.dumps({"error": "shed", "qos": qos_cls,
                                    "reason": reason,
                                    "retry_after": round(retry_after, 3)}
                                   ).encode("utf8"),
                        extra={"Retry-After":
                               f"{max(retry_after, 0.0):.3f}",
                               "Cache-Control": "no-store"})
        ol = self.store.get(doc_id)
        if action == "pull":
            if is_frame(body):
                ftype, payload = decode_frame(body)
                if ftype != FRAME_SUMMARY:
                    raise WireError("pull body: expected SUMMARY frame")
                summary = decode_summary(payload)
            else:
                summary = json.loads(body or b"{}")
            with self.store.lock:
                common, _rem = intersect_with_summary(ol.cg, summary)
                patch = encode_oplog(ol, ENCODE_PATCH, from_version=common)
            w = self._wire()
            if self._wire_reply_ok():
                frame = encode_frame(FRAME_PATCH, patch, compress=True)
                if len(frame) < len(patch):
                    w.account("antientropy", sent_bytes=len(frame),
                              json_bytes=len(patch), framed=True)
                    return self._send(200, frame, WIRE_CTYPE)
            if w is not None:
                w.account("antientropy", sent_bytes=len(patch))
            return self._send(200, patch, "application/octet-stream")
        if action == "push":
            # wire frames unwrap FIRST: agent-name validation below must
            # see the raw DMNDTYPS blob(s), not the frame envelope. A
            # PATCH frame carries one patch; a SNAPSHOT frame carries a
            # record list (compacted far-behind catch-up) replayed in
            # order under the same lock.
            blobs = [body]
            if is_frame(body):
                ftype, payload = decode_frame(body)
                if ftype == FRAME_PATCH:
                    blobs = [payload]
                elif ftype == FRAME_SNAPSHOT:
                    blobs = decode_records(payload)
                else:
                    raise WireError(
                        "push body: expected PATCH or SNAPSHOT frame")
            # the binary path must enforce the same agent-name rules as
            # the JSON paths — a patch can register brand-new agents, and
            # an astral name would poison browser-vs-server convergence
            # for the whole doc (see _agent_name_ok)
            try:
                bad = [nm for blob in blobs
                       for nm in _patch_agent_names(blob)
                       if not _agent_name_ok(nm)]
            except Exception:
                return self._send(400, b'{"error": "bad patch"}')
            if bad:
                return self._send(400, b'{"error": "bad agent name"}')
            with self.store.lock:
                pre = list(ol.version)
                pre_len = len(ol)
                for blob in blobs:
                    decode_into(ol, blob)
                n_new = len(ol) - pre_len
                # Does folding the pushed ops into the pre-push document
                # actually collide (concurrent inserts at one gap)?
                # Surfaced so clients can flag ambiguous merges
                # (reference: has_conflicts_when_merging, merge.rs:51).
                # Cheap plan gate first: a push whose ops fast-forward
                # from `pre` (no conflict zone) can't collide — skip the
                # O(history) native transform for the common linear case.
                try:
                    from ..listmerge.plan2 import compile_plan2
                    plan = compile_plan2(ol.cg.graph, pre,
                                         list(ol.version))
                    collisions = 0 if not plan.entries else \
                        ol.count_conflicts_when_merging(pre)
                except Exception:
                    collisions = None
            self.store.mark_dirty(doc_id)
            self.store.notify(doc_id)
            if self.store.reads is not None:
                self.store.reads.on_local_mutation(doc_id)
            if n_new:
                tctx = self._trace_ctx()
                if obs is not None and tctx is not None \
                        and self.headers.get("X-DT-Replication") is None:
                    # journey opens at ingress (before submit_merge:
                    # begin is first-wins, the handler owns identity);
                    # binary patches carry agent names but no single
                    # (agent, seq), so identity is the first new agent.
                    # Anti-entropy patches are excluded: those edits'
                    # journeys live on their owner, not here.
                    agents = _patch_agent_names(blobs[0])
                    obs.journey.begin(agents[0] if agents else None,
                                      None, doc=doc_id,
                                      trace=tctx.trace_id)
                self.store.submit_merge(doc_id, n_new, trace=tctx,
                                        qos=qos_cls)
            return self._send(200, json.dumps(
                {"ok": True, "collisions": collisions}).encode("utf8"))
        if action == "edit":
            if is_frame(body):
                ftype, payload = decode_frame(body)
                if ftype != FRAME_OPS:
                    raise WireError("edit body: expected OPS frame")
                req = decode_ops(payload)
            else:
                req = json.loads(body)
            # Normalize each op ONCE (ints coerced exactly once, via
            # operator.index so floats like 3.7 are rejected, not
            # truncated) and use the normalized list for BOTH validation
            # and application — a value that passes validation can then
            # never reach the oplog in a different form.
            from operator import index as _ix
            ops = []
            for op in req["ops"]:
                if op.get("kind") == "ins":
                    ops.append(("ins", _ix(op["pos"]), op.get("text")))
                elif op.get("kind") == "del":
                    ops.append(("del", _ix(op["start"]), _ix(op["end"])))
                else:
                    return self._send(400, b'{"error": "bad op"}')
            if not _agent_name_ok(req.get("agent")):
                return self._send(400, b'{"error": "bad agent"}')
            if obs is not None:
                obs.attrib.note("ops", agent=req["agent"], n=len(ops))
                obs.attrib.note("bytes", agent=req["agent"], n=float(n))
            with self.store.lock:
                frontier = list(ol.cg.remote_to_local_frontier(
                    req.get("version") or []))
                # Validate the WHOLE batch against the doc length at the
                # client's version before touching the oplog: a rejected op
                # must not leave earlier batch ops half-applied.
                blen = len(ol.checkout(frontier))
                for op in ops:
                    if op[0] == "ins":
                        _k, pos, text = op
                        if not (isinstance(text, str) and text
                                and _utf8_clean(text)
                                and 0 <= pos <= blen):
                            return self._send(400, b'{"error": "bad op"}')
                        blen += len(text)
                    else:
                        _k, start, end = op
                        if not 0 <= start < end <= blen:
                            return self._send(400, b'{"error": "bad op"}')
                        blen -= end - start
                agent = ol.get_or_create_agent_id(req["agent"])
                for op in ops:
                    if op[0] == "ins":
                        lv = ol.add_insert_at(agent, frontier, op[1], op[2])
                    else:
                        lv = ol.add_delete_at(agent, frontier, op[1],
                                              op[2], None)
                    frontier = [lv]
                out = ol.cg.local_to_remote_frontier(frontier)
            self.store.mark_dirty(doc_id)
            self.store.notify(doc_id)
            if self.store.reads is not None:
                self.store.reads.on_local_mutation(doc_id)
            tctx = self._trace_ctx()
            if obs is not None and tctx is not None:
                # journey identity = the edit's (agent, last seq): the
                # post-apply remote frontier carries the agent's head
                seq = next((s for a, s in out if a == req["agent"]),
                           None)
                obs.journey.begin(req["agent"], seq, doc=doc_id,
                                  trace=tctx.trace_id)
            self.store.submit_merge(doc_id, len(ops), trace=tctx,
                                    qos=qos_cls)
            return self._send(200, json.dumps({"version": out})
                              .encode("utf8"))
        if action == "changes":
            from ..text import ot
            req = json.loads(body or b"{}")
            try:
                wait = min(max(float(req.get("wait") or 0), 0.0), 60.0)
            except (TypeError, ValueError):
                return self._send(400, b'{"error": "bad wait"}')
            deadline = time.monotonic() + wait
            c = self.store.cond(doc_id)
            # The condition is held around BOTH the emptiness check and the
            # wait (notify_all also runs under it), so a notify can never
            # land in between and be lost.
            with c:
                while True:
                    with self.store.lock:
                        frontier = list(ol.cg.remote_to_local_frontier(
                            req.get("version") or []))
                        trav = ot.xf_stream_to_traversal(
                            ol.iter_xf_operations_from(frontier, ol.version))
                        out = {"op": trav,
                               "version": ol.cg.local_to_remote_frontier(
                                   ol.cg.graph.version_union(frontier,
                                                             ol.version))}
                    remaining = deadline - time.monotonic()
                    if trav or remaining <= 0:
                        return self._send(200,
                                          json.dumps(out).encode("utf8"))
                    c.wait(timeout=min(remaining, 5.0))
        if action == "ops":
            # In-browser CRDT peer protocol (reference: the wiki app's
            # WASM client runs the full CRDT locally,
            # wiki/client/dt_doc.ts:40-171; here the browser runs a JS
            # engine — web_assets.CRDT_HTML — and exchanges ORIGINAL
            # positional ops with explicit parent versions, never
            # server-transformed positions):
            #   body {"have": {agent_name: next_seq...},
            #         "push": [{agent, seq, parents: [[a, s]...], kind,
            #                   pos, content|len}...]}
            #   -> {"ops": [...missing ops in the same shape...],
            #       "version": remote frontier}
            req = json.loads(body or b"{}")
            applied = 0
            try:
                with self.store.lock:
                    cache = {}   # (frontier, blen) carried across the batch
                    for op in req.get("push") or []:
                        try:
                            _crdt_apply_op(ol, op, cache)
                        except AssertionError as e:
                            # engine invariant tripped mid-apply (e.g. a doc
                            # poisoned before op validation existed): a
                            # client error, not a handler-thread crash loop
                            raise ValueError(
                                f"engine invariant: {e}") from e
                        applied += 1
                    out_ops = _crdt_ops_since(ol, req.get("have") or {})
                    ver = ol.cg.local_to_remote_frontier(ol.version)
            finally:
                if applied:
                    # ops before a mid-batch failure ARE in the log;
                    # flusher + long-pollers must see them either way
                    # (both helpers take store.lock themselves)
                    self.store.mark_dirty(doc_id)
                    self.store.notify(doc_id)
                    if self.store.reads is not None:
                        self.store.reads.on_local_mutation(doc_id)
                    tctx = self._trace_ctx()
                    if obs is not None and tctx is not None:
                        op0 = (req.get("push") or [{}])[0]
                        obs.journey.begin(op0.get("agent"),
                                          op0.get("seq"), doc=doc_id,
                                          trace=tctx.trace_id)
                    self.store.submit_merge(doc_id, applied,
                                            trace=tctx)
                    if obs is not None:
                        for op in req.get("push") or []:
                            a = op.get("agent")
                            if isinstance(a, str) and a:
                                obs.attrib.note("ops", agent=a)
            return self._send(200, json.dumps(
                {"ops": out_ops, "version": ver}).encode("utf8"))
        if action == "history":
            # Batched time travel: ONE vmapped device call materializes
            # every requested historical snapshot (tpu/plan_kernels.py
            # texts_at_versions — a visibility mask per version over one
            # shared linearization). The reference can only checkout one
            # version at a time, rebuilding a tracker per call
            # (src/list/oplog.rs:32). This powers the visualizer's
            # history strip as a product feature, not a test-only demo.
            from operator import index as _ix
            req = json.loads(body or b"{}")
            n = min(max(_ix(req.get("n", 16)), 1), 64)
            # Under the store lock like every other checkout endpoint:
            # checkouts share the per-oplog native context, and a
            # concurrent push rebuilding that context mid-call would be a
            # use-after-free. (Host strips are a few hundred ms worst
            # case; the device path is opt-in — see doc_history_strip.)
            with self.store.lock:
                snaps = doc_history_strip(ol, n, list(ol.version))
            return self._send(200, json.dumps({"snapshots": snaps})
                              .encode("utf8"))
        if action == "at":
            from operator import index as _ix
            req = json.loads(body)
            try:
                lv = _ix(req["lv"])
            except (TypeError, KeyError):
                return self._send(400, b'{"error": "bad lv"}')
            with self.store.lock:
                if not 0 <= lv < len(ol):
                    return self._send(400, b'{"error": "lv out of range"}')
                f = ol.cg.graph.find_dominators([lv])
                text = ol.checkout(f).snapshot()
            return self._send(200, json.dumps({"text": text})
                              .encode("utf8"))
        return self._send(404, b"{}")


class _Server(ThreadingHTTPServer):
    store: DocStore = None

    def server_close(self):  # final flush on clean shutdown
        if self.store is not None:
            if self.store.replica is not None:
                self.store.replica.stop()
            if self.store.scheduler is not None:
                self.store.scheduler.stop_pump(drain=True)
            self.store.stop_flusher()
            self.store.flush(force=True)
        super().server_close()


def serve(port: int = 8008, data_dir: Optional[str] = None,
          serve_shards: int = 0, peers: Optional[list] = None,
          replicate_opts: Optional[dict] = None,
          obs_opts: Optional[dict] = None,
          follower_reads: bool = False,
          read_opts: Optional[dict] = None,
          qos: bool = False,
          qos_opts: Optional[dict] = None) -> ThreadingHTTPServer:
    """`peers` is the static mesh (["host:port", ...], may include
    this server's own address — it is dropped from the table). With
    peers set, a replicate.ReplicaNode is attached and started: health
    probes, lease maintenance and anti-entropy run in the background,
    and mutations for docs owned elsewhere are proxied. Tests that
    bind port 0 call replicate.attach_replication themselves once the
    ephemeral port is known. `obs_opts` are Observability kwargs
    (sample_rate etc.); every server gets a bundle — the tracer head-
    samples (1% default) and the recorder only fires on rare events,
    so the default is cheap enough to leave on."""
    from ..obs import Observability
    store = DocStore(data_dir)
    oo = dict(obs_opts or {})
    if data_dir is not None:
        # incident bundles park next to the journals/snapshots they
        # explain; callers may still override with their own dir
        oo.setdefault("incident_dir", data_dir)
    store.obs = Observability(**oo)
    if serve_shards:
        # engine="host" on purpose: this process serves HTTP, and
        # first-touch JAX backend init against a wedged accelerator
        # tunnel would hang every handler (same rationale as
        # doc_history_strip's device gate). The scheduler still
        # exercises the full route/queue/flush/evict machinery; flip to
        # engine="device" only in a process that owns its chips.
        from ..serve.scheduler import MergeScheduler
        sched = MergeScheduler(serve_shards, resolve=store.get,
                               engine="host", sync_lock=store.lock)
        store.attach_scheduler(sched)
        sched.attach_obs(store.obs)
        if qos:
            # attach BEFORE start_pump so the controller thread starts
            # (and stops) with the scheduler's own lifecycle
            from ..qos import QosController
            sched.attach_qos(QosController(**(qos_opts or {})))
            # incident bundles freeze the controller state at capture
            store.obs.incidents.qos_provider = sched.qos.export
        sched.start_pump()
    if follower_reads:
        # staleness-bounded local GETs on non-owner replicas + the
        # shared checkout cache; harmless (always-owner) on a
        # single-node server
        from ..read import attach_follower_reads
        attach_follower_reads(store, **(read_opts or {}))
    handler = type("Handler", (SyncHandler,), {"store": store})
    httpd = _Server(("127.0.0.1", port), handler)
    httpd.store = store
    if peers is not None:
        from ..replicate import attach_replication
        opts = dict(replicate_opts or {})
        join_addr = opts.pop("join", None)
        self_id = f"127.0.0.1:{httpd.server_address[1]}"
        if data_dir is not None and "journal_prefix" not in opts:
            # lease epochs / promises / incarnation survive a crash
            opts["journal_prefix"] = os.path.join(data_dir, "_replica")
        node = attach_replication(httpd, self_id,
                                  [p for p in peers if p != self_id],
                                  **opts)
        node.start()
        if join_addr:
            node.join_mesh(join_addr)
    store.start_flusher()
    return httpd


class SyncClient:
    """Client-side replica (reference: wiki/client/dt_doc.ts:40-171).

    Transport errors on pull/push are retried `retries` times with the
    jittered exponential `Backoff` shared with the peer mesh
    (replicate/peers.py) — transient connection drops and HTTP 5xx are
    retried, 4xx application rejections raise immediately. Both
    operations are idempotent (summary-driven patch exchange), so a
    retry after a response lost mid-flight is harmless."""

    def __init__(self, base_url: str, doc_id: str, agent_name: str,
                 retries: int = 3, timeout: float = 10.0) -> None:
        self.base = base_url.rstrip("/")
        self.doc_id = doc_id
        self.retries = retries
        self.timeout = timeout
        self.oplog = OpLog()
        self.oplog.doc_id = doc_id
        self.agent = self.oplog.get_or_create_agent_id(agent_name)
        self.branch = self.oplog.checkout_tip()

    def _url(self, action: str) -> str:
        return f"{self.base}/doc/{self.doc_id}/{action}"

    def _fetch(self, action: str, data: Optional[bytes] = None) -> bytes:
        from ..replicate.peers import Backoff, call_with_retries
        req = urllib.request.Request(self._url(action), data=data)

        def once() -> bytes:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.read()

        return call_with_retries(
            once, retries=self.retries,
            backoff=Backoff(base_s=0.05, cap_s=1.0,
                            key=f"{self.doc_id}/{action}"))

    def pull(self) -> None:
        summary = json.dumps(summarize_versions(self.oplog.cg)).encode("utf8")
        patch = self._fetch("pull", data=summary)
        decode_into(self.oplog, patch)
        self.branch.merge(self.oplog, self.oplog.version)

    def push(self) -> None:
        server_summary = json.loads(self._fetch("summary"))
        common, _ = intersect_with_summary(self.oplog.cg, server_summary)
        patch = encode_oplog(self.oplog, ENCODE_PATCH, from_version=common)
        self._fetch("push", data=patch)

    def sync(self) -> None:
        self.push()
        self.pull()

    def insert(self, pos: int, text: str) -> None:
        self.branch.insert(self.oplog, self.agent, pos, text)

    def delete(self, start: int, end: int) -> None:
        self.branch.delete(self.oplog, self.agent, start, end)

    def text(self) -> str:
        return self.branch.snapshot()


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=8008)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--serve-shards", type=int, default=0,
                   help="enable the sharded merge scheduler with N "
                   "host-engine shards (0 = off); metrics at /metrics")
    p.add_argument("--peers", default=None,
                   help="comma-separated host:port list of the full "
                   "replication mesh (this server's own address is "
                   "dropped); enables doc-ownership leases, mutation "
                   "proxying and anti-entropy")
    p.add_argument("--lease-ttl", type=float, default=2.0,
                   help="doc-ownership lease TTL in seconds")
    p.add_argument("--join", default=None,
                   help="host:port of an existing mesh member to "
                   "announce ourselves to at startup (dynamic "
                   "membership; the mesh is learned from its reply)")
    p.add_argument("--obs-sample-rate", type=float, default=0.01,
                   help="trace head-sampling rate (0 disables tracing; "
                   "histograms and the flight recorder are always on)")
    p.add_argument("--follower-reads", action="store_true",
                   help="serve GET /doc/{id}[/state] from this replica "
                   "under the staleness contract (?max_staleness= + "
                   "X-DT-Min-Version) instead of always locally; "
                   "contract misses proxy to the doc's owner")
    p.add_argument("--qos", action="store_true",
                   help="attach the adaptive-admission QoS controller "
                   "(qos/): per-class effective flush deadlines, depth "
                   "budgets and mesh-aware 429 load shedding; state at "
                   "/debug/qos (requires --serve-shards)")
    p.add_argument("--no-incidents", dest="incidents",
                   action="store_false", default=True,
                   help="disable the incident engine's anomaly "
                   "detector (the overhead A/B control arm); "
                   "/debug/incidents still answers, empty")
    args = p.parse_args()
    peers = [s.strip() for s in args.peers.split(",") if s.strip()] \
        if args.peers else ([] if args.join else None)
    httpd = serve(args.port, args.data_dir,
                  serve_shards=args.serve_shards, peers=peers,
                  replicate_opts={"lease_ttl_s": args.lease_ttl,
                                  "join": args.join},
                  obs_opts={"sample_rate": args.obs_sample_rate,
                            "incidents": args.incidents},
                  follower_reads=args.follower_reads,
                  qos=args.qos)
    print(f"serving on http://127.0.0.1:{args.port}"
          + (f" (mesh: {','.join(peers)})" if peers else ""))
    httpd.serve_forever()


if __name__ == "__main__":
    main()
