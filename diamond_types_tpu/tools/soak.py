"""Long-running randomized soak harness — the CI fuzzers at campaign
scale (reference test strategy: SURVEY.md §4.2; the reference runs its
seeded fuzzers across threads with a failing-seed "parachute",
src/list_fuzzer_tools.rs fuzz_multithreaded — this is the rebuild's
equivalent, run for hours in the background rather than minutes in CI).

Each seed plays one scenario end to end:
  * 3-5 peers diverge with Unicode-heavy random edits (bigger docs and
    more rounds than the CI fuzzers in tests/test_fuzz.py);
  * random pair syncs alternate between the two real transports —
    whole-oplog merge (text/crdt.py merge_oplogs) and the wire
    protocol (version-summary handshake + binary patch,
    causalgraph/summary.py + encoding ENCODE_PATCH) — with pairwise
    byte-equality asserted after every sync;
  * full mesh sync at the end: every peer must converge byte-identical;
  * codec gauntlet on the final oplog: full-snapshot round-trip, a
    patch from a random mid version onto a fork, and a checkout at a
    random historical version re-checked against a fresh decode.

Failures log the seed (replay: `python -m diamond_types_tpu.tools.soak
--seed0 <seed> --count 1`) and the campaign keeps going.

Usage:
  python -m diamond_types_tpu.tools.soak --seed0 1000000 \
      --log /tmp/soak.jsonl            # run until killed
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time
import traceback

from ..causalgraph.summary import (intersect_with_summary,
                                   summarize_versions)

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
from ..encoding.decode import decode_into, load_oplog
from ..encoding.encode import ENCODE_FULL, ENCODE_PATCH, encode_oplog
from ..text.crdt import ListCRDT, merge_oplogs

# Unicode-heavy alphabet, same spread as tests/test_fuzz.py (ASCII +
# Latin-1 + Greek + arrows + astral-plane symbols).
ALPHABET = ("abcdefghijklmnop_ XYZ123*&^%$#@!~`:;'\"|\n"
            "©¥½ΎΔδϠ←↯↻⇈"
            "\U00010190\U00010194\U00010198\U0001019a")

PEER_NAMES = ("alice", "bob", "carol", "dave", "erin")


def _random_edit(rng: random.Random, oplog, agent, version, content):
    doc_len = len(content)
    if doc_len == 0 or rng.random() < (0.65 if doc_len < 400 else 0.45):
        pos = rng.randint(0, doc_len)
        n = rng.randint(1, 8)
        s = "".join(rng.choice(ALPHABET) for _ in range(n))
        lv = oplog.add_insert_at(agent, version, pos, s)
        content = content[:pos] + s + content[pos:]
    else:
        start = rng.randint(0, doc_len - 1)
        n = min(rng.randint(1, 10), doc_len - start)
        lv = oplog.add_delete_at(agent, version, start, start + n,
                                 content[start:start + n])
        content = content[:start] + content[start + n:]
    return [lv], content


def _sync_pair(rng: random.Random, a, b) -> None:
    """Bidirectional sync via a random transport; both peers end at the
    same tip and must agree byte for byte."""
    if rng.random() < 0.5:
        merge_oplogs(a.oplog, b.oplog)
        merge_oplogs(b.oplog, a.oplog)
    else:
        # wire protocol: summary handshake + binary patch, both ways
        common_ab, _ = intersect_with_summary(
            a.oplog.cg, summarize_versions(b.oplog.cg))
        decode_into(b.oplog,
                    encode_oplog(a.oplog, ENCODE_PATCH,
                                 from_version=common_ab))
        common_ba, _ = intersect_with_summary(
            b.oplog.cg, summarize_versions(a.oplog.cg))
        decode_into(a.oplog,
                    encode_oplog(b.oplog, ENCODE_PATCH,
                                 from_version=common_ba))
    sa = a.oplog.checkout_tip().snapshot()
    sb = b.oplog.checkout_tip().snapshot()
    assert sa == sb, "pairwise divergence after sync"


def run_seed(seed: int) -> dict:
    """One full scenario; returns stats. Raises on any invariant break."""
    rng = random.Random(seed)
    n_peers = rng.randint(3, 5)
    peers = []
    for name in PEER_NAMES[:n_peers]:
        d = ListCRDT()
        d.get_or_create_agent_id(name)
        peers.append(d)
    states = [([], "") for _ in peers]       # (version, shadow content)

    rounds = rng.randint(12, 24)
    for _ in range(rounds):
        for idx, d in enumerate(peers):
            v, c = states[idx]
            for _ in range(rng.randint(1, 4)):
                v, c = _random_edit(rng, d.oplog, 0, v, c)
            states[idx] = (v, c)
        i, j = rng.sample(range(n_peers), 2)
        _sync_pair(rng, peers[i], peers[j])
        # local shadows are stale after a sync; refresh from checkout
        for k in (i, j):
            b = peers[k].oplog.checkout_tip()
            states[k] = (list(peers[k].oplog.version), b.snapshot())

    # full mesh: everyone syncs with everyone
    for i in range(n_peers):
        for j in range(n_peers):
            if i != j:
                merge_oplogs(peers[i].oplog, peers[j].oplog)
    finals = [d.oplog.checkout_tip().snapshot() for d in peers]
    assert all(f == finals[0] for f in finals), "mesh divergence"

    # codec gauntlet on peer 0
    ol = peers[0].oplog
    n_ops = len(ol)
    snap = encode_oplog(ol, ENCODE_FULL)
    ol2 = load_oplog(snap)
    assert ol2.checkout_tip().snapshot() == finals[0], "snapshot round-trip"
    # patch from a random mid version onto a fork that was split there
    mid = [rng.randrange(n_ops)] if n_ops else []
    mid = ol.cg.graph.find_dominators(mid)
    if mid:
        # LVs are renumbered densely by the file format, so the same
        # version must be named agent-wise across the decode boundary
        mid2 = ol2.cg.remote_to_local_frontier(
            ol.cg.local_to_remote_frontier(mid))
        # historical checkout must agree between original and decode
        assert ol.checkout(mid).snapshot() == \
            ol2.checkout(mid2).snapshot(), "historical checkout mismatch"
        patch = encode_oplog(ol, ENCODE_PATCH, from_version=mid)
        fork = load_oplog(snap)
        decode_into(fork, patch)   # idempotent over known ops
        assert fork.checkout_tip().snapshot() == finals[0], "patch ingest"
    return {"peers": n_peers, "rounds": rounds, "ops": n_ops,
            "doc_len": len(finals[0])}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed0", type=int, default=1_000_000)
    p.add_argument("--count", type=int, default=0,
                   help="seeds to run (0 = until killed)")
    p.add_argument("--log", default=None,
                   help="JSONL progress/failure log (default stdout)")
    args = p.parse_args(argv)

    out = open(args.log, "a") if args.log else sys.stdout

    def emit(obj):
        obj["ts"] = round(time.time(), 1)
        out.write(json.dumps(obj, ensure_ascii=False) + "\n")
        out.flush()

    emit({"event": "soak_start", "seed0": args.seed0, "count": args.count})
    done = failures = 0
    t0 = time.time()
    ops_total = 0
    seed = args.seed0

    _bench_mod = []

    def _bench_active() -> bool:
        # official bench runs must not compete with the soak for CPU
        # (bench.py bench_is_active; imported lazily so the soak works
        # from an installed package without the repo-root driver too).
        # One-time import: this is polled every 5 s for hours, so the
        # sys.path edit and import scan must not repeat per call.
        if not _bench_mod:
            try:
                if _REPO_ROOT not in sys.path:
                    sys.path.insert(0, _REPO_ROOT)
                import bench as _b
                _bench_mod.append(_b)
            except Exception:
                _bench_mod.append(None)
        if _bench_mod[0] is None:
            return False
        try:
            return _bench_mod[0].bench_is_active()
        except Exception:
            return False

    while args.count == 0 or done < args.count:
        if _bench_active():
            emit({"event": "paused", "why": "bench.py run in flight"})
            while _bench_active():
                time.sleep(5)
            emit({"event": "resumed"})
        try:
            stats = run_seed(seed)
            ops_total += stats["ops"]
        except Exception:
            failures += 1
            emit({"event": "FAILURE", "seed": seed,
                  "traceback": traceback.format_exc()[-2000:]})
        done += 1
        seed += 1
        if done % 25 == 0:
            emit({"event": "progress", "seeds_done": done,
                  "failures": failures, "ops_total": ops_total,
                  "elapsed_s": round(time.time() - t0, 1)})
    emit({"event": "soak_end", "seeds_done": done, "failures": failures,
          "ops_total": ops_total})
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
