"""Declarative workload programs + the scenario harness.

The bench/soak drivers each hard-code one traffic shape; this package
makes the shape data. A `Scenario` (spec.py) composes seeded arrival
processes (arrivals.py: Poisson, bursty/flash-crowd, ramp),
doc-popularity laws (popularity.py: Zipf, hot-set rotation), a
read:write mix, session churn, bulk imports behind interactive
traffic, multi-tenant namespaces, and an optional bank-churn lane (the
tiered-residency scale run). The runner (runner.py) drives
serve+replicate+read together against the live SLO engine and emits
one versioned scorecard (obs/scorecard.py) per run, so regressions are
one `cli scorecard-diff` away.

Everything is deterministic from the scenario seed: schedules are
generated on a virtual clock before any traffic flows, so the same
spec + seed replays the same event sequence byte-identically.
"""

from __future__ import annotations

from .arrivals import Bursty, Poisson, Ramp, make_arrivals
from .popularity import HotSetRotation, Uniform, Zipf, make_popularity
from .runner import run_scenario
from .spec import SCENARIOS, Scenario, get_scenario, register

__all__ = [
    "Poisson", "Bursty", "Ramp", "make_arrivals",
    "Zipf", "HotSetRotation", "Uniform", "make_popularity",
    "Scenario", "SCENARIOS", "get_scenario", "register",
    "run_scenario",
]
