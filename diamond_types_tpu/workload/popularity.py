"""Doc-popularity laws: which doc does the next event touch?

A law maps (virtual time, seeded rng stream) -> doc index in
[0, n_docs). Like the arrival processes, laws are deterministic from
their constructor arguments — `draws()` returns the same sequence on
every call — so a scenario's full event schedule is replayable.

`Zipf` is the steady skew (rank-r doc drawn with weight 1/r^s);
`HotSetRotation` models trending topics: a small hot set absorbs most
traffic and rotates to a different seeded subset every
`rotate_every_s` of virtual time, which is what keeps warm caches and
hot-doc attribution honest.
"""

from __future__ import annotations

import bisect
import random
from typing import Dict, List


class PopularityLaw:
    kind = "base"

    def __init__(self, n_docs: int, seed: int = 0) -> None:
        self.n_docs = max(int(n_docs), 1)
        self.seed = seed

    def _rng(self) -> random.Random:
        return random.Random(f"{self.kind}:{self.seed}:{self.n_docs}")

    def draws(self, times: List[float]) -> List[int]:
        """Doc index per virtual arrival time (same length/order)."""
        raise NotImplementedError  # pragma: no cover - abstract

    def to_dict(self) -> Dict:
        raise NotImplementedError  # pragma: no cover - abstract


class Uniform(PopularityLaw):
    kind = "uniform"

    def draws(self, times: List[float]) -> List[int]:
        rng = self._rng()
        return [rng.randrange(self.n_docs) for _ in times]

    def to_dict(self) -> Dict:
        return {"kind": self.kind}


class Zipf(PopularityLaw):
    """Rank-r doc drawn with weight 1/r^s (s ~ 1.1 is the web's
    classic skew). Rank order IS doc-index order: doc 0 is the head."""

    kind = "zipf"

    def __init__(self, n_docs: int, s: float = 1.1,
                 seed: int = 0) -> None:
        super().__init__(n_docs, seed)
        self.s = float(s)
        acc, cdf = 0.0, []
        for r in range(1, self.n_docs + 1):
            acc += 1.0 / (r ** self.s)
            cdf.append(acc)
        self._cdf = [c / acc for c in cdf]

    def weight(self, rank: int) -> float:
        """Normalized probability of the rank-`rank` doc (0-based)."""
        lo = self._cdf[rank - 1] if rank else 0.0
        return self._cdf[rank] - lo

    def draws(self, times: List[float]) -> List[int]:
        rng = self._rng()
        return [bisect.bisect_left(self._cdf, rng.random())
                for _ in times]

    def to_dict(self) -> Dict:
        return {"kind": self.kind, "s": self.s}


class HotSetRotation(PopularityLaw):
    """`hot_weight` of traffic lands uniformly on a `hot_k`-doc hot
    set; the set is a seeded sample that rotates every
    `rotate_every_s` of virtual time. The cold remainder is uniform
    over all docs."""

    kind = "hotset"

    def __init__(self, n_docs: int, hot_k: int = 2,
                 hot_weight: float = 0.8,
                 rotate_every_s: float = 5.0, seed: int = 0) -> None:
        super().__init__(n_docs, seed)
        self.hot_k = max(min(int(hot_k), self.n_docs), 1)
        self.hot_weight = float(hot_weight)
        self.rotate_every_s = max(float(rotate_every_s), 1e-9)

    def hot_set(self, t: float) -> List[int]:
        epoch = int(t / self.rotate_every_s)
        rng = random.Random(f"{self.kind}:{self.seed}:{epoch}")
        return rng.sample(range(self.n_docs), self.hot_k)

    def draws(self, times: List[float]) -> List[int]:
        rng = self._rng()
        out = []
        for t in times:
            if rng.random() < self.hot_weight:
                out.append(rng.choice(self.hot_set(t)))
            else:
                out.append(rng.randrange(self.n_docs))
        return out

    def to_dict(self) -> Dict:
        return {"kind": self.kind, "hot_k": self.hot_k,
                "hot_weight": self.hot_weight,
                "rotate_every_s": self.rotate_every_s}


_KINDS = {"uniform": Uniform, "zipf": Zipf, "hotset": HotSetRotation}


def make_popularity(spec: Dict, n_docs: int,
                    seed: int = 0) -> PopularityLaw:
    spec = dict(spec)
    kind = spec.pop("kind")
    try:
        cls = _KINDS[kind]
    except KeyError:
        raise ValueError(f"unknown popularity kind: {kind!r}") from None
    return cls(n_docs, seed=spec.pop("seed", seed), **spec)
