"""Seeded arrival processes on a virtual clock.

Each process turns (seed, duration) into a sorted list of virtual
arrival timestamps BEFORE any traffic flows — `schedule()` is a pure
function of the constructor arguments, so the same spec replays the
same event sequence byte-identically (the determinism contract the
sampler tests pin). Non-homogeneous shapes (bursty, ramp) use Lewis &
Shedler thinning against the peak rate: candidate points arrive at
`rate_max` and survive with probability `rate(t)/rate_max`, which
keeps one rng stream per schedule and an exact target intensity.
"""

from __future__ import annotations

import random
from typing import Dict, List


class ArrivalProcess:
    """Base: subclasses define `rate(t)` (events/virtual-second) and
    `rate_max`; `schedule()` thins a homogeneous Poisson stream."""

    kind = "base"
    rate_max: float = 0.0

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed

    def rate(self, t: float) -> float:  # pragma: no cover - abstract
        raise NotImplementedError

    def schedule(self, duration_s: float) -> List[float]:
        """Sorted virtual arrival times in [0, duration_s). A fresh
        rng per call: two calls on one instance are identical."""
        rng = random.Random(f"{self.kind}:{self.seed}")
        peak = self.rate_max
        out: List[float] = []
        if peak <= 0.0 or duration_s <= 0.0:
            return out
        t = 0.0
        while True:
            t += rng.expovariate(peak)
            if t >= duration_s:
                return out
            if rng.random() * peak < self.rate(t):
                out.append(t)

    def to_dict(self) -> Dict:
        raise NotImplementedError  # pragma: no cover - abstract


class Poisson(ArrivalProcess):
    """Homogeneous Poisson: exponential inter-arrivals at a flat
    rate — the steady interactive-traffic floor."""

    kind = "poisson"

    def __init__(self, rate_per_s: float, seed: int = 0) -> None:
        super().__init__(seed)
        self.rate_per_s = float(rate_per_s)
        self.rate_max = self.rate_per_s

    def rate(self, t: float) -> float:
        return self.rate_per_s

    def to_dict(self) -> Dict:
        return {"kind": self.kind, "rate_per_s": self.rate_per_s}


class Bursty(ArrivalProcess):
    """Flash crowd: a Poisson floor at `base_per_s` with periodic
    windows (`every_s` apart, `burst_len_s` long) where the rate
    multiplies by `burst_x` — the hot-doc admission stressor."""

    kind = "bursty"

    def __init__(self, base_per_s: float, burst_x: float = 10.0,
                 every_s: float = 10.0, burst_len_s: float = 2.0,
                 seed: int = 0) -> None:
        super().__init__(seed)
        self.base_per_s = float(base_per_s)
        self.burst_x = float(burst_x)
        self.every_s = float(every_s)
        self.burst_len_s = float(burst_len_s)
        self.rate_max = self.base_per_s * max(self.burst_x, 1.0)

    def in_burst(self, t: float) -> bool:
        return (t % self.every_s) < self.burst_len_s

    def rate(self, t: float) -> float:
        return self.rate_max if self.in_burst(t) else self.base_per_s

    def to_dict(self) -> Dict:
        return {"kind": self.kind, "base_per_s": self.base_per_s,
                "burst_x": self.burst_x, "every_s": self.every_s,
                "burst_len_s": self.burst_len_s}


class Ramp(ArrivalProcess):
    """Linear ramp from `start_per_s` to `end_per_s` over `ramp_s`
    (then flat at `end_per_s`) — the scale-up / bulk-import shape."""

    kind = "ramp"

    def __init__(self, start_per_s: float, end_per_s: float,
                 ramp_s: float, seed: int = 0) -> None:
        super().__init__(seed)
        self.start_per_s = float(start_per_s)
        self.end_per_s = float(end_per_s)
        self.ramp_s = max(float(ramp_s), 1e-9)
        self.rate_max = max(self.start_per_s, self.end_per_s)

    def rate(self, t: float) -> float:
        if t >= self.ramp_s:
            return self.end_per_s
        frac = t / self.ramp_s
        return self.start_per_s + (self.end_per_s
                                   - self.start_per_s) * frac

    def to_dict(self) -> Dict:
        return {"kind": self.kind, "start_per_s": self.start_per_s,
                "end_per_s": self.end_per_s, "ramp_s": self.ramp_s}


_KINDS = {"poisson": Poisson, "bursty": Bursty, "ramp": Ramp}


def make_arrivals(spec: Dict, seed: int = 0) -> ArrivalProcess:
    """Build a process from its declarative spec dict (the `kind` key
    selects the class; the rest are constructor kwargs)."""
    spec = dict(spec)
    kind = spec.pop("kind")
    try:
        cls = _KINDS[kind]
    except KeyError:
        raise ValueError(f"unknown arrival kind: {kind!r}") from None
    return cls(seed=spec.pop("seed", seed), **spec)
