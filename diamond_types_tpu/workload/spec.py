"""The declarative `Scenario` spec + the registry the CLI lists.

A scenario is pure data (dataclass of plain dicts/numbers — JSON
round-trippable, stamped verbatim into the scorecard) describing:

  arrivals     interactive-write arrival process (arrivals.py spec)
  popularity   which doc each write touches (popularity.py spec)
  reads_per_write   the read:write mix (realistic default ~100:1;
                    smoke overrides it down to stay seconds-long)
  tenants / docs_per_tenant   multi-tenant namespaces — doc ids are
                    "t{tenant}-doc{i:03d}" (the id grammar forbids /)
  sessions_per_tenant / session_churn_every_s   editing sessions per
                    tenant; churn retires agent names on a virtual
                    cadence and mints fresh ones
  bulk         optional bulk-import lane (its own arrival spec +
                    payload size) running BEHIND interactive traffic
  bank         optional bank-churn lane: docs churning through an
                    undersized warm tier (TieredStore + Hydrator) with
                    device-tier spill accounting — the tiered-
                    residency scale run rides this
  chaos        optional fault tape (replicate/faults.py): one
                    asymmetric partition plus one crash-restart at
                    fixed virtual times; arms persistent journals so
                    the crashed server reboots on its own state

Virtual time: `duration_s` of traffic is scheduled up front on the
scenario's injectable clock and executed in `tick_s` steps; nothing
sleeps to simulate load, so wall time is bounded by real work only.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional


@dataclass
class Scenario:
    name: str
    description: str = ""
    seed: int = 0
    servers: int = 2
    serve_shards: int = 1
    tenants: int = 1
    docs_per_tenant: int = 4
    duration_s: float = 4.0          # virtual seconds of traffic
    tick_s: float = 0.5              # control-plane step cadence
    arrivals: Dict = field(
        default_factory=lambda: {"kind": "poisson", "rate_per_s": 20.0})
    popularity: Dict = field(
        default_factory=lambda: {"kind": "zipf", "s": 1.1})
    reads_per_write: float = 100.0
    sessions_per_tenant: int = 2
    session_churn_every_s: float = 0.0   # 0 = sessions never churn
    bulk: Optional[Dict] = None
    bank: Optional[Dict] = None
    chaos: Optional[Dict] = None
    reconcile_rounds: int = 12
    slow: bool = False               # excluded from tier-1 by marker

    def doc_ids(self) -> List[str]:
        return [f"t{t}-doc{i:03d}" for t in range(self.tenants)
                for i in range(self.docs_per_tenant)]

    def to_dict(self) -> Dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "Scenario":
        return cls(**d)


SCENARIOS: Dict[str, Scenario] = {}


def register(sc: Scenario) -> Scenario:
    SCENARIOS[sc.name] = sc
    return sc


def get_scenario(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        known = ", ".join(sorted(SCENARIOS))
        raise ValueError(
            f"unknown scenario {name!r} (known: {known})") from None


# ---- registry ------------------------------------------------------------

# Tier-1 smoke: every harness feature exercised (two tenants, session
# churn, a bulk lane, a bank-churn lane small enough to finish in
# seconds) with the read mix cut far below the realistic 100:1 so the
# gate stays fast; the scorecard must still come out complete.
register(Scenario(
    name="smoke",
    description="tier-1 gate: small, seconds-long, deterministic; "
                "complete scorecard with every column populated",
    seed=7, servers=2, serve_shards=1, tenants=2, docs_per_tenant=3,
    duration_s=3.0, tick_s=0.5,
    arrivals={"kind": "poisson", "rate_per_s": 14.0},
    popularity={"kind": "zipf", "s": 1.1},
    reads_per_write=3.0,
    sessions_per_tenant=2, session_churn_every_s=1.0,
    bulk={"arrivals": {"kind": "ramp", "start_per_s": 0.0,
                       "end_per_s": 4.0, "ramp_s": 3.0},
          "bytes_per_op": 256},
    bank={"docs": 48, "warm_slots": 8, "rounds": 2,
          "edits_per_round": 32},
))

# Mesh-transport stressor: a 3-server mesh with aggressive session
# churn and a modest doc set, so anti-entropy re-walks the same docs
# round after round and most edits land on non-owners (proxied). This
# is the wire tier's before/after scenario — run once with
# DT_WIRE_DISABLED=1 (JSON protocol, no frontier short-circuit) and
# once framed, then scorecard-diff the wire.* bytes_per_op columns.
register(Scenario(
    name="churn",
    description="session-churn mesh traffic: the wire-tier transport "
                "baseline (antientropy + proxy bytes_per_op)",
    seed=17, servers=3, serve_shards=1, tenants=2, docs_per_tenant=8,
    duration_s=8.0, tick_s=0.25,
    arrivals={"kind": "poisson", "rate_per_s": 10.0},
    popularity={"kind": "zipf", "s": 1.3},
    reads_per_write=6.0,
    sessions_per_tenant=3, session_churn_every_s=1.5,
))

register(Scenario(
    name="flash-crowd",
    description="bursty arrivals on a rotating hot set: the admission/"
                "QoS stressor (ROADMAP item 1's scenario matrix)",
    seed=11, servers=3, serve_shards=2, tenants=2, docs_per_tenant=8,
    duration_s=20.0, tick_s=0.5,
    arrivals={"kind": "bursty", "base_per_s": 12.0, "burst_x": 8.0,
              "every_s": 6.0, "burst_len_s": 1.5},
    popularity={"kind": "hotset", "hot_k": 2, "hot_weight": 0.85,
                "rotate_every_s": 5.0},
    reads_per_write=20.0,
    sessions_per_tenant=3, session_churn_every_s=4.0,
    slow=True,
))

register(Scenario(
    name="ramp-bulk",
    description="bulk import ramping up behind steady interactive "
                "traffic at the realistic ~100:1 read mix",
    seed=13, servers=2, serve_shards=2, tenants=4, docs_per_tenant=6,
    duration_s=15.0, tick_s=0.5,
    arrivals={"kind": "poisson", "rate_per_s": 8.0},
    popularity={"kind": "zipf", "s": 1.2},
    reads_per_write=100.0,
    sessions_per_tenant=2, session_churn_every_s=5.0,
    bulk={"arrivals": {"kind": "ramp", "start_per_s": 0.0,
                       "end_per_s": 30.0, "ramp_s": 10.0},
          "bytes_per_op": 2048},
    slow=True,
))

# The churn tape under injected faults: one asymmetric mid-run
# partition (server 1 cannot reach server 0, the reverse path stays
# up) and a crash-restart of server 2 on persistent journals.
# Client-visible errors and SLO burn are EXPECTED while the mesh
# degrades — the gate is the safety property: every server
# byte-identical after the heal and reboot.
register(Scenario(
    name="chaos-churn",
    description="churn traffic under faults: one asymmetric partition "
                "+ one crash-restart; availability degrades honestly, "
                "the gate is post-heal byte-identical convergence",
    seed=17, servers=3, serve_shards=1, tenants=2, docs_per_tenant=8,
    duration_s=8.0, tick_s=0.25,
    arrivals={"kind": "poisson", "rate_per_s": 10.0},
    popularity={"kind": "zipf", "s": 1.3},
    reads_per_write=6.0,
    sessions_per_tenant=3, session_churn_every_s=1.5,
    chaos={"partition": {"a": 1, "b": 0, "at_s": 2.0, "heal_s": 4.0,
                         "oneway": True},
           "crash": {"server": 2, "at_s": 4.5, "restart_s": 6.0}},
    reconcile_rounds=24,
    slow=True,
))

# The tiered-residency scale run (PR 8 residual): 1M docs churning
# through a 10k-slot bank, gated on spill accounting + cold-start p99.
# Docs materialize on first touch (TieredStore.load treats a missing
# home as a fresh doc), so the run's cost is the churn, not a seeding
# pass over the full population.
register(Scenario(
    name="bank-churn-1m",
    description="1M docs through a 10k-slot bank with device-tier "
                "spill accounting (the honest tiered-residency scale "
                "run; hours, not seconds)",
    seed=8, servers=1, serve_shards=2, tenants=1, docs_per_tenant=4,
    duration_s=30.0, tick_s=1.0,
    arrivals={"kind": "poisson", "rate_per_s": 4.0},
    popularity={"kind": "zipf", "s": 1.1},
    reads_per_write=10.0,
    bank={"docs": 1_000_000, "warm_slots": 10_000, "rounds": 50,
          "edits_per_round": 20_000},
    slow=True,
))
