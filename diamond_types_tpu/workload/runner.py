"""Scenario runner: drive serve+replicate+read against the SLO engine.

Execution model (deterministic from the scenario seed):

  1. **Schedule** — every interactive write, read, bulk-import op and
     session-churn event is generated up front on the virtual clock
     (arrivals.py / popularity.py), then sorted into `tick_s` buckets.
  2. **Boot** — N in-process sync servers on ephemeral ports (the
     replicate-soak boot pattern: follower reads on, sample_rate=1.0
     so journeys and convergence lag populate), wired into one mesh
     whose control plane is stepped inline once per tick — probes,
     lease maintenance, anti-entropy — never free-running threads.
  3. **Drive** — each tick executes its bucket over real HTTP (writes
     POST /doc/{id}/edit, reads GET /doc/{id} round-robin across the
     mesh so followers serve them), steps the control plane, evaluates
     every node's SLO engine and integrates burn-minutes (a tick in a
     non-ok state charges tick_s/60 to that objective, summed across
     nodes), and publishes the live snapshot obs-watch renders.
  4. **Bank lane** — scenarios with a `bank` section then churn docs
     through an undersized Hydrator warm tier wired to the primary
     server's ServeMetrics, so device-tier spills land in the same
     hydration block /metrics and the scorecard read.
  5. **Reconcile + scorecard** — anti-entropy rounds until every
     server holds byte-identical text, then the run is snapshotted
     into a versioned scorecard (obs/scorecard.py).

Wall time is bounded by real work: nothing sleeps to simulate load.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from ..analysis.witness import make_lock
from ..obs.hist import Histogram
from ..obs.incident import INCIDENT_KINDS
from ..obs.scorecard import build_scorecard, publish_scenario
from .arrivals import make_arrivals
from .popularity import Zipf, make_popularity
from .spec import Scenario

# detector tuning for scenario runs: windows sized to wall seconds of
# tick work (not virtual time); min_rate high enough that boot-burst
# series (lease acquires, quorum rounds — steady for a few startup
# polls, then legitimately quiet forever) never warm into the stall
# watch; stall_after_s longer than the flash-crowd tape's 4.5 s
# inter-burst gap so bursty-but-healthy traffic never alarms; and a
# cooldown short enough that a partition and a crash in one tape each
# get their own bundle. Tuned empirically: flash-crowd must produce
# ZERO bundles, chaos-churn at least one (the p99 step the partition
# puts on read staleness).
RUNNER_INCIDENT_OPTS = dict(cooldown_s=30.0, rate_window_s=10.0,
                            stall_after_s=5.0, warmup_polls=4,
                            min_rate=1.0, spike_factor=8.0,
                            p99_factor=6.0, min_p99_s=0.01)

_WRITE_TOKENS = ("edit", "merge", "patch", "sync", "word", "line")


class _Session:
    """One editing session: an agent name plus its last-known version
    per doc (the `version` field each edit applies at). Churn retires
    the whole object and mints a fresh agent name."""

    def __init__(self, tenant: int, slot: int, gen: int) -> None:
        self.agent = f"t{tenant}s{slot}g{gen}"
        self.versions: Dict[str, list] = {}


class _Counts:
    def __init__(self) -> None:
        self.writes = 0          # successful interactive edit calls
        self.write_ops = 0
        self.reads = 0
        self.read_refusals = 0   # follower 503s (staleness contract)
        self.bulk_ops = 0
        self.bank_edits = 0
        self.sheds = 0           # QoS 429s (deliberate, not errors)
        self.errors = 0
        self.bytes_sent = 0
        self.bytes_received = 0

    def ops(self) -> int:
        return self.writes + self.reads + self.bulk_ops \
            + self.bank_edits

    def as_dict(self) -> Dict[str, int]:
        return {"ops": self.ops(), "writes": self.writes,
                "write_ops": self.write_ops, "reads": self.reads,
                "read_refusals": self.read_refusals,
                "bulk_ops": self.bulk_ops,
                "bank_edits": self.bank_edits, "sheds": self.sheds,
                "errors": self.errors,
                "bytes_sent": self.bytes_sent,
                "bytes_received": self.bytes_received}


def _build_events(sc: Scenario) -> List[tuple]:
    """The full deterministic event tape: (t, kind, payload) sorted by
    virtual time. Kinds: write(doc_idx, n), read(doc_idx), bulk(tenant),
    churn()."""
    events: List[tuple] = []
    writes = make_arrivals(sc.arrivals, seed=sc.seed)
    times = writes.schedule(sc.duration_s)
    docs = make_popularity(sc.popularity, len(sc.doc_ids()),
                           seed=sc.seed).draws(times)
    acc = 0.0
    for t, d in zip(times, docs):
        events.append((t, "write", d))
        acc += sc.reads_per_write
        n_reads, acc = int(acc), acc - int(acc)
        for j in range(n_reads):
            events.append((t, "read", d))
    if sc.bulk:
        bulk = make_arrivals(sc.bulk["arrivals"], seed=sc.seed + 1)
        for i, t in enumerate(bulk.schedule(sc.duration_s)):
            events.append((t, "bulk", i % sc.tenants))
    if sc.session_churn_every_s > 0:
        t = sc.session_churn_every_s
        while t < sc.duration_s:
            events.append((t, "churn", None))
            t += sc.session_churn_every_s
    if sc.chaos:
        p = sc.chaos.get("partition")
        if p:
            events.append((float(p["at_s"]), "cut", None))
            events.append((float(p["heal_s"]), "heal", None))
        c = sc.chaos.get("crash")
        if c:
            events.append((float(c["at_s"]), "crash",
                           int(c.get("server", 1))))
            events.append((float(c["restart_s"]), "reboot",
                           int(c.get("server", 1))))
    events.sort(key=lambda e: (e[0], e[1]))
    return events


def run_scenario(sc: Optional[Scenario], data_dir: Optional[str] = None,
                 progress: bool = False, qos: bool = False,
                 incidents: bool = True,
                 incident_opts: Optional[dict] = None,
                 checkpoint_every_s: float = 0.0,
                 resume_dir: Optional[str] = None,
                 stop_after_ticks: Optional[int] = None) -> dict:
    """`qos=True` attaches the adaptive-admission controller to every
    server and tags lanes with their class (interactive edits vs bulk
    imports); the scorecard then carries a `qos` block merged across
    the mesh. Default False keeps the static admission path byte-
    identical — the A/B control arm for `scorecard-diff`.

    `incidents=True` (default) arms the incident engine's anomaly
    detector on every server (polled once per tick) and embeds an
    `incidents` block in the scorecard; `incidents=False` is the
    overhead A/B control arm.

    Long-run mode: `checkpoint_every_s > 0` arms per-server persistent
    data dirs (the chaos-churn journaling) and writes a runner-state
    checkpoint — tape cursor, per-session frontiers, rng state,
    interim counters, incident index — every N *virtual* seconds.
    `resume_dir` reloads such a checkpoint (`sc` may be None; the
    scenario rides inside it), reboots the servers on their journaled
    dirs, and replays the tape from the cursor, so the final scorecard
    is the one the uninterrupted run would have produced.
    `stop_after_ticks` force-checkpoints after that tick and tears the
    mesh down crash-style (the in-process kill used by the resume test
    and the bench soak-resume smoke), returning an `aborted` marker
    instead of a scorecard."""
    from ..qos.classes import QOS_HEADER
    from ..qos.metrics import merge_snapshots
    from ..replicate.node import attach_replication
    from ..tools.server import serve

    # ---- resume: the scenario and all toggles ride the checkpoint --------
    ck = None
    run_root = None
    if resume_dir is not None:
        with open(os.path.join(resume_dir, "checkpoint.json"),
                  encoding="utf8") as f:
            ck = json.load(f)
        sc = Scenario.from_dict(ck["scenario"])
        qos = bool(ck["qos"])
        incidents = bool(ck["incidents"])
        incident_opts = ck.get("incident_opts") or incident_opts
        checkpoint_every_s = float(ck.get("checkpoint_every_s") or 0.0)
        run_root = resume_dir

    rng = random.Random(f"runner:{sc.name}:{sc.seed}")
    events = _build_events(sc)
    doc_ids = sc.doc_ids()
    counts = _Counts()
    read_latency = Histogram()
    t_start = time.monotonic()
    # shape-steer counters are process-global and unconditional; the
    # start snapshot turns them into per-run deltas for the scorecard
    from ..tpu.steer import STEER
    steer0 = STEER.snapshot()
    inc_opts = {**RUNNER_INCIDENT_OPTS, **(incident_opts or {})}

    # ---- persistence arming (replicate/faults.py + long-run mode) --------
    # a chaos tape needs two things the plain runner skips: a shared
    # FaultInjector on every PeerTable, and per-server persistence so
    # the crash victim reboots on its own journals and .dt files. The
    # long-run mode arms the same per-server dirs (checkpoint/resume
    # rides the journals), chaos or not.
    faults = None
    persist = bool(sc.chaos) or checkpoint_every_s > 0 \
        or resume_dir is not None
    keep_root = checkpoint_every_s > 0 or resume_dir is not None
    dirs: List[Optional[str]] = [None] * sc.servers
    chaos_counts = {"partitions": 0, "heals": 0, "crashes": 0,
                    "reboots": 0}
    if sc.chaos:
        from ..replicate.faults import FaultInjector
        faults = FaultInjector(seed=sc.seed)
    if persist:
        if run_root is None:
            run_root = tempfile.mkdtemp(prefix="dt-scenario-run-")
        dirs = [os.path.join(run_root, f"n{i}")
                for i in range(sc.servers)]
        for d in dirs:
            os.makedirs(d, exist_ok=True)

    def _node_opts(i: int) -> Dict:
        opts = dict(seed=sc.seed, lease_ttl_s=1.0, timeout_s=2.0,
                    backoff_base_s=0.02, backoff_cap_s=0.1)
        if faults is not None:
            opts["faults"] = faults
        if dirs[i] is not None:
            opts["journal_prefix"] = os.path.join(dirs[i], "_replica")
        return opts

    # ---- boot the mesh (replicate-soak pattern, stepped inline) ----------
    httpds, nodes, addrs = [], [], []
    live = [True] * sc.servers
    boots = [0] * sc.servers
    tick_box = {"tick": 0}
    burn_minutes: Dict[str, float] = {}
    prior_incidents: List[dict] = []
    prior_suppressed = 0

    def _mk_context(i: int):
        """Capture-time context frozen into each incident bundle: the
        burn-minute integral and tick let the scorecard rank bundles
        by worst burn."""
        def ctx() -> dict:
            return {"server": addrs[i] if i < len(addrs) else None,
                    "tick": tick_box["tick"],
                    "burn_minutes_total":
                        round(sum(burn_minutes.values()), 4)}
        return ctx

    def _serve_node(i: int, port: int = 0):
        boots[i] += 1
        httpd = serve(port=port, serve_shards=sc.serve_shards,
                      data_dir=dirs[i], follower_reads=True,
                      obs_opts=dict(
                          sample_rate=1.0, incidents=incidents,
                          incident_opts=dict(
                              inc_opts,
                              prefix=f"n{i}.{boots[i]}.")),
                      qos=qos)
        httpd.store.obs.incidents.context_provider = _mk_context(i)
        return httpd

    saved_ports = (ck.get("ports") or []) if ck is not None else []
    for i in range(sc.servers):
        httpd = None
        if i < len(saved_ports):
            # resume prefers the checkpointed ports (replica journals
            # key lease state by self_id = host:port); fall back to an
            # ephemeral port if something else grabbed it meanwhile
            try:
                httpd = _serve_node(i, port=int(saved_ports[i]))
            except OSError:
                httpd = None
        if httpd is None:
            httpd = _serve_node(i)
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    for i, httpd in enumerate(httpds):
        if sc.servers > 1:
            node = attach_replication(
                httpd, addrs[i], [a for a in addrs if a != addrs[i]],
                **_node_opts(i))
            nodes.append(node)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()

    def _harvest_incidents(i: int) -> None:
        """Fold server i's in-memory incident index (+ per-bundle burn
        context) into the run-level rows before its obs bundle is lost
        to a crash/teardown, exactly once per boot."""
        nonlocal prior_suppressed
        httpd = httpds[i]
        if getattr(httpd, "_incidents_harvested", False):
            return
        httpd._incidents_harvested = True
        obs = httpd.store.obs
        for r in obs.incidents.index_json()["incidents"]:
            b = obs.incidents.get(r["id"]) or {}
            ctx = b.get("context") or {}
            prior_incidents.append({
                "id": r["id"], "t": r["t"], "kind": r["kind"],
                "series": r["series"], "detail": r.get("detail"),
                "server": addrs[i],
                "burn_minutes_total":
                    ctx.get("burn_minutes_total", 0.0)})
        prior_suppressed += obs.incident_detector.suppressed

    def crash_server(i: int) -> None:
        """Tear slot `i` down WITHOUT closing its journal (the reboot
        replays the WAL, torn tail and all) — the soak's crash shape."""
        _harvest_incidents(i)
        nodes[i].journal = None
        nodes[i].leases.journal = None
        httpds[i].shutdown()
        httpds[i].server_close()
        live[i] = False

    def reboot_server(i: int) -> None:
        port = int(addrs[i].split(":")[1])
        httpd = _serve_node(i, port=port)
        node = attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            **_node_opts(i))
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        httpds[i] = httpd
        nodes[i] = node
        live[i] = True

    def pick_server() -> int:
        """Round-robin target among LIVE servers (the load balancer's
        health check; a crashed server takes no client traffic)."""
        alive = [i for i in range(sc.servers) if live[i]]
        return alive[rng.randrange(len(alive))]

    def step_control_plane() -> None:
        for j, node in enumerate(nodes):
            if not live[j]:
                continue
            node.table.probe_once()
            node.maintain()
        for j, node in enumerate(nodes):
            if live[j]:
                node.antientropy.run_round()

    # ---- HTTP primitives -------------------------------------------------
    def post_edit(si: int, doc: str, session: _Session,
                  ops: List[dict], qos_cls: Optional[str] = None) -> bool:
        body = json.dumps({"agent": session.agent,
                           "version": session.versions.get(doc, []),
                           "ops": ops}).encode("utf8")
        req = urllib.request.Request(
            f"http://{addrs[si]}/doc/{doc}/edit", data=body)
        if qos_cls is not None:
            req.add_header(QOS_HEADER, qos_cls)
        counts.bytes_sent += len(body)
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                resp = r.read()
        except urllib.error.HTTPError as e:
            e.close()
            if e.code == 429:    # deliberate QoS shed, not a failure
                counts.sheds += 1
            else:
                counts.errors += 1
            return False
        except OSError:
            counts.errors += 1
            return False
        counts.bytes_received += len(resp)
        session.versions[doc] = json.loads(resp)["version"]
        return True

    def get_doc(si: int, doc: str) -> None:
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(
                    f"http://{addrs[si]}/doc/{doc}", timeout=5) as r:
                counts.bytes_received += len(r.read())
        except urllib.error.HTTPError as e:
            e.close()
            if e.code == 503:     # honest staleness refusal, not a bug
                counts.read_refusals += 1
            else:
                counts.errors += 1
            return
        except OSError:
            counts.errors += 1
            return
        counts.reads += 1
        read_latency.record(time.monotonic() - t0)

    # ---- sessions --------------------------------------------------------
    gen = 0
    sessions: Dict[int, List[_Session]] = {
        t: [_Session(t, k, gen) for k in range(sc.sessions_per_tenant)]
        for t in range(sc.tenants)}
    session_churns = 0

    # ---- tick loop -------------------------------------------------------
    ticks = max(int(sc.duration_s / sc.tick_s + 0.999999), 1)
    # zero-filled per objective so the scorecard column is explicit
    # (and diffable) even on a fully healthy run (update in place:
    # the incident context closures hold a reference)
    for o in httpds[0].store.obs.slo.objectives:
        burn_minutes[o.name] = 0.0
    ev_i = 0
    start_tick = 0

    # ---- resume: restore the runner state the checkpoint froze ----------
    if ck is not None:
        start_tick = int(ck["tick"])
        ev_i = int(ck["ev_i"])
        gen = int(ck["gen"])
        session_churns = int(ck["session_churns"])
        counts.__dict__.update(ck["counts"])
        burn_minutes.update(ck["burn_minutes"])
        chaos_counts.update(ck["chaos_counts"])
        st = ck["rng_state"]
        rng.setstate((st[0], tuple(st[1]), st[2]))
        h = ck["read_latency"]
        read_latency.counts = list(h["counts"])
        read_latency.overflow = int(h["overflow"])
        read_latency.count = int(h["count"])
        read_latency.sum = float(h["sum"])
        read_latency.max = float(h["max"])
        sessions = {}
        for t_key, rows in ck["sessions"].items():
            lst = []
            for k, row in enumerate(rows):
                s = _Session(int(t_key), k, gen)
                s.agent = row["agent"]
                s.versions = {d: list(v)
                              for d, v in row["versions"].items()}
                lst.append(s)
            sessions[int(t_key)] = lst
        prior_incidents.extend(ck.get("incident_index") or [])
        prior_suppressed += int(ck.get("suppressed") or 0)
        # re-create the mid-crash topology the checkpoint froze (the
        # tape's pending reboot event will bring the victim back)
        for i, was_live in enumerate(ck.get("live") or []):
            if not was_live and live[i] and nodes:
                crash_server(i)

    def _write_checkpoint(next_tick: int) -> None:
        """Atomic runner-state checkpoint under the run root: enough
        to replay the tape from `next_tick` against rebooted servers.
        The doc/lease state itself is NOT here — it lives in the
        per-server journals the same dirs already persist."""
        state = {
            "version": 1,
            "scenario": sc.to_dict(),
            "qos": qos, "incidents": incidents,
            "incident_opts": incident_opts,
            "checkpoint_every_s": checkpoint_every_s,
            "tick": next_tick, "ticks": ticks, "ev_i": ev_i,
            "gen": gen, "session_churns": session_churns,
            "counts": dict(counts.__dict__),
            "burn_minutes": dict(burn_minutes),
            "chaos_counts": dict(chaos_counts),
            "live": list(live),
            "ports": [int(a.split(":")[1]) for a in addrs],
            "rng_state": [rng.getstate()[0], list(rng.getstate()[1]),
                          rng.getstate()[2]],
            "read_latency": {"counts": list(read_latency.counts),
                             "overflow": read_latency.overflow,
                             "count": read_latency.count,
                             "sum": read_latency.sum,
                             "max": read_latency.max},
            "sessions": {str(t): [{"agent": s.agent,
                                   "versions": s.versions}
                                  for s in lst]
                         for t, lst in sessions.items()},
            "incident_index": prior_incidents + [
                r for i in range(sc.servers) if live[i]
                for r in _peek_incidents(i)],
            "suppressed": prior_suppressed + sum(
                httpds[i].store.obs.incident_detector.suppressed
                for i in range(sc.servers) if live[i]),
            # interim scorecard: the coarse progress numbers an
            # operator tails while the soak runs
            "interim": {"writes": counts.writes, "reads": counts.reads,
                        "errors": counts.errors,
                        "sheds": counts.sheds,
                        "burn_minutes_total":
                            round(sum(burn_minutes.values()), 4)},
        }
        path = os.path.join(run_root, "checkpoint.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf8") as f:
            f.write(json.dumps(state) + "\n")
        os.replace(tmp, path)

    def _peek_incidents(i: int) -> List[dict]:
        """Server i's current in-memory incident rows (burn-enriched),
        without marking them harvested."""
        obs = httpds[i].store.obs
        rows = []
        for r in obs.incidents.index_json()["incidents"]:
            b = obs.incidents.get(r["id"]) or {}
            ctx = b.get("context") or {}
            rows.append({"id": r["id"], "t": r["t"], "kind": r["kind"],
                         "series": r["series"],
                         "detail": r.get("detail"),
                         "server": addrs[i],
                         "burn_minutes_total":
                             ctx.get("burn_minutes_total", 0.0)})
        return rows

    def publish(phase: str, tick: int, extra: str = "") -> None:
        worst, names = "ok", []
        for httpd in httpds:
            v = httpd.store.obs.slo.verdict()
            if v["burning"]:
                worst = "burning"
                names += v["burning"]
            elif v["warning"] and worst != "burning":
                worst = "warning"
                names += v["warning"]
        publish_scenario({
            "name": sc.name, "phase": phase,
            "tick": tick, "ticks": ticks,
            "virtual_t": round(min(tick * sc.tick_s, sc.duration_s), 2),
            "writes": counts.writes, "reads": counts.reads,
            "errors": counts.errors,
            "slo_state": worst,
            "verdict": (f"slo={worst}"
                        + (" [" + ",".join(sorted(set(names))) + "]"
                           if names else "") + extra),
        })

    next_ckpt = 0.0
    if checkpoint_every_s > 0:
        next_ckpt = (start_tick * sc.tick_s) + checkpoint_every_s
    for tick in range(start_tick, ticks):
        tick_box["tick"] = tick + 1
        horizon = (tick + 1) * sc.tick_s
        while ev_i < len(events) and events[ev_i][0] < horizon:
            t, kind, arg = events[ev_i]
            ev_i += 1
            if kind == "write":
                doc = doc_ids[arg]
                tenant = int(doc[1:doc.index("-")])
                ses = sessions[tenant][
                    rng.randrange(sc.sessions_per_tenant)]
                tok = f"{rng.choice(_WRITE_TOKENS)} "
                if post_edit(pick_server(), doc, ses,
                             [{"kind": "ins", "pos": 0, "text": tok}]):
                    counts.writes += 1
                    counts.write_ops += 1
            elif kind == "read":
                get_doc(pick_server(), doc_ids[arg])
            elif kind == "bulk":
                tenant = arg
                doc = f"t{tenant}-bulk000"
                ses = sessions[tenant][0]
                payload = "x" * int(sc.bulk.get("bytes_per_op", 1024))
                if post_edit(pick_server(), doc, ses,
                             [{"kind": "ins", "pos": 0,
                               "text": payload}],
                             qos_cls="bulk" if qos else None):
                    counts.bulk_ops += 1
            elif kind == "cut":
                p = sc.chaos["partition"]
                faults.partition(addrs[int(p.get("a", 1))],
                                 addrs[int(p.get("b", 0))],
                                 oneway=bool(p.get("oneway", True)))
                chaos_counts["partitions"] += 1
            elif kind == "heal":
                p = sc.chaos["partition"]
                faults.heal(addrs[int(p.get("a", 1))],
                            addrs[int(p.get("b", 0))])
                chaos_counts["heals"] += 1
            elif kind == "crash":
                if live[arg]:
                    crash_server(arg)
                    chaos_counts["crashes"] += 1
            elif kind == "reboot":
                if not live[arg]:
                    reboot_server(arg)
                    chaos_counts["reboots"] += 1
            elif kind == "churn":
                gen += 1
                session_churns += 1
                sessions = {
                    t: [_Session(t, k, gen)
                        for k in range(sc.sessions_per_tenant)]
                    for t in range(sc.tenants)}
        step_control_plane()
        # burn-minute integration: a tick spent in a non-ok state
        # charges tick_s/60 to that objective (summed across nodes —
        # mesh-wide burn)
        for httpd in httpds:
            for row in httpd.store.obs.slo.evaluate():
                if row["state"] != "ok":
                    burn_minutes[row["name"]] = burn_minutes.get(
                        row["name"], 0.0) + sc.tick_s / 60.0
        # incident engine: one detector poll per live server per tick
        # (the slo_transition events the evaluate() above just recorded
        # are visible to this poll — burn bundles fire the same tick)
        for j in range(sc.servers):
            if live[j]:
                httpds[j].store.obs.incident_detector.poll()
        publish("traffic", tick + 1)
        if progress:    # pragma: no cover - human pacing output
            print(f"  tick {tick + 1}/{ticks}: {counts.writes} writes "
                  f"{counts.reads} reads {counts.errors} errors")
        virt = (tick + 1) * sc.tick_s
        if checkpoint_every_s > 0 and virt >= next_ckpt:
            _write_checkpoint(tick + 1)
            while next_ckpt <= virt:
                next_ckpt += checkpoint_every_s
        if stop_after_ticks is not None and tick + 1 >= stop_after_ticks \
                and tick + 1 < ticks:
            # the in-process kill: force a checkpoint, then tear every
            # server down crash-style (journals left open — resume
            # replays the WALs, torn tails and all)
            _write_checkpoint(tick + 1)
            publish("aborted", tick + 1, extra=" aborted=True")
            for i in range(sc.servers):
                if not live[i]:
                    continue
                if nodes:
                    nodes[i].journal = None
                    nodes[i].leases.journal = None
                httpds[i].shutdown()
                httpds[i].server_close()
            return {"aborted": True, "resume_dir": run_root,
                    "tick": tick + 1, "ticks": ticks,
                    "scenario": sc.name}

    # ---- bank-churn lane (device-tier spill accounting) ------------------
    bank_report = None
    if sc.bank:
        publish("bank-churn", ticks)
        bank_report = _run_bank_lane(sc, httpds[0], rng, counts,
                                     data_dir=data_dir,
                                     progress=progress)

    # ---- reconcile to convergence ----------------------------------------
    publish("reconcile", ticks)
    converged_after = None
    for r in range(sc.reconcile_rounds):
        step_control_plane()
        if _converged(addrs, doc_ids):
            converged_after = r + 1
            break
        time.sleep(0.02)    # let advert/breaker windows lapse
    converged = _converged(addrs, doc_ids)

    # ---- collect ---------------------------------------------------------
    serve_snaps = [h.store.scheduler.metrics.snapshot()
                   if h.store.scheduler is not None else None
                   for h in httpds]
    flush_p99 = max((s["latencies"]["flush"]["p99"]
                     for s in serve_snaps if s), default=None)
    vis_p99s = [h.store.obs.ts.quantile("journey.visibility", 0.99,
                                        window_s=3600.0)
                for h in httpds]
    vis_p99 = max((v for v in vis_p99s if v > 0), default=0.0)
    hydration: Dict[str, int] = {}
    for s in serve_snaps:
        if s:
            for k, v in s["hydration"].items():
                hydration[k] = hydration.get(k, 0) + v
    slo_burning, slo_warning, slo_ok = [], [], True
    for httpd in httpds:
        v = httpd.store.obs.slo.verdict()
        slo_ok = slo_ok and v["slo_ok"]
        slo_burning += v["burning"]
        slo_warning += v["warning"]
    lag = {addrs[i]: n.obs.journey.lag_summary()
           for i, n in enumerate(nodes)}
    # wire transport: per-channel counters summed across the mesh (every
    # host accounts the bytes IT sends, so the sum is total transport);
    # single-server runs have no mesh and omit the block entirely
    wire: Optional[Dict[str, Dict[str, float]]] = None
    if nodes:
        from ..wire.frames import WIRE_CHANNELS, WIRE_KEYS
        wire = {ch: {k: 0 for k in WIRE_KEYS} for ch in WIRE_CHANNELS}
        for node in nodes:
            flat = node.metrics.wire_counters()
            for ch in WIRE_CHANNELS:
                for k in WIRE_KEYS:
                    wire[ch][k] += flat[f"{ch}_{k}"]
    per_server = [{
        "addr": addrs[i],
        "flush_p99_s": (serve_snaps[i]["latencies"]["flush"]["p99"]
                        if serve_snaps[i] else None),
        "flushed_ops": (serve_snaps[i]["totals"]["flushed_ops"]
                        if serve_snaps[i] else 0),
        "visibility_p99_s": round(vis_p99s[i], 6),
    } for i in range(sc.servers)]
    # QoS: merge every server's QosMetrics snapshot into one mesh-wide
    # block (None when the controller was off, so A/B control cards
    # diff clean against pre-QoS baselines)
    qos_block = merge_snapshots([
        h.store.scheduler.qos.metrics.snapshot()
        if h.store.scheduler is not None
        and h.store.scheduler.qos is not None else None
        for h in httpds])
    if qos_block is not None:
        qos_block["sheds_observed"] = counts.sheds
    # incident engine: fold every surviving server's index into the
    # run-level rows (crash victims were harvested at crash time, and
    # a resumed run carries its pre-kill rows via the checkpoint)
    for i in range(sc.servers):
        _harvest_incidents(i)
    by_kind = dict.fromkeys(INCIDENT_KINDS, 0)
    for r in prior_incidents:
        by_kind[r["kind"]] = by_kind.get(r["kind"], 0) + 1
    worst = max(prior_incidents,
                key=lambda r: r.get("burn_minutes_total", 0.0),
                default=None)
    incidents_block = {
        "enabled": bool(incidents),
        "count": len(prior_incidents),
        "by_kind": by_kind,
        "suppressed": prior_suppressed,
        "worst_burn_minutes_id": worst["id"] if worst else None,
        "worst_burn_minutes":
            worst.get("burn_minutes_total", 0.0) if worst else 0.0,
        "timeline": sorted(prior_incidents, key=lambda r: r["t"]),
    }
    # device flush-pipeline block (scorecard `serve`): summed window
    # staging + dispatch fan-in from the servers' ServeMetrics, jit
    # hit rate from the steer counters' per-run delta. Host-engine
    # runs never dispatch a device window, so the block stays None and
    # the serve.* bands skip (missing-path semantics) — exactly like
    # pre-steer baselines.
    serve_block: Optional[dict] = None
    dw = sum(s["window"]["device_windows"] for s in serve_snaps if s)
    if dw > 0:
        steer1 = STEER.snapshot()
        looks = steer1["lookups"] - steer0["lookups"]
        warm_hits = (steer1["hits"] + steer1["padded"]
                     - steer0["hits"] - steer0["padded"])
        staged = sum(s["window"].get("staged_bytes", 0)
                     for s in serve_snaps if s)
        disp = sum(s["window"]["dispatches"] for s in serve_snaps if s)
        serve_block = {
            "jit_cache_hit_rate": round(warm_hits / looks, 4)
            if looks else 1.0,
            "staged_bytes": staged,
            "staged_bytes_per_window": round(staged / dw, 2),
            "device_calls_per_window": round(disp / dw, 4),
            "steer_compiles": steer1["compiles"] - steer0["compiles"],
        }
    wall_s = time.monotonic() - t_start
    # under an injected-fault tape, availability degrades by DESIGN
    # (client errors while partitioned, SLO burn during the crash) —
    # the run's gate is the safety property: byte-identical
    # convergence once healed and rebooted. Errors and burn are still
    # recorded honestly in the scorecard.
    ok = bool(converged) if sc.chaos else \
        bool(converged and slo_ok and counts.errors == 0)

    card = build_scorecard(
        scenario=sc.to_dict(),
        wall_s=wall_s, virtual_s=sc.duration_s,
        totals=counts.as_dict(),
        latency_p99_s={
            "flush": flush_p99,
            "read": read_latency.snapshot()["p99"],
            "visibility": round(vis_p99, 6),
        },
        latencies={"read": read_latency.snapshot()},
        slo={"slo_ok": slo_ok,
             "burning": sorted(set(slo_burning)),
             "warning": sorted(set(slo_warning))},
        burn_minutes=burn_minutes,
        convergence={"converged": converged,
                     "reconcile_rounds": converged_after,
                     "lag": lag},
        hydration=hydration,
        wire=wire,
        per_server=per_server,
        ok=ok,
        qos=qos_block,
        incidents=incidents_block,
        serve=serve_block,
        extra={"session_churns": session_churns,
               **({"bank": bank_report} if bank_report else {}),
               **({"chaos": {**chaos_counts,
                             "faults": faults.snapshot()}}
                  if sc.chaos else {}),
               **({"run_dir": run_root, "resumed": ck is not None}
                  if keep_root else {})},
    )
    publish("done", ticks, extra=f" ok={ok}")
    for httpd in httpds:
        httpd.shutdown()
        httpd.server_close()
    if run_root is not None and not keep_root:
        shutil.rmtree(run_root, ignore_errors=True)
    return card


def _run_bank_lane(sc: Scenario, primary, rng: random.Random,
                   counts: _Counts, data_dir: Optional[str] = None,
                   progress: bool = False) -> dict:
    """Churn `bank.docs` docs through a `bank.warm_slots`-sized
    Hydrator warm tier. The hydrator reports into the PRIMARY server's
    ServeMetrics, so spills_to_snapshot / spill_bytes land in the same
    hydration block the /metrics endpoint, prom families and scorecard
    read. Docs materialize on first touch (a missing home loads as a
    fresh oplog) — the population size costs nothing up front."""
    import shutil
    import tempfile

    from ..serve.hydrate import Hydrator
    from ..storage.tier import TieredStore

    bank = sc.bank
    root = data_dir or tempfile.mkdtemp(prefix="dt-scenario-bank-")
    own_root = data_dir is None
    guard = make_lock("workload.bank_oplog", "oplog")
    metrics = primary.store.scheduler.metrics \
        if primary.store.scheduler is not None else None
    store = TieredStore(root)
    hyd = Hydrator(store, workers=2, warm_max=bank["warm_slots"],
                   evict_grace_s=0.0, oplog_lock=guard,
                   metrics=metrics, seed=sc.seed)
    law = Zipf(bank["docs"], s=1.1, seed=sc.seed + 2)
    t0 = time.monotonic()
    touched = set()
    try:
        for rnd in range(bank["rounds"]):
            picks = law.draws([0.0] * bank["edits_per_round"])
            for j, d in enumerate(picks):
                doc = f"bank{d:07d}"
                ol = hyd.resolve(doc)
                a = ol.get_or_create_agent_id(f"bank{sc.seed}")
                with guard:
                    ol.add_insert(a, 0, f"<{rnd}.{j}> ")
                counts.bank_edits += 1
                touched.add(doc)
            if progress:    # pragma: no cover - human pacing output
                print(f"  bank round {rnd + 1}/{bank['rounds']}: "
                      f"{counts.bank_edits} edits, "
                      f"{hyd.warm_count()} warm")
    finally:
        hyd.stop(checkpoint=True)
    snap = hyd.counters_snapshot()
    return {"docs": bank["docs"], "warm_slots": bank["warm_slots"],
            "docs_touched": len(touched),
            "edits": counts.bank_edits,
            "spills_to_snapshot": snap.get("spills_to_snapshot", 0),
            "spill_bytes": snap.get("spill_bytes", 0),
            "wall_s": round(time.monotonic() - t0, 3),
            "cleaned": own_root and bool(
                shutil.rmtree(root, ignore_errors=True) or True)}


def _converged(addrs: List[str], doc_ids: List[str]) -> bool:
    for d in doc_ids:
        texts = set()
        for a in addrs:
            try:
                with urllib.request.urlopen(
                        f"http://{a}/doc/{d}", timeout=5) as r:
                    texts.add(r.read())
            except OSError:
                return False
        if len(texts) > 1:
            return False
    return True
