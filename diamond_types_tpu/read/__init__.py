"""Follower reads: serve checkouts from any replica under an explicit
staleness contract.

Writes stay owner-fenced (``replicate/``); this package turns the other
N-1 hosts into read bandwidth. A follower may answer ``GET /doc/{id}``
locally iff it can prove the response is no staler than the client's
``?max_staleness=`` bound and dominates the client's ``X-DT-Min-Version``
read-your-writes token; otherwise it proxies the read to the owner (or
refuses with 503 when the owner is unreachable).

Pieces:
  * :class:`~diamond_types_tpu.read.follower.FollowerIndex` — per-doc
    catch-up evidence (owner frontier advertisements piggybacked on ping
    gossip + anti-entropy rounds, completed-reconcile timestamps) that
    answers "how stale can a local read be, at most?".
  * :class:`~diamond_types_tpu.read.cache.CheckoutCache` — bounded LRU of
    materialized checkouts keyed ``(doc, frontier)`` with single-flight
    coalescing, invalidated by flush completion (owners) and
    anti-entropy apply (followers).
  * :class:`~diamond_types_tpu.read.path.ReadPath` — the serve decision:
    local / wait-then-local / proxy / refuse, with metrics + spans.
  * :class:`~diamond_types_tpu.read.metrics.ReadMetrics` — the ServeMetrics
    v8 ``read`` block, rendered as ``dt_read_*`` prom families.
  * :func:`~diamond_types_tpu.read.bench.run_read_bench` — two-server A/B
    driver (``cli read-bench``): follower reads vs owner-only proxying.
"""

from .cache import CheckoutCache
from .follower import FollowerIndex, frontier_known
from .metrics import READ_KEYS, ReadMetrics
from .path import ReadPath, attach_follower_reads

__all__ = [
    "CheckoutCache",
    "FollowerIndex",
    "frontier_known",
    "READ_KEYS",
    "ReadMetrics",
    "ReadPath",
    "attach_follower_reads",
]
