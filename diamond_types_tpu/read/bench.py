"""Follower-read A/B bench (CLI: `read-bench`).

Boots a two-server replication mesh on ephemeral localhost ports with
follower reads attached to both nodes, drives a continuous single-agent
writer per doc at each doc's owner, and runs two phases of Zipf-skewed
reader threads. Each read is routed to the chosen doc's NON-owner
replica — docs split across both nodes by the lease machinery, so the
readers spread across both; reads landing on the owner are identical
in both worlds and would only dilute the A/B contrast:

  * control   — every GET carries `?max_staleness=0`: only a node with
                staleness 0 (the lease holder) may serve locally, so
                every follower-side read proxies to the owner. This is
                the owner-only-checkout world the subsystem replaces.
  * follower  — every GET carries `?max_staleness=<bound>`: followers
                serve from their own oplog whenever the staleness
                evidence (anti-entropy adverts + reconcile floors)
                proves the bound, falling back to the proxy otherwise.

Every response is verified CLIENT-side, not trusted from the server:

  * staleness — a local response under a finite bound must carry
                `X-DT-Staleness` and it must not exceed the bound;
  * RYW       — every Nth read sends the doc's latest write token as
                `X-DT-Min-Version`; the response's `X-DT-Frontier`
                must carry the writer agent at a seq >= the token's
                (one writer agent per doc makes this check exact).

The verdict (`ok`) requires ZERO violations of either contract and
zero transport errors in both phases; when `min_speedup` is set the
follower/control aggregate-throughput ratio must also clear it. A
failing verdict embeds the flight-recorder tail of both nodes
(`events_tail`), same as replicate-soak.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from ..replicate.node import attach_replication


def _zipf_weights(n: int, s: float) -> List[float]:
    return [1.0 / (i + 1) ** s for i in range(n)]


def _post_json(addr: str, path: str, doc: dict, timeout: float) -> dict:
    req = urllib.request.Request(
        f"http://{addr}{path}", data=json.dumps(doc).encode("utf8"))
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode("utf8"))


class _Writer(threading.Thread):
    """One sequential writer agent per doc, always at the doc's owner:
    the doc's frontier stays single-headed on that agent, so the RYW
    check below is an exact per-agent seq comparison."""

    def __init__(self, owners: Dict[str, str], tokens: Dict[str, list],
                 interval_s: float, timeout_s: float) -> None:
        super().__init__(daemon=True)
        self.owners = owners
        self.tokens = tokens        # doc -> latest remote frontier
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.writes = 0
        self.errors = 0
        self._halt = threading.Event()

    def stop(self) -> None:
        self._halt.set()

    def run(self) -> None:
        doc_ids = sorted(self.owners)
        i = 0
        while not self._halt.is_set():
            doc_id = doc_ids[i % len(doc_ids)]
            i += 1
            try:
                out = _post_json(
                    self.owners[doc_id], f"/doc/{doc_id}/edit",
                    {"agent": f"w-{doc_id}",
                     "version": self.tokens[doc_id],
                     "ops": [{"kind": "ins", "pos": 0, "text": "w"}]},
                    self.timeout_s)
                self.tokens[doc_id] = out["version"]
                self.writes += 1
            except (OSError, KeyError, ValueError):
                self.errors += 1
            self._halt.wait(self.interval_s)


class _Reader(threading.Thread):
    """Zipf-skewed GETs, each routed to the chosen doc's NON-owner
    replica (the population follower reads exist for: a read landing
    on the owner is identical in both worlds and would only dilute the
    A/B contrast), verifying the staleness bound and the RYW token on
    every response. ``tokens`` is a phase-start snapshot of each doc's
    latest write version — the re-read-your-earlier-write flow — so a
    token read measures contract verification, not the catch-up wait
    (the acceptance test covers the wait/fallback path)."""

    def __init__(self, route: Dict[str, str], doc_ids: List[str],
                 weights: List[float],
                 tokens: Dict[str, list], reads: int,
                 max_staleness: float, min_version_every: int,
                 seed: int, timeout_s: float) -> None:
        super().__init__(daemon=True)
        self.route = route
        self.doc_ids = doc_ids
        self.weights = weights
        self.tokens = tokens
        self.reads = reads
        self.max_staleness = max_staleness
        self.min_version_every = min_version_every
        self.rng = random.Random(seed)
        self.timeout_s = timeout_s
        self.ok_reads = 0
        self.local = 0
        self.proxied = 0
        self.refused = 0
        self.errors = 0
        self.staleness_violations = 0
        self.ryw_violations = 0
        self.max_seen_staleness = 0.0
        self.latencies: List[float] = []

    def _check(self, doc_id: str, headers, token: Optional[list]) -> None:
        source = headers.get("X-DT-Read-Source", "")
        if source == "local":
            self.local += 1
            st = headers.get("X-DT-Staleness")
            if st is None:
                # a local response under a finite bound must PROVE it
                self.staleness_violations += 1
            else:
                val = float(st)
                self.max_seen_staleness = max(self.max_seen_staleness,
                                              val)
                if val > self.max_staleness + 1e-9:
                    self.staleness_violations += 1
        else:
            self.proxied += 1
        if token:
            heads = {a: int(s) for a, s in
                     json.loads(headers.get("X-DT-Frontier") or "[]")}
            for agent, seq in token:
                if heads.get(agent, -1) < int(seq):
                    self.ryw_violations += 1
                    break

    def run(self) -> None:
        for i in range(self.reads):
            doc_id = self.rng.choices(self.doc_ids,
                                      weights=self.weights)[0]
            token = None
            headers = {}
            if self.min_version_every and \
                    i % self.min_version_every == 0:
                token = self.tokens[doc_id]
                if token:
                    headers["X-DT-Min-Version"] = json.dumps(token)
            url = (f"http://{self.route[doc_id]}/doc/{doc_id}/state"
                   f"?max_staleness={self.max_staleness}")
            t0 = time.monotonic()
            try:
                req = urllib.request.Request(url, headers=headers)
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as r:
                    r.read()
                    self.ok_reads += 1
                    self.latencies.append(time.monotonic() - t0)
                    self._check(doc_id, r.headers, token)
            except urllib.error.HTTPError as e:
                e.read()
                if e.code == 503:
                    self.refused += 1
                else:
                    self.errors += 1
            except (OSError, ValueError):
                self.errors += 1


def run_read_bench(docs: int = 3, readers: int = 6,
                   reads_per_reader: int = 120, seed: int = 7,
                   zipf_s: float = 1.2, max_staleness_s: float = 2.0,
                   write_interval_s: float = 0.02,
                   min_version_every: int = 4,
                   lease_ttl_s: float = 30.0, serve_shards: int = 1,
                   settle_rounds: int = 80, doc_bytes: int = 16384,
                   min_speedup: Optional[float] = None,
                   progress: bool = False) -> dict:
    from ..tools.server import serve
    from . import attach_follower_reads

    doc_ids = [f"doc{i}" for i in range(docs)]
    weights = _zipf_weights(docs, zipf_s)
    node_opts = dict(seed=seed, lease_ttl_s=lease_ttl_s,
                     probe_interval_s=0.25,
                     antientropy_interval_s=0.25,
                     timeout_s=2.0, backoff_base_s=0.02,
                     backoff_cap_s=0.1)

    httpds, nodes, addrs = [], [], []
    for _ in range(2):
        httpd = serve(port=0, serve_shards=serve_shards)
        # the reader fleet opens a fresh connection per GET; the default
        # listen backlog (5) overflows under that churn whenever the
        # accept loop is briefly starved, and one dropped SYN costs the
        # client a ~1s kernel retransmit that dominates the phase wall
        httpd.socket.listen(256)
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    for i, httpd in enumerate(httpds):
        node = attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            **node_opts)
        attach_follower_reads(httpd.store)
        nodes.append(node)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()

    def step_control_plane() -> None:
        for n in nodes:
            n.table.probe_once()
            n.maintain()
        for n in nodes:
            n.antientropy.run_round()

    t0 = time.monotonic()
    # seed every doc (the mutation router proxies to whichever node
    # the lease machinery elects), then step until both nodes agree on
    # one ACTIVE owner per doc and the follower side holds a usable
    # staleness advert for it
    # checkout-sized payloads: a proxied read (de)serializes the body
    # an extra time and ships it over one extra hop, so the A/B
    # contrast is only visible with documents of realistic weight
    seed_text = ("lorem ipsum dolor sit amet " * 64)[:1707]
    tokens: Dict[str, list] = {}
    for doc_id in doc_ids:
        version: list = []
        for _ in range(max(1, doc_bytes // len(seed_text))):
            out = _post_json(addrs[0], f"/doc/{doc_id}/edit",
                             {"agent": f"w-{doc_id}", "version": version,
                              "ops": [{"kind": "ins", "pos": 0,
                                       "text": seed_text}]}, 5.0)
            version = out["version"]
        tokens[doc_id] = version

    owners: Dict[str, str] = {}

    def _settled() -> bool:
        owners.clear()
        for doc_id in doc_ids:
            holder = [n for n in nodes
                      if n.leases.active_epoch(doc_id) > 0]
            if len(holder) != 1:
                return False
            owner = holder[0]
            follower = next(n for n in nodes if n is not owner)
            if follower.route_mutation(doc_id) != owner.self_id:
                return False
            # the follower must already hold evidence good enough to
            # serve within the bound, or phase B starts cold
            rp = follower.store.reads
            fol = follower.store.get(doc_id)
            st = rp.index.staleness(
                doc_id, owner.self_id,
                lambda fr: rp._dominates(fol, fr))
            if st is None or st > max_staleness_s:
                return False
            owners[doc_id] = owner.self_id
        return True

    settled = False
    for _ in range(settle_rounds):
        step_control_plane()
        if _settled():
            settled = True
            break
        time.sleep(0.02)

    writer = _Writer(owners if settled else
                     {d: addrs[0] for d in doc_ids},
                     tokens, write_interval_s, timeout_s=5.0)
    writer.start()
    # background control plane keeps adverts fresh while the reader
    # phases run (manual stepping stops here)
    for n in nodes:
        n.start()

    # per-doc follower route: every read lands on the replica that
    # does NOT own the doc (docs split across both nodes, so the
    # readers spread across both; a read at the owner behaves the same
    # in both phases and would only dilute the A/B contrast)
    route = {d: next(a for a in addrs if a != owners.get(d, addrs[1]))
             for d in doc_ids}

    def _caught_up(snap: Dict[str, list]) -> bool:
        for doc_id, token in snap.items():
            follower = next(n for n in nodes
                            if n.self_id == route[doc_id])
            rp = follower.store.reads
            if not rp._dominates(follower.store.get(doc_id), token):
                return False
        return True

    def run_phase(max_staleness: float, label: str) -> dict:
        # phase-start RYW snapshot: each doc's latest write version,
        # then wait for the followers to absorb it so a token read
        # measures verification, not the anti-entropy catch-up sleep
        snap = {d: list(tokens[d]) for d in doc_ids}
        deadline = time.monotonic() + 4 * max(max_staleness_s, 0.5)
        while not _caught_up(snap) and time.monotonic() < deadline:
            time.sleep(0.02)
        rs = [_Reader(route, doc_ids, weights, snap,
                      reads_per_reader, max_staleness,
                      min_version_every, seed * 1000 + j, 10.0)
              for j in range(readers)]
        p0 = time.monotonic()
        for r in rs:
            r.start()
        for r in rs:
            r.join()
        wall = max(time.monotonic() - p0, 1e-9)
        total = sum(r.ok_reads for r in rs)
        out = {
            "max_staleness_s": max_staleness,
            "reads": total,
            "reads_per_s": round(total / wall, 1),
            "wall_s": round(wall, 3),
            "local": sum(r.local for r in rs),
            "proxied": sum(r.proxied for r in rs),
            "refused": sum(r.refused for r in rs),
            "errors": sum(r.errors for r in rs),
            "staleness_violations": sum(r.staleness_violations
                                        for r in rs),
            "ryw_violations": sum(r.ryw_violations for r in rs),
            "max_observed_staleness_s": round(
                max(r.max_seen_staleness for r in rs), 4),
        }
        lat = sorted(x for r in rs for x in r.latencies)
        if lat:
            out["latency_s"] = {
                "p50": round(lat[len(lat) // 2], 5),
                "p95": round(lat[int(len(lat) * 0.95)], 5),
                "max": round(lat[-1], 5),
            }
        if progress:
            print(f"{label}: {out['reads_per_s']} reads/s "
                  f"({out['local']} local / {out['proxied']} proxied)")
        return out

    control = run_phase(0.0, "control")
    follower = run_phase(max_staleness_s, "follower")

    writer.stop()
    writer.join(timeout=5)
    for n in nodes:
        n.stop()

    speedup = round(follower["reads_per_s"]
                    / max(control["reads_per_s"], 1e-9), 2)
    violations = sum(p["staleness_violations"] + p["ryw_violations"]
                    for p in (control, follower))
    errors = control["errors"] + follower["errors"] + writer.errors
    ok = (settled and violations == 0 and errors == 0
          and (min_speedup is None or speedup >= min_speedup))
    report = {
        "config": {"docs": docs, "readers": readers,
                   "reads_per_reader": reads_per_reader, "seed": seed,
                   "zipf_s": zipf_s, "max_staleness_s": max_staleness_s,
                   "min_version_every": min_version_every,
                   "serve_shards": serve_shards,
                   "min_speedup": min_speedup},
        "settled": settled,
        "owners": dict(owners),
        "writes": writer.writes,
        "write_errors": writer.errors,
        "control": control,
        "follower": follower,
        "speedup": speedup,
        "violations": violations,
        "errors": errors,
        "ok": ok,
        "wall_s": round(time.monotonic() - t0, 3),
        "read_metrics": {n.self_id:
                         n.store.reads.metrics.snapshot()
                         for n in nodes},
    }
    if not ok:
        # flight-recorder tail makes a failed bench diagnosable from
        # the JSON report alone (same idiom as replicate-soak)
        events = []
        for n in nodes:
            obs = getattr(n, "obs", None)
            if obs is None:
                continue
            for ev in obs.recorder.tail(50):
                events.append(dict(ev, node=n.self_id))
        events.sort(key=lambda e: e.get("t", 0.0))
        report["events_tail"] = events[-50:]
    for httpd in httpds:
        httpd.shutdown()
        httpd.server_close()
    return report
