"""Counters + histograms for the follower-read tier.

``ReadMetrics`` is the ServeMetrics v8 ``read`` block: attached to a
node's :class:`~diamond_types_tpu.read.path.ReadPath` and exported both
through ``GET /metrics`` (top-level ``read`` key) and, when a scheduler
is present, inside the ServeMetrics snapshot — ``obs/prom.py`` renders
either as ``dt_read_*`` families.

``READ_KEYS`` is the full counter surface, exported as a tuple for the
same reason ``serve/metrics.py`` exports ``HYDRATION_KEYS``: the prom
renderer and the tests import it, so the three surfaces cannot drift.
"""

from ..analysis import make_lock
from ..obs.hist import Histogram

# Every counter the read path can bump. Groups:
#   serve outcome:  reads, local, proxied_staleness, proxied_min_version,
#                   proxied_forced (X-DT-Proxied arrivals served locally
#                   on the owner side of a proxy hop), refused
#   cache:          cache_hits / cache_misses / cache_coalesced /
#                   cache_evictions / cache_wait_timeouts
#   invalidation:   flush_invalidations (owner, flush completion),
#                   ae_invalidations (follower, anti-entropy apply),
#                   invalidated_entries (cache entries actually dropped)
#   catch-up:       catchup_waits (entered the bounded wait),
#                   catchup_satisfied, catchup_timeouts
#   index feed:     adverts (owner frontier advertisements folded),
#                   reconciles (completed anti-entropy reconciles noted)
#   elastic mesh:   proxied_steered (staleness proxies redirected to a
#                   lightly loaded follower instead of the owner),
#                   warmed_on_hydrate (checkout-cache entries
#                   pre-materialized when hydration finished)
READ_KEYS = (
    "reads",
    "local",
    "proxied_staleness",
    "proxied_min_version",
    "proxied_forced",
    "refused",
    "cache_hits",
    "cache_misses",
    "cache_coalesced",
    "cache_evictions",
    "cache_wait_timeouts",
    "flush_invalidations",
    "ae_invalidations",
    "invalidated_entries",
    "catchup_waits",
    "catchup_satisfied",
    "catchup_timeouts",
    "adverts",
    "reconciles",
    "proxied_steered",
    "warmed_on_hydrate",
)


class ReadMetrics:
    """Thread-safe counters for the follower-read tier.

    Keys are FIXED (``READ_KEYS``): ``bump`` raises on an unknown key so
    a typo in the read path fails loudly in tests instead of silently
    minting a family the renderer never expected (same contract as
    ``ReplicationMetrics._GROUPS``).
    """

    # v1 -> v2: elastic mesh — proxied_steered + warmed_on_hydrate
    SCHEMA_VERSION = 2

    def __init__(self):
        self._lock = make_lock("read.metrics", "leaf")
        self._c = {k: 0 for k in READ_KEYS}
        # Staleness of every locally-served follower read (seconds of
        # proven-catch-up age; owners record 0.0).
        self.staleness = Histogram()
        # Wall time spent in the bounded catch-up wait, satisfied or not.
        self.wait = Histogram()
        # live-telemetry double-write target (obs TimeSeries), wired by
        # read.attach_follower_reads when the store has an obs bundle
        self.ts = None

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._c[key] += n
        if self.ts is not None:
            self.ts.inc(f"read.{key}", n)

    def observe_staleness(self, seconds: float) -> None:
        s = max(0.0, seconds)
        self.staleness.record(s)
        if self.ts is not None:
            self.ts.observe("read.staleness", s)

    def observe_wait(self, seconds: float) -> None:
        s = max(0.0, seconds)
        self.wait.record(s)
        if self.ts is not None:
            self.ts.observe("read.read_wait", s)

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._c)
        reads = counters["reads"]
        proxied = (counters["proxied_staleness"]
                   + counters["proxied_min_version"])
        return {
            "version": self.SCHEMA_VERSION,
            "counters": counters,
            "proxied": proxied,
            "local_ratio": (counters["local"] / reads) if reads else None,
            "staleness": self.staleness.snapshot(),
            "latencies": {"read_wait": self.wait.snapshot()},
        }
