"""The follower-read serve decision: local, wait-then-local, proxy, or
refuse.

``ReadPath`` sits between ``tools/server.py``'s GET handlers and the
store. On every read it classifies this node's relationship to the doc:

  * **owner** (holds the ACTIVE lease, or no replication is attached):
    serve locally with staleness 0 — through the cache.
  * **follower**: serve locally iff (a) the client's ``X-DT-Min-Version``
    token is dominated by the local oplog (waiting up to ``max_wait_s``
    for the anti-entropy stream to deliver it), and (b) the
    :class:`FollowerIndex` can bound the read's staleness within the
    client's ``?max_staleness=`` budget. Either miss proxies the read to
    the owner over the peer table (so fault injection and circuit
    breakers apply); an unreachable owner refuses with 503 rather than
    serve an out-of-contract response.

Proxied reads fetch the owner's ``/doc/{id}/state`` (the frontier rides
in the JSON, so the relayed ``X-DT-Frontier`` is authoritative) with
``X-DT-Proxied`` as the loop guard: the owner side serves locally,
still enforcing the min-version token but never proxying again.

The store's replica is resolved per-request, not at construction —
tests and the soak/bench drivers attach replication *after* the HTTP
server exists.
"""

import json
import time
from typing import List, Optional

from ..obs.trace import NOOP_SPAN, TRACE_HEADER, format_context
from .cache import CheckoutCache, frontier_key
from .follower import FollowerIndex, frontier_known
from .metrics import ReadMetrics

MIN_VERSION_HEADER = "X-DT-Min-Version"
FRONTIER_HEADER = "X-DT-Frontier"
SOURCE_HEADER = "X-DT-Read-Source"
STALENESS_HEADER = "X-DT-Staleness"


class ReadResult:
    __slots__ = ("status", "body", "ctype", "headers", "source")

    def __init__(self, status: int, body: bytes, ctype: str,
                 headers: dict, source: str):
        self.status = status
        self.body = body
        self.ctype = ctype
        self.headers = headers
        self.source = source


class ReadPath:
    """Per-node follower-read coordinator: FollowerIndex + CheckoutCache
    + the serve/proxy/refuse decision."""

    def __init__(self, store, metrics: Optional[ReadMetrics] = None,
                 cache_entries: int = 256, max_wait_s: float = 0.25,
                 poll_interval_s: float = 0.02,
                 proxy_timeout_s: float = 2.0):
        self.store = store
        self.metrics = metrics or ReadMetrics()
        self.index = FollowerIndex(self.metrics)
        self.cache = CheckoutCache(cache_entries, self.metrics)
        self.max_wait_s = max_wait_s
        self.poll_interval_s = poll_interval_s
        self.proxy_timeout_s = proxy_timeout_s

    # ---- environment -----------------------------------------------------

    @property
    def node(self):
        return getattr(self.store, "replica", None)

    @property
    def obs(self):
        return getattr(self.store, "obs", None)

    def _span(self, name: str, trace=None, **attrs):
        obs = self.obs
        if obs is None:
            return NOOP_SPAN
        return obs.tracer.start(name, parent=trace, attrs=attrs or None)

    # ---- invalidation hooks ----------------------------------------------

    def on_flush(self, doc_id: str) -> None:
        """Owner-side: a scheduler flush completed for the doc — its tip
        moved, so cached checkouts are stale-frontier footprint."""
        self.metrics.bump("flush_invalidations")
        self.cache.invalidate(doc_id)

    def on_antientropy_apply(self, doc_id: str) -> None:
        """Follower-side: anti-entropy pulled a patch into the doc."""
        self.metrics.bump("ae_invalidations")
        self.cache.invalidate(doc_id)

    def on_local_mutation(self, doc_id: str) -> None:
        """A locally-accepted write moved the tip (owner edits, pushed
        patches). Frontier-keyed entries stay correct; dropping them
        keeps the cache from pinning superseded checkouts."""
        self.cache.invalidate(doc_id)

    # ---- contract evaluation ---------------------------------------------

    def _dominates(self, ol, frontier) -> bool:
        with self.store.lock:
            return frontier_known(ol, frontier)

    def _wait_for_version(self, ol, min_version, trace=None,
                          doc_id: str = "") -> bool:
        """Bounded wait for the anti-entropy stream to deliver the
        client's read-your-writes token. Returns satisfaction."""
        if self._dominates(ol, min_version):
            return True
        self.metrics.bump("catchup_waits")
        span = self._span("read.wait", trace, doc=doc_id)
        t0 = time.monotonic()
        ok = False
        try:
            deadline = t0 + self.max_wait_s
            while time.monotonic() < deadline:
                time.sleep(self.poll_interval_s)
                if self._dominates(ol, min_version):
                    ok = True
                    break
        finally:
            dt = time.monotonic() - t0
            self.metrics.observe_wait(dt)
            self.metrics.bump(
                "catchup_satisfied" if ok else "catchup_timeouts")
            span.end(satisfied=ok, wait_s=round(dt, 4))
        return ok

    # ---- materialization -------------------------------------------------

    def _local_body(self, doc_id: str, ol, kind: str):
        """Checkout at the current tip via the cache. Returns
        (body, ctype, remote_frontier)."""
        with self.store.lock:
            frontier = list(ol.version)
            remote = ol.cg.local_to_remote_frontier(frontier)
        fkey = frontier_key(remote)

        def materialize():
            with self.store.lock:
                return ol.checkout(frontier).snapshot()

        text, _outcome = self.cache.get(doc_id, fkey, materialize)
        if kind == "state":
            body = json.dumps({"text": text, "version": remote}) \
                .encode("utf8")
            return body, "application/json", remote
        return text.encode("utf8"), "text/plain; charset=utf-8", remote

    def _serve_local(self, doc_id: str, ol, kind: str,
                     staleness: Optional[float]) -> ReadResult:
        body, ctype, remote = self._local_body(doc_id, ol, kind)
        headers = {FRONTIER_HEADER: json.dumps(remote),
                   SOURCE_HEADER: "local"}
        if staleness is not None:
            headers[STALENESS_HEADER] = f"{staleness:.3f}"
            self.metrics.observe_staleness(staleness)
        self.metrics.bump("local")
        return ReadResult(200, body, ctype, headers, "local")

    # ---- proxy / refuse --------------------------------------------------

    def _refuse(self, reason: str) -> ReadResult:
        self.metrics.bump("refused")
        body = json.dumps({"error": "read contract unsatisfiable",
                           "reason": reason}).encode("utf8")
        return ReadResult(503, body, "application/json",
                          {SOURCE_HEADER: "refused"}, "refused")

    def _proxy(self, doc_id: str, owner: str, kind: str, reason: str,
               min_version, trace=None,
               soft_fail: bool = False) -> Optional[ReadResult]:
        """``soft_fail`` (steered-follower attempts) returns None on any
        failure instead of minting a 503 — the caller falls back to the
        owner, so the read is not refused and must not count as one."""
        node = self.node
        span = self._span("read.proxy", trace, doc=doc_id, target=owner,
                          reason=reason)
        headers = {"X-DT-Proxied": "1"}
        if min_version is not None:
            headers[MIN_VERSION_HEADER] = json.dumps(min_version)
        ctx = span.context() if span.sampled else trace
        if ctx is not None:
            headers[TRACE_HEADER] = format_context(ctx)
        # advertise wire v1 so the owner may frame the mesh leg; the
        # end client still gets JSON — the saving is hop-only
        wire = getattr(node, "wire", None)
        wire_hdr = wire.header_value() if wire is not None else None
        if wire_hdr is not None:
            from ..wire.frames import WIRE_HEADER
            headers[WIRE_HEADER] = wire_hdr
        try:
            status, body = node.table.call(
                owner, f"/doc/{doc_id}/state",
                timeout=self.proxy_timeout_s, headers=headers)
        except Exception as e:
            span.end(outcome="unreachable", error=e.__class__.__name__)
            if soft_fail:
                return None
            return self._refuse(f"{reason}; owner unreachable")
        if status != 200:
            span.end(outcome=f"status_{status}")
            if soft_fail:
                return None
            return self._refuse(f"{reason}; owner answered {status}")
        try:
            from ..wire.frames import (FRAME_STATE, WireError,
                                       decode_frame, decode_state,
                                       is_frame)
            if is_frame(body):
                ftype, payload = decode_frame(body)
                if ftype != FRAME_STATE:
                    raise WireError("proxy: expected STATE frame")
                text, remote = decode_state(payload)
            else:
                state = json.loads(body)
                text, remote = state["text"], state["version"]
        except (ValueError, KeyError, TypeError):
            span.end(outcome="bad_body")
            if soft_fail:
                return None
            return self._refuse(f"{reason}; bad owner response")
        span.end(outcome="ok")
        self.metrics.bump("proxied_min_version" if reason == "min_version"
                          else "proxied_staleness")
        out_headers = {FRONTIER_HEADER: json.dumps(remote),
                       SOURCE_HEADER: "proxied"}
        if kind == "state":
            # re-inflate for the client regardless of transport framing
            return ReadResult(200, json.dumps(
                {"text": text, "version": remote}).encode("utf8"),
                "application/json", out_headers, "proxied")
        return ReadResult(200, text.encode("utf8"),
                          "text/plain; charset=utf-8", out_headers,
                          "proxied")

    # ---- elastic-mesh hooks ----------------------------------------------

    def warm_on_hydrate(self, doc_id: str, ol=None) -> bool:
        """Hydrator completion hook: pre-materialize the checkout cache
        entry for the doc's current frontier, so the first read after a
        migration/hydration is a cache hit instead of a cold checkout.
        ``ol`` is the freshly-installed oplog when the hydrator calls
        this; store-resident docs pass None and resolve by id.
        Best-effort — a doc evicted between hydrate and this call just
        skips the warm."""
        try:
            with self.store.lock:
                if ol is None:
                    ol = self.store.docs.get(doc_id)
                if ol is None:
                    return False
                frontier = list(ol.version)
                remote = ol.cg.local_to_remote_frontier(frontier)
            fkey = frontier_key(remote)

            def materialize():
                with self.store.lock:
                    return ol.checkout(frontier).snapshot()

            _text, outcome = self.cache.get(doc_id, fkey, materialize)
        except Exception:       # pragma: no cover - warm must not wedge
            return False
        if outcome == "miss":   # freshly installed, not already warm
            self.metrics.bump("warmed_on_hydrate")
        return outcome in ("miss", "hit")

    def _steer_target(self, doc_id: str, owner: str,
                      max_staleness: Optional[float]):
        """Pick a lightly loaded follower to absorb a staleness proxy
        instead of the owner. Returns (peer_id, owner_advert_frontier)
        or (None, None). Safety comes from the proxy protocol, not the
        load table: we forward the owner's advertised frontier as the
        min-version token, so the steered follower serves only if its
        oplog provably contains it (and refuses otherwise — we then
        fall back to the owner). The load numbers (gossiped held-lease
        counts) only decide WHO to try."""
        node = self.node
        advert = self.index.advert_of(doc_id, owner)
        if advert is None:
            return None, None
        frontier, as_of = advert
        age = max(0.0, time.monotonic() - as_of)
        if max_staleness is not None and age > max_staleness:
            return None, None   # evidence too old to promise anything
        loads = getattr(node, "peer_load", None)
        if not loads:
            return None, None
        owner_load = loads.get(owner)
        if owner_load is None:
            return None, None
        cands = [(load, pid) for pid, load in loads.items()
                 if pid not in (owner, node.self_id)
                 and pid in node.ownership_ids() and load < owner_load]
        if not cands:
            return None, None
        return min(cands)[1], frontier

    # ---- the decision ----------------------------------------------------

    def read(self, doc_id: str, kind: str = "text",
             max_staleness: Optional[float] = None,
             min_version: Optional[List] = None,
             forced_local: bool = False, trace=None) -> ReadResult:
        """Serve one GET under the staleness contract. ``kind`` is
        ``"text"`` (GET /doc/{id}) or ``"state"`` (GET /doc/{id}/state).
        ``forced_local`` marks the owner side of a proxy hop: never
        proxy again (loop guard), but still honor the token."""
        self.metrics.bump("reads")
        obs = self.obs
        if obs is not None and getattr(obs, "attrib", None) is not None:
            # per-doc read attribution: "which doc is hot" is exactly
            # what follower-read placement wants out of /debug/hot
            obs.attrib.note("ops", doc=doc_id)
        ol = self.store.get(doc_id)
        node = self.node

        if node is None:
            # Single-node server: always authoritative.
            return self._serve_local(doc_id, ol, kind, 0.0)

        if forced_local:
            self.metrics.bump("proxied_forced")
            if min_version is not None \
                    and not self._wait_for_version(ol, min_version, trace,
                                                   doc_id):
                return self._refuse("min_version (proxied hop)")
            staleness = 0.0 if node.leases.active_epoch(doc_id) > 0 \
                else None
            return self._serve_local(doc_id, ol, kind, staleness)

        if node.leases.active_epoch(doc_id) > 0:
            # Owner: authoritative, staleness 0. The token is trivially
            # satisfied for writes routed here; a token minted on
            # another replica's degraded local accept may still be
            # missing, so check it.
            if min_version is not None \
                    and not self._wait_for_version(ol, min_version, trace,
                                                   doc_id):
                return self._refuse("min_version (owner missing token)")
            return self._serve_local(doc_id, ol, kind, 0.0)

        # Follower.
        owner = node.route_mutation(doc_id)
        if min_version is not None \
                and not self._wait_for_version(ol, min_version, trace,
                                               doc_id):
            if owner == node.self_id:
                return self._refuse("min_version; no reachable owner")
            return self._proxy(doc_id, owner, kind, "min_version",
                               min_version, trace)

        if max_staleness is not None:
            staleness = self.index.staleness(
                doc_id, owner, lambda fr: self._dominates(ol, fr))
            if staleness is None or staleness > max_staleness:
                if owner == node.self_id:
                    return self._refuse("staleness; no reachable owner")
                # elastic mesh: try a lightly loaded follower first,
                # proving freshness via the min-version token (the
                # owner's advertised frontier, merged with the
                # client's own token); any failure falls back to the
                # owner proxy
                target, adv = self._steer_target(doc_id, owner,
                                                 max_staleness)
                if target is not None:
                    token = list(adv) + list(min_version or [])
                    res = self._proxy(doc_id, target, kind, "staleness",
                                      token, trace, soft_fail=True)
                    if res is not None:
                        self.metrics.bump("proxied_steered")
                        return res
                return self._proxy(doc_id, owner, kind, "staleness",
                                   min_version, trace)
            return self._serve_local(doc_id, ol, kind, staleness)

        # No staleness bound requested: serve local, reporting the
        # bound we could prove (if any) for observability.
        staleness = self.index.staleness(
            doc_id, owner, lambda fr: self._dominates(ol, fr))
        return self._serve_local(doc_id, ol, kind, staleness)


def attach_follower_reads(store, **opts) -> ReadPath:
    """Build a ReadPath, hang it on the store (``store.reads``), and
    wire the owner-side flush-completion invalidation hook when a
    scheduler is attached. Mirrors ``attach_replication``'s shape."""
    rp = ReadPath(store, **opts)
    store.reads = rp
    sched = getattr(store, "scheduler", None)
    if sched is not None:
        sched.read_invalidate = rp.on_flush
        if getattr(sched, "metrics", None) is not None:
            sched.metrics.read = rp.metrics
        # elastic mesh: pre-materialize the checkout cache whenever the
        # residency tier brings a doc warm (first read after a
        # migration/hydration hits instead of checking out cold)
        if getattr(sched, "hydrator", None) is not None:
            sched.hydrator.on_warm = rp.warm_on_hydrate
    # live-telemetry double-write: read counters/staleness/waits land
    # in the windowed TimeSeries for the read-staleness SLO
    obs = getattr(store, "obs", None)
    if obs is not None and getattr(obs, "ts", None) is not None:
        rp.metrics.ts = obs.ts
    return rp
