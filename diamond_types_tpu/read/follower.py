"""Per-node catch-up evidence for the follower-read staleness contract.

A follower may serve ``GET /doc/{id}`` locally only when it can bound
the response's staleness. The bound comes from two kinds of timestamped
evidence, both piggybacked on traffic the mesh already sends:

  * **Advertisement**: the owner's frontier as of time ``t`` (carried on
    ping gossip and on anti-entropy ``/replicate/docs`` rounds). If the
    local oplog DOMINATES that frontier — every advertised ``(agent,
    seq)`` head is locally known — then the local checkout is at least
    as new as the owner was at ``t``, so its staleness is at most
    ``now - t``.
  * **Reconcile**: a completed anti-entropy round with the owner that
    started at ``t`` proves the local oplog holds everything the owner
    had at ``t`` (the summary handshake pulls any remainder), giving the
    same ``now - t`` bound without a frontier comparison.

``staleness()`` returns the tightest bound across all usable evidence,
``None`` when there is none — an unbounded read, which the contract
treats as a miss (proxy to the owner). Owners answer 0 directly in
:class:`~diamond_types_tpu.read.path.ReadPath` and never consult this
index.

Timestamps are conservative lower bounds on "when the owner was in this
state": anti-entropy stamps *before* issuing the request; ping-gossip
folds stamp at fold time, accepting sub-RTT slop (the contract's useful
bounds are hundreds of milliseconds and up).
"""

import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..analysis import make_lock

RemoteFrontier = Sequence[Sequence]          # [[agent, seq], ...]


def frontier_known(ol, frontier: RemoteFrontier) -> bool:
    """True iff the local oplog contains every ``(agent, seq)`` head of
    a remote frontier — i.e. the local state dominates it. Caller holds
    the store's oplog guard."""
    aa = ol.cg.agent_assignment
    for head in frontier:
        agent_name, seq = head[0], int(head[1])
        agent = aa.try_get_agent(agent_name)
        if agent is None:
            return False
        if aa.try_agent_version_to_lv(agent, seq) is None:
            return False
    return True


class _DocEvidence:
    __slots__ = ("adverts", "reconciled")

    def __init__(self):
        # peer_id -> (remote_frontier, as_of). Kept per-peer so a stale
        # lease holder's late advert can't clobber the real owner's.
        self.adverts: Dict[str, Tuple[List[List], float]] = {}
        # peer_id -> monotonic floor of completed-reconcile round starts.
        self.reconciled: Dict[str, float] = {}


class FollowerIndex:
    """Tracks, per doc, the owner's advertised frontier and our proven
    catch-up times. Fed by ping gossip and the anti-entropy loop; read
    by :class:`~diamond_types_tpu.read.path.ReadPath` on every follower
    read."""

    def __init__(self, metrics=None):
        self._read_lock = make_lock("read.follower", "io")
        self._docs: Dict[str, _DocEvidence] = {}
        self.metrics = metrics
        # obs.journey.OpJourney hook (wired by ReplicaNode when the
        # server carries an obs bundle): a peer's advert is the final
        # edit-to-visibility stage — a follower read can be served
        self.journey = None

    # ---- evidence feed ---------------------------------------------------

    def note_advert(self, doc_id: str, peer_id: str,
                    frontier: RemoteFrontier,
                    as_of: Optional[float] = None) -> None:
        """Record ``peer_id``'s frontier for ``doc_id`` as of ``as_of``
        (monotonic; defaults to now). Only adverts from the doc's
        current owner count toward staleness — callers record
        everything and ``staleness()`` filters."""
        t = time.monotonic() if as_of is None else as_of
        fr = [[h[0], int(h[1])] for h in frontier]
        with self._read_lock:
            ev = self._docs.setdefault(doc_id, _DocEvidence())
            prev = ev.adverts.get(peer_id)
            if prev is None or prev[1] <= t:
                ev.adverts[peer_id] = (fr, t)
        if self.metrics is not None:
            self.metrics.bump("adverts")
        j = self.journey
        if j is not None:
            # journey closes here: the advert proves the peer reached a
            # frontier at `t` — guarded inside the tracker so it only
            # lands after `applied_at_peer` from the same peer
            j.stamp_doc(doc_id, "advert_usable", peer=peer_id, t=t)

    def note_reconciled(self, doc_id: str, peer_id: str,
                        as_of: Optional[float] = None) -> None:
        """Record a COMPLETED anti-entropy reconcile with ``peer_id``
        whose round started at ``as_of``."""
        t = time.monotonic() if as_of is None else as_of
        with self._read_lock:
            ev = self._docs.setdefault(doc_id, _DocEvidence())
            ev.reconciled[peer_id] = max(ev.reconciled.get(peer_id, 0.0), t)
        if self.metrics is not None:
            self.metrics.bump("reconciles")

    def forget(self, doc_id: str) -> None:
        with self._read_lock:
            self._docs.pop(doc_id, None)

    # ---- queries ---------------------------------------------------------

    def advert_of(self, doc_id: str,
                  owner_id: str) -> Optional[Tuple[List[List], float]]:
        """The owner's latest advertised ``(frontier, as_of)``, if any."""
        with self._read_lock:
            ev = self._docs.get(doc_id)
            if ev is None:
                return None
            return ev.adverts.get(owner_id)

    def staleness(self, doc_id: str, owner_id: str, dominates,
                  now: Optional[float] = None) -> Optional[float]:
        """Tightest provable staleness bound (seconds) for a local read
        of ``doc_id`` whose owner is ``owner_id``, or ``None`` when no
        evidence applies. ``dominates(frontier)`` answers whether the
        local oplog contains the given remote frontier (the caller
        evaluates it under the store's oplog guard)."""
        t = time.monotonic() if now is None else now
        with self._read_lock:
            ev = self._docs.get(doc_id)
            if ev is None:
                return None
            advert = ev.adverts.get(owner_id)
            reconciled = ev.reconciled.get(owner_id)
        best: Optional[float] = reconciled
        if advert is not None:
            fr, as_of = advert
            if (best is None or as_of > best) and dominates(fr):
                best = as_of
        if best is None:
            return None
        return max(0.0, t - best)

    def lag(self, doc_id: str, owner_id: str, dominates) -> Optional[int]:
        """Number of owner-advertised frontier heads the local oplog is
        missing (0 = fully caught up to the last advert). ``None`` when
        the owner has never advertised. ``dominates`` is evaluated per
        single-head frontier, under the caller's oplog guard."""
        advert = self.advert_of(doc_id, owner_id)
        if advert is None:
            return None
        fr, _ = advert
        return sum(0 if dominates([h]) else 1 for h in fr)

    def snapshot(self) -> dict:
        """Debug view: per-doc advert/reconcile peer counts."""
        with self._read_lock:
            return {
                "docs": len(self._docs),
                "adverts": sum(len(e.adverts) for e in self._docs.values()),
                "reconciled": sum(len(e.reconciled)
                                  for e in self._docs.values()),
            }
