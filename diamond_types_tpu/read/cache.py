"""Bounded LRU of materialized checkouts, keyed ``(doc, frontier)``.

Shared by every read endpoint on a node (text and state GETs hit the
same entries — the cached value is the checkout text; endpoints dress it
differently). Invalidated by flush completion on owners and by
anti-entropy apply on followers; because the key includes the frontier,
invalidation is a freshness/footprint concern, never a correctness one —
a stale entry can only be returned for the exact frontier it encodes.

Single-flight: a read flash-crowd on one hot ``(doc, frontier)``
materializes the checkout ONCE. The first miss becomes the leader and
materializes OUTSIDE the cache guard (``materialize`` re-enters the
store's oplog guard, which is a lower rung than this cache's io guard —
holding the cache guard across it would invert the canonical lock
order); followers block on the flight's event and reuse the result.
"""

from collections import OrderedDict
from typing import Callable, Optional, Tuple

import threading

from ..analysis import make_lock

FrontierKey = Tuple[Tuple[str, int], ...]


def frontier_key(frontier) -> FrontierKey:
    """Canonical hashable form of a remote frontier ([[agent, seq]...])."""
    return tuple(sorted((h[0], int(h[1])) for h in frontier))


class _Flight:
    __slots__ = ("done", "value", "error")

    def __init__(self):
        self.done = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None


class CheckoutCache:
    """LRU + single-flight for checkout materializations.

    ``get`` returns ``(value, outcome)`` with outcome one of ``"hit"``,
    ``"miss"`` (this caller materialized), ``"coalesced"`` (another
    caller's in-flight materialization was reused) or ``"timeout"``
    (the leader took too long; this caller materialized independently
    without caching — the flash-crowd degrades, it never deadlocks).
    """

    def __init__(self, capacity: int = 256, metrics=None,
                 flight_timeout_s: float = 5.0):
        self.capacity = max(1, int(capacity))
        self.flight_timeout_s = flight_timeout_s
        self.metrics = metrics
        self._cache_lock = make_lock("read.cache", "io")
        self._entries: "OrderedDict[Tuple[str, FrontierKey], object]" = \
            OrderedDict()
        self._flights = {}

    def _bump(self, key: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.bump(key, n)

    # ---- read path -------------------------------------------------------

    def get(self, doc_id: str, fkey: FrontierKey,
            materialize: Callable[[], object]):
        key = (doc_id, fkey)
        with self._cache_lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self._bump("cache_hits")
                return self._entries[key], "hit"
            flight = self._flights.get(key)
            if flight is None:
                flight = _Flight()
                self._flights[key] = flight
                leader = True
            else:
                leader = False
        if not leader:
            if flight.done.wait(self.flight_timeout_s) \
                    and flight.error is None:
                self._bump("cache_coalesced")
                return flight.value, "coalesced"
            # Leader failed or is wedged: materialize for ourselves,
            # skipping the cache (the leader owns the flight slot).
            self._bump("cache_wait_timeouts")
            return materialize(), "timeout"
        try:
            value = materialize()
        except BaseException as e:
            flight.error = e
            with self._cache_lock:
                self._flights.pop(key, None)
            flight.done.set()
            raise
        flight.value = value
        evicted = 0
        with self._cache_lock:
            self._flights.pop(key, None)
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                evicted += 1
        flight.done.set()
        self._bump("cache_misses")
        if evicted:
            self._bump("cache_evictions", evicted)
        return value, "miss"

    # ---- lifecycle -------------------------------------------------------

    def invalidate(self, doc_id: str) -> int:
        """Drop every cached frontier of ``doc_id``; returns the count."""
        with self._cache_lock:
            victims = [k for k in self._entries if k[0] == doc_id]
            for k in victims:
                del self._entries[k]
        if victims:
            self._bump("invalidated_entries", len(victims))
        return len(victims)

    def clear(self) -> None:
        with self._cache_lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._cache_lock:
            return len(self._entries)
