"""Subgraph projection: restrict the time DAG to a filtered set of LVs.

Capability mirror of the reference's subgraph tools (reference:
src/causalgraph/graph/subgraph.rs:39-242 — `subgraph`, `project_onto_subgraph`):
build a mini-DAG containing only the ops touching one CRDT/item, remapping
frontiers into it. Key for multi-CRDT documents and for bounding merge work.

Different construction from the reference (which interleaves a reverse filter
iterator with the priority-queue walk): here projection collects "maximal
filtered ancestor" candidates with a run-granular walk and finishes with an
exact find_dominators pass; the subgraph builder then projects each filtered
piece's parents independently. Simpler, and verified against a brute-force
ancestor-closure oracle on random DAGs.
"""

from __future__ import annotations

from bisect import bisect_right
from typing import List, Sequence, Tuple

import heapq

from ..core.span import Span
from .graph import Graph, ROOT


def _clip_filter(filter_spans: Sequence[Span], cap: int) -> List[Span]:
    """Ascending filter spans clipped to LVs < cap."""
    out = []
    for (a, b) in filter_spans:
        if a >= cap:
            break
        out.append((a, min(b, cap)))
    return out


def _max_filtered_le(filter_spans: Sequence[Span], lo: int, hi: int) -> int:
    """Highest filtered LV in [lo, hi], or ROOT."""
    i = bisect_right(filter_spans, hi, key=lambda s: s[0]) - 1
    while i >= 0:
        a, b = filter_spans[i]
        if b <= lo:
            return ROOT
        v = min(hi, b - 1)
        if v >= max(lo, a):
            return v
        i -= 1
    return ROOT


def project_onto_subgraph(graph: Graph, filter_spans: Sequence[Span],
                          frontier: Sequence[int]) -> List[int]:
    """Map `frontier` to its image in the filtered subgraph: the dominator set
    of the newest filtered LVs in its history (reference: subgraph.rs:236-242).
    `filter_spans` must be ascending and disjoint."""
    if not frontier:
        return []
    filter_spans = list(filter_spans)
    if not filter_spans:
        return []
    fmin = filter_spans[0][0]
    heap = [-v for v in frontier]
    heapq.heapify(heap)
    candidates = set()
    while heap:
        v = -heapq.heappop(heap)
        if v < fmin:
            continue
        i = graph.find_idx(v)
        start = graph.starts[i]
        # Skip same-run queue entries (their histories are covered).
        while heap and -heap[0] >= start:
            heapq.heappop(heap)
        f = _max_filtered_le(filter_spans, start, v)
        if f != ROOT:
            candidates.add(f)
        else:
            for p in graph.parents[i]:
                heapq.heappush(heap, -p)
    return graph.find_dominators(sorted(candidates))


def subgraph(graph: Graph, filter_spans: Sequence[Span],
             parents: Sequence[int]) -> Tuple[Graph, List[int]]:
    """Build the filtered mini-DAG (original LV numbering preserved) plus the
    projection of `parents` into it (reference: subgraph.rs:39-236).

    The result graph contains exactly the LVs of `filter_spans` (clipped to
    the history of `parents`); each piece's parents are the projections of
    its original parents onto the earlier filtered set.
    """
    filter_spans = list(filter_spans)
    out = Graph()

    # Restrict the filter to the history of `parents`.
    kept: List[Span] = []
    for (a, b) in filter_spans:
        pos = a
        while pos < b:
            i = graph.find_idx(pos)
            hi = min(b, graph.ends[i])
            # Run pieces outside parents' history get dropped.
            last = hi - 1
            if graph.frontier_contains_version(parents, last):
                kept.append((pos, hi))
            else:
                # The prefix of the piece may still be contained.
                lo_ok = pos - 1
                lo, hi2 = pos, last
                while lo <= hi2:
                    mid = (lo + hi2) // 2
                    if graph.frontier_contains_version(parents, mid):
                        lo_ok = mid
                        lo = mid + 1
                    else:
                        hi2 = mid - 1
                if lo_ok >= pos:
                    kept.append((pos, lo_ok + 1))
            pos = hi

    for (a, b) in kept:
        pos = a
        while pos < b:
            i = graph.find_idx(pos)
            hi = min(b, graph.ends[i])
            orig_parents = graph.parents_at(pos)
            proj = project_onto_subgraph(
                graph, _clip_filter(kept, pos), orig_parents)
            out.push(proj, pos, hi)
            pos = hi

    return out, project_onto_subgraph(graph, kept, parents)
