"""Bidirectional (agent, seq) <-> LV mapping.

Redesign of the reference's AgentAssignment (reference:
src/causalgraph/agent_assignment/mod.rs:10-45): per-agent RLE runs of seqs
mapped to LV spans, plus a global LV-ordered column of (agent, seq_start)
runs. Both sides are append-mostly sorted RLE vectors searched by bisect.
"""

from __future__ import annotations

from bisect import bisect_right, insort
from typing import Dict, List, Optional, Tuple

AgentId = int
AgentVersion = Tuple[AgentId, int]  # (agent, seq)


class AgentAssignment:
    __slots__ = ("agent_names", "_name_to_id", "client_runs", "global_runs")

    def __init__(self) -> None:
        self.agent_names: List[str] = []
        self._name_to_id: Dict[str, AgentId] = {}
        # Per agent: sorted list of (seq_start, seq_end, lv_start). May be
        # inserted into out-of-order (remote peers can deliver seq runs in any
        # order), hence insort rather than append-only.
        self.client_runs: List[List[Tuple[int, int, int]]] = []
        # Global, LV-ordered, packed: (lv_start, lv_end, agent, seq_start).
        self.global_runs: List[Tuple[int, int, int, int]] = []

    # --- agents ----------------------------------------------------------

    def get_or_create_agent(self, name: str) -> AgentId:
        aid = self._name_to_id.get(name)
        if aid is None:
            aid = len(self.agent_names)
            self.agent_names.append(name)
            self._name_to_id[name] = aid
            self.client_runs.append([])
        return aid

    def try_get_agent(self, name: str) -> Optional[AgentId]:
        return self._name_to_id.get(name)

    def get_agent_name(self, agent: AgentId) -> str:
        return self.agent_names[agent]

    def next_seq_for(self, agent: AgentId) -> int:
        runs = self.client_runs[agent]
        return runs[-1][1] if runs else 0

    def len_lv(self) -> int:
        return self.global_runs[-1][1] if self.global_runs else 0

    # --- assignment -------------------------------------------------------

    def assign_span(self, agent: AgentId, seq_start: int, lv_start: int, n: int) -> None:
        """Record that LVs [lv_start, lv_start+n) are (agent, seq_start..+n)."""
        assert n > 0
        runs = self.client_runs[agent]
        if (runs and runs[-1][1] == seq_start
                and runs[-1][2] + (runs[-1][1] - runs[-1][0]) == lv_start):
            runs[-1] = (runs[-1][0], seq_start + n, runs[-1][2])
        elif runs and seq_start < runs[-1][1]:
            # Out-of-order seq delivery: keep the per-client list sorted.
            insort(runs, (seq_start, seq_start + n, lv_start))
        else:
            runs.append((seq_start, seq_start + n, lv_start))

        g = self.global_runs
        if (g and g[-1][1] == lv_start and g[-1][2] == agent
                and g[-1][3] + (g[-1][1] - g[-1][0]) == seq_start):
            g[-1] = (g[-1][0], lv_start + n, agent, g[-1][3])
        else:
            assert not g or lv_start == g[-1][1], "LVs must be assigned densely"
            g.append((lv_start, lv_start + n, agent, seq_start))

    # --- queries ----------------------------------------------------------

    def local_to_agent_version(self, lv: int) -> AgentVersion:
        lo, hi, agent, seq0 = self._find_global(lv)
        return (agent, seq0 + (lv - lo))

    def local_span_to_agent_span(self, lv: int, max_len: int) -> Tuple[AgentId, int, int]:
        """Returns (agent, seq_start, run_len<=max_len) for the run at `lv`."""
        lo, hi, agent, seq0 = self._find_global(lv)
        n = min(hi - lv, max_len)
        return agent, seq0 + (lv - lo), n

    def _find_global(self, lv: int) -> Tuple[int, int, int, int]:
        i = bisect_right(self.global_runs, lv, key=lambda r: r[0]) - 1
        if i < 0 or lv >= self.global_runs[i][1]:
            raise KeyError(f"LV {lv} unassigned")
        return self.global_runs[i]

    def try_agent_version_to_lv(self, agent: AgentId, seq: int) -> Optional[int]:
        if agent >= len(self.client_runs):
            return None
        runs = self.client_runs[agent]
        i = bisect_right(runs, seq, key=lambda r: r[0]) - 1
        if i < 0 or seq >= runs[i][1]:
            return None
        s0, _s1, lv0 = runs[i]
        return lv0 + (seq - s0)

    def agent_version_to_lv(self, agent: AgentId, seq: int) -> int:
        lv = self.try_agent_version_to_lv(agent, seq)
        if lv is None:
            raise KeyError(f"(agent {agent}, seq {seq}) unknown")
        return lv

    def seq_run_known_len(self, agent: AgentId, seq: int) -> int:
        """How many seqs from `seq` onward map to contiguous LVs."""
        runs = self.client_runs[agent]
        i = bisect_right(runs, seq, key=lambda r: r[0]) - 1
        assert i >= 0 and seq < runs[i][1]
        return runs[i][1] - seq

    def tie_break_agent_versions(self, a: AgentVersion, b: AgentVersion) -> int:
        """Deterministic ordering for fully concurrent versions: by agent name,
        then seq (reference: agent_assignment/mod.rs:163)."""
        if a == b:
            return 0
        na, nb = self.agent_names[a[0]], self.agent_names[b[0]]
        k = (na, a[1])
        j = (nb, b[1])
        return -1 if k < j else 1
