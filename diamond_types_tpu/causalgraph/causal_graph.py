"""CausalGraph facade: agent assignment + parents graph + current version.

Capability mirror of the reference CausalGraph (reference:
src/causalgraph/mod.rs:21-34, causalgraph.rs:65-201), including the 3-case
partial-overlap dedup in `merge_and_assign` that makes patch ingestion
idempotent and order-tolerant.
"""

from __future__ import annotations

from bisect import bisect_right, insort
from typing import List, Optional, Sequence, Tuple

from ..core.frontier import Frontier, replace_with_1
from ..core.span import Span
from .agent import AgentAssignment, AgentId
from .graph import Graph, ROOT


class CausalGraph:
    __slots__ = ("agent_assignment", "graph", "version")

    def __init__(self) -> None:
        self.agent_assignment = AgentAssignment()
        self.graph = Graph()
        self.version: Frontier = []

    def __len__(self) -> int:
        return self.graph.next_lv()

    def get_or_create_agent(self, name: str) -> AgentId:
        return self.agent_assignment.get_or_create_agent(name)

    # --- local append path ------------------------------------------------

    def assign_local_op(self, agent: AgentId, num: int) -> Span:
        """Append `num` new LVs by `agent` with the current version as parent
        (reference: causalgraph.rs:82-93)."""
        return self.assign_local_op_with_parents(list(self.version), agent, num)

    def assign_local_op_with_parents(self, parents: Sequence[int], agent: AgentId,
                                     num: int) -> Span:
        start = len(self)
        seq = self.agent_assignment.next_seq_for(agent)
        self.agent_assignment.assign_span(agent, seq, start, num)
        self.graph.push(parents, start, start + num)
        self.graph._advance_known_run(self.version, parents, (start, start + num))
        return (start, start + num)

    # --- remote merge path --------------------------------------------------

    def merge_and_assign(self, parents: Sequence[int], agent: AgentId,
                         seq_start: int, n: int) -> Span:
        """Merge a remote run (agent, seq_start..+n) whose first op has
        `parents`. Returns the *newly added* LV span, which is empty/truncated
        when ops are already known (reference: causalgraph.rs:132-201).
        """
        time_start = len(self)
        aa = self.agent_assignment
        runs = aa.client_runs[agent]
        seq_last = seq_start + n - 1

        # Case 1: last seq already known => whole span already known.
        i = bisect_right(runs, seq_last, key=lambda r: r[0]) - 1
        if i >= 0 and seq_last < runs[i][1]:
            return (time_start, time_start)

        # idx = insertion point for this new run in the per-client RLE list.
        idx = bisect_right(runs, seq_start, key=lambda r: r[0])
        if idx >= 1:
            ps0, ps1, plv = runs[idx - 1]
            if ps1 >= seq_start:
                # Case 3: overlap at the head. Trim to the unknown tail.
                actual_len = (seq_start + n) - ps1
                time_span = (time_start, time_start + actual_len)
                if ps1 > seq_start:
                    # Overlapping head: the tail's parent is the last known LV
                    # of the previous run.
                    eff_parents: Sequence[int] = [plv + (ps1 - ps0) - 1]
                else:
                    eff_parents = parents
                self.graph.push(eff_parents, *time_span)
                self.graph._advance_known_run(self.version, eff_parents, time_span)
                # Extend the client run & global column.
                if plv + (ps1 - ps0) == time_start:
                    runs[idx - 1] = (ps0, seq_start + n, plv)
                else:
                    insort(runs, (ps1, seq_start + n, time_start))
                aa.global_runs.append((time_start, time_start + actual_len, agent, ps1))
                return time_span

        # Case 2: fully new.
        time_span = (time_start, time_start + n)
        insort(runs, (seq_start, seq_start + n, time_start))
        g = aa.global_runs
        if (g and g[-1][1] == time_start and g[-1][2] == agent
                and g[-1][3] + (g[-1][1] - g[-1][0]) == seq_start):
            g[-1] = (g[-1][0], time_start + n, agent, g[-1][3])
        else:
            g.append((time_start, time_start + n, agent, seq_start))
        self.graph.push(parents, *time_span)
        self.graph._advance_known_run(self.version, parents, time_span)
        return time_span

    # --- wire-safe version naming ------------------------------------------

    def local_to_remote_frontier(self, f: Sequence[int]) -> List[Tuple[str, int]]:
        """Frontier as [(agent_name, seq)] (reference: remote_ids.rs:17-207)."""
        out = []
        for lv in f:
            agent, seq = self.agent_assignment.local_to_agent_version(lv)
            out.append((self.agent_assignment.get_agent_name(agent), seq))
        return out

    def remote_to_local_frontier(self, rf: Sequence[Tuple[str, int]]) -> Frontier:
        out = []
        for name, seq in rf:
            agent = self.agent_assignment.try_get_agent(name)
            if agent is None:
                raise KeyError(f"unknown agent {name!r}")
            out.append(self.agent_assignment.agent_version_to_lv(agent, seq))
        return sorted(out)

    # --- iteration -----------------------------------------------------------

    def iter_entries(self):
        """Yield (lv_start, lv_end, parents, agent, seq_start) runs, splitting
        on both graph-run and agent-run boundaries (reference:
        causalgraph.rs:208-222 rle_zip)."""
        g = self.graph
        for gi in range(len(g)):
            lo, hi = g.starts[gi], g.ends[gi]
            pos = lo
            while pos < hi:
                agent, seq, n = self.agent_assignment.local_span_to_agent_span(
                    pos, hi - pos)
                parents = g.parents[gi] if pos == lo else (pos - 1,)
                yield (pos, pos + n, parents, agent, seq)
                pos += n
