"""The causal graph ("time DAG") and its query toolkit.

Columnar redesign of the reference's parents store + DAG algorithms
(reference: src/causalgraph/graph/mod.rs:26-53, src/causalgraph/graph/tools.rs).
Entries are runs of LVs `[start, end)` whose parents are implicit-linear inside
the run; each run stores the parents of its first LV, plus a `shadow`: the
earliest LV such that the whole run transitively descends from every LV in
`[shadow, start)` — the dominator-skip optimization the reference relies on
(reference: src/causalgraph/graph/mod.rs:29-31).

Storage is struct-of-arrays (parallel Python lists; numpy export via
`as_arrays()`) so the same layout ships to the JAX device tier as dense
CSR-style adjacency (see diamond_types_tpu.tpu).

ROOT is represented as -1 so natural integer ordering sorts it below every
real LV (the reference uses usize::MAX plus wrapping tricks; -1 needs none).
"""

from __future__ import annotations

import heapq
from bisect import bisect_right
from enum import IntEnum
from typing import Callable, List, Optional, Sequence, Tuple

from ..core.frontier import (
    Frontier, frontier_from, insert_nonoverlapping, replace_with_1,
)
from ..core.span import Span, push_reversed_rle, span_is_empty

ROOT = -1


class DiffFlag(IntEnum):
    ONLY_A = 0
    ONLY_B = 1
    SHARED = 2


class Graph:
    """RLE time-DAG. Mirrors capability of reference Graph (graph/mod.rs:47-53)."""

    __slots__ = ("starts", "ends", "shadows", "parents", "child_idxs",
                 "root_child_idxs")

    def __init__(self) -> None:
        self.starts: List[int] = []
        self.ends: List[int] = []
        self.shadows: List[int] = []
        self.parents: List[Tuple[int, ...]] = []
        self.child_idxs: List[List[int]] = []
        self.root_child_idxs: List[int] = []

    # --- construction ---------------------------------------------------

    def __len__(self) -> int:
        return len(self.starts)

    def next_lv(self) -> int:
        return self.ends[-1] if self.ends else 0

    def push(self, parents: Sequence[int], start: int, end: int) -> None:
        """Append a run of LVs `[start, end)` with `parents` for the first LV.

        Extends the previous run when the history is linear (reference:
        graph/mod.rs:85-96 fast path), otherwise computes the shadow and wires
        child indexes.
        """
        assert end > start
        if self.starts:
            last = len(self.starts) - 1
            if (len(parents) == 1 and parents[0] == self.ends[last] - 1
                    and self.ends[last] == start):
                self.ends[last] = end
                return

        # Shadow: walk down while our immediate predecessor LV is a parent.
        shadow = start
        pset = tuple(parents)
        while shadow >= 1 and (shadow - 1) in pset:
            shadow = self.shadows[self.find_idx(shadow - 1)]

        new_idx = len(self.starts)
        if not parents:
            self.root_child_idxs.append(new_idx)
        else:
            for p in pset:
                self.child_idxs[self.find_idx(p)].append(new_idx)

        self.starts.append(start)
        self.ends.append(end)
        self.shadows.append(shadow)
        self.parents.append(tuple(sorted(pset)))
        self.child_idxs.append([])

    # --- lookup ---------------------------------------------------------

    def find_idx(self, v: int) -> int:
        """Index of the run containing LV `v`."""
        i = bisect_right(self.starts, v) - 1
        if i < 0 or v >= self.ends[i]:
            raise KeyError(f"LV {v} not in graph")
        return i

    def parents_at(self, v: int) -> Tuple[int, ...]:
        """Parents of a single LV (implicit v-1 inside a run)."""
        i = self.find_idx(v)
        if v > self.starts[i]:
            return (v - 1,)
        return self.parents[i]

    def entry_span(self, idx: int) -> Span:
        return (self.starts[idx], self.ends[idx])

    def _entry_contains(self, idx: int, v: int) -> bool:
        return self.starts[idx] <= v < self.ends[idx]

    def _is_direct_descendant_coarse(self, a: int, b: int) -> bool:
        # reference: graph/tools.rs:52-59
        if a == b:
            return True
        if b == ROOT:
            return True
        return a > b and self._entry_contains(self.find_idx(a), b)

    # --- containment ----------------------------------------------------

    def frontier_contains_version(self, frontier: Sequence[int], target: int) -> bool:
        """Does `frontier` dominate LV `target`? (reference: graph/tools.rs:88-146)."""
        if target == ROOT:
            return True
        if target in frontier:
            return True
        if not frontier:
            return False

        # Fast path via shadows.
        for o in frontier:
            if o > target:
                i = self.find_idx(o)
                if self.shadows[i] <= target:
                    return True

        heap: List[int] = [-o for o in frontier if o > target]
        heapq.heapify(heap)
        while heap:
            order = -heapq.heappop(heap)
            i = self.find_idx(order)
            if self.shadows[i] <= target:
                return True
            start = self.starts[i]
            while heap and -heap[0] >= start:
                heapq.heappop(heap)
            for p in self.parents[i]:
                if p == target:
                    return True
                elif p > target:
                    heapq.heappush(heap, -p)
        return False

    def frontier_contains_frontier(self, a: Sequence[int], b: Sequence[int]) -> bool:
        if list(a) == list(b):
            return True
        return all(self.frontier_contains_version(a, bb) for bb in b)

    def version_cmp(self, v1: int, v2: int) -> Optional[int]:
        """-1 if v1 < v2 (v2 dominates), 0 equal, 1 if v1 > v2; None concurrent."""
        if v1 == v2:
            return 0
        if v1 < v2:
            return -1 if self.frontier_contains_version([v2], v1) else None
        return 1 if self.frontier_contains_version([v1], v2) else None

    # --- diff -----------------------------------------------------------

    def diff(self, a: Sequence[int], b: Sequence[int]) -> Tuple[List[Span], List[Span]]:
        """(spans only in a's history, spans only in b's) ascending order."""
        only_a, only_b = self.diff_rev(a, b)
        return only_a[::-1], only_b[::-1]

    def diff_rev(self, a: Sequence[int], b: Sequence[int]) -> Tuple[List[Span], List[Span]]:
        # Fast paths (reference: graph/tools.rs:176-203)
        if list(a) == list(b):
            return [], []
        if len(a) == 1 and len(b) == 1:
            aa, bb = a[0], b[0]
            if self._is_direct_descendant_coarse(aa, bb):
                return [(bb + 1, aa + 1)], []
            if self._is_direct_descendant_coarse(bb, aa):
                return [], [(aa + 1, bb + 1)]
        return self._diff_slow(a, b)

    def _diff_slow(self, a: Sequence[int], b: Sequence[int]) -> Tuple[List[Span], List[Span]]:
        only_a: List[Span] = []
        only_b: List[Span] = []

        def mark(lo: int, hi: int, flag: DiffFlag) -> None:
            # marks [lo, hi] inclusive
            if flag == DiffFlag.SHARED:
                return
            out = only_a if flag == DiffFlag.ONLY_A else only_b
            push_reversed_rle(out, (lo, hi + 1))

        self._diff_slow_internal(a, b, mark)
        return only_a, only_b

    def _diff_slow_internal(self, a: Sequence[int], b: Sequence[int],
                            mark: Callable[[int, int, DiffFlag], None]) -> None:
        # Two-color max-heap walk (reference: graph/tools.rs:225-292).
        heap: List[Tuple[int, int]] = []  # (-lv, flag)
        for v in a:
            heap.append((-v, DiffFlag.ONLY_A))
        for v in b:
            heap.append((-v, DiffFlag.ONLY_B))
        heapq.heapify(heap)
        num_shared = 0

        while heap:
            nord, flag = heapq.heappop(heap)
            ord_ = -nord
            if flag == DiffFlag.SHARED:
                num_shared -= 1

            # Merge duplicate heads.
            while heap and -heap[0][0] == ord_:
                _, pf = heapq.heappop(heap)
                if pf != flag:
                    flag = DiffFlag.SHARED
                if pf == DiffFlag.SHARED:
                    num_shared -= 1

            i = self.find_idx(ord_)
            start = self.starts[i]

            # Consume heads that fall inside this same run.
            while heap and -heap[0][0] >= start:
                peek_ord = -heap[0][0]
                peek_flag = heap[0][1]
                if peek_flag != flag:
                    mark(peek_ord + 1, ord_, flag)
                    ord_ = peek_ord
                    flag = DiffFlag.SHARED
                if peek_flag == DiffFlag.SHARED:
                    num_shared -= 1
                heapq.heappop(heap)

            mark(start, ord_, flag)

            for p in self.parents[i]:
                heapq.heappush(heap, (-p, flag))
                if flag == DiffFlag.SHARED:
                    num_shared += 1

            if len(heap) == num_shared:
                break

    # --- conflicts ------------------------------------------------------

    def find_conflicting(self, a: Sequence[int], b: Sequence[int],
                         visit: Callable[[Span, DiffFlag], None]) -> Frontier:
        """Visit spans (in reverse LV order) reachable from `a` or `b` but not
        their common ancestor; returns the common ancestor frontier
        (reference: graph/tools.rs:454-484).
        """
        if list(a) == list(b):
            return list(a)
        if len(a) == 1 and len(b) == 1:
            aa, bb = a[0], b[0]
            if self._is_direct_descendant_coarse(aa, bb):
                visit((bb + 1, aa + 1), DiffFlag.ONLY_A)
                return [bb] if bb != ROOT else []
            if self._is_direct_descendant_coarse(bb, aa):
                visit((aa + 1, bb + 1), DiffFlag.ONLY_B)
                return [aa] if aa != ROOT else []
        return self._find_conflicting_slow(a, b, visit)

    def _find_conflicting_slow(self, a: Sequence[int], b: Sequence[int],
                               visit: Callable[[Span, DiffFlag], None]) -> Frontier:
        # Time points: (last, merged_with). Max-heap: highest `last` first; among
        # equal `last`, fewest merged_with first (reference: graph/tools.rs:296-445).
        def tp(front: Sequence[int]) -> Tuple[int, Tuple[int, ...]]:
            f = list(front)
            if not f:
                return (ROOT, ())
            return (f[-1], tuple(f[:-1]))

        def key(t: Tuple[int, Tuple[int, ...]]) -> Tuple[int, int, Tuple[int, ...]]:
            return (-t[0], len(t[1]), t[1])

        heap: List[Tuple[Tuple[int, int, Tuple[int, ...]],
                         Tuple[int, Tuple[int, ...]], int]] = []
        heapq.heappush(heap, (key(tp(a)), tp(a), DiffFlag.ONLY_A))
        heapq.heappush(heap, (key(tp(b)), tp(b), DiffFlag.ONLY_B))

        while True:
            _, time, flag = heapq.heappop(heap)
            t = time[0]

            if t == ROOT:
                return []

            # Merge duplicate whole time points.
            while heap and heap[0][1] == time:
                _, _, pf = heapq.heappop(heap)
                if pf != flag:
                    flag = DiffFlag.SHARED

            if not heap:
                frontier = list(time[1]) + [t]
                return frontier

            # Shatter merge points.
            if time[1]:
                for t2 in time[1]:
                    e = (t2, ())
                    heapq.heappush(heap, (key(e), e, flag))

            i = self.find_idx(t)
            rng: Span = (self.starts[i], t + 1)

            while True:
                if heap:
                    peek_time = heap[0][1]
                    if peek_time[0] != ROOT and peek_time[0] >= self.starts[i]:
                        _, time2, next_flag = heapq.heappop(heap)
                        if time2[0] + 1 < rng[1]:
                            offset = time2[0] + 1 - self.starts[i]
                            rem = (rng[0] + offset, rng[1])
                            rng = (rng[0], rng[0] + offset)
                            visit(rem, flag)
                        if time2[1]:
                            for t2 in time2[1]:
                                e = (t2, ())
                                heapq.heappush(heap, (key(e), e, next_flag))
                        if next_flag != flag:
                            flag = DiffFlag.SHARED
                    else:
                        visit(rng, flag)
                        e = tp(self.parents[i])
                        heapq.heappush(heap, (key(e), e, flag))
                        break
                else:
                    return [rng[1] - 1]

    def find_conflicting_simple(self, a: Sequence[int], b: Sequence[int]):
        """Returns (common_ancestor_frontier, rev_spans)."""
        rev_spans: List[Span] = []
        common = self.find_conflicting(a, b, lambda s, f: push_reversed_rle(rev_spans, s))
        return common, rev_spans

    # --- dominators -----------------------------------------------------

    def _find_dominators_full_internal(self, versions: Sequence[int],
                                       stop_at_shadow: Optional[int],
                                       visit: Callable[[int, bool], None]) -> None:
        # reference: graph/tools.rs:580-651. Inputs encoded with LSB=0 so the
        # "normal" (descendant-reached) copy of an LV pops before the input copy.
        if len(versions) <= 1:
            for v in versions:
                visit(v, True)
            return

        def enc_input(v: int) -> int:
            return v << 1

        def enc_normal(v: int) -> int:
            return (v << 1) + 1

        heap = [-enc_input(v) for v in versions]
        heapq.heapify(heap)
        inputs_remaining = len(heap)
        last_emitted: Optional[int] = None

        while heap:
            v_enc = -heapq.heappop(heap)
            is_input, v = (v_enc % 2 == 0), v_enc >> 1

            if is_input:
                visit(v, True)
                last_emitted = v
                inputs_remaining -= 1

            i = self.find_idx(v)
            if stop_at_shadow is not None and self.shadows[i] <= stop_at_shadow:
                break

            start = self.starts[i]
            while heap:
                v2_enc = -heap[0]
                is_input2, v2 = (v2_enc % 2 == 0), v2_enc >> 1
                if v2 < start:
                    break
                heapq.heappop(heap)
                if is_input2:
                    if last_emitted != v2:
                        visit(v2, False)
                        last_emitted = v2
                    inputs_remaining -= 1
            if inputs_remaining == 0:
                break
            for p in self.parents[i]:
                if p != ROOT:
                    heapq.heappush(heap, -enc_normal(p))

    def find_dominators(self, versions: Sequence[int]) -> Frontier:
        versions = sorted(versions)
        if len(versions) <= 1:
            return list(versions)
        min_v, max_v = versions[0], versions[-1]
        i = self.find_idx(max_v)
        if self.shadows[i] <= min_v:
            return [max_v]
        out: List[int] = []
        self._find_dominators_full_internal(
            versions, min_v, lambda v, dom: out.append(v) if dom else None)
        return out[::-1]

    def find_dominators_2(self, v1: Sequence[int], v2: Sequence[int]) -> Frontier:
        """Union of two frontiers that are each already dominator sets
        (reference: graph/tools.rs:545-578)."""
        if not v1:
            return list(v2)
        if not v2:
            return list(v1)
        if len(v1) == 1 and len(v2) == 1:
            a, b = v1[0], v2[0]
            c = self.version_cmp(a, b)
            if c is None:
                return sorted((a, b))
            return [a] if c > 0 else [b]
        first_v = min(v1[0], v2[0])
        out: List[int] = []
        self._find_dominators_full_internal(
            list(v1) + list(v2), first_v,
            lambda v, dom: out.append(v) if dom else None)
        return out[::-1]

    def version_union(self, a: Sequence[int], b: Sequence[int]) -> Frontier:
        out: List[int] = []
        self._find_dominators_full_internal(
            list(a) + list(b), None,
            lambda v, dom: out.append(v) if dom else None)
        return out[::-1]

    # --- frontier movement ----------------------------------------------

    def advance_frontier(self, f: Frontier, rng: Span) -> None:
        """Advance `f` in place across a (fully applied) range of LVs
        (reference: src/frontier.rs:199-214)."""
        start, end = rng
        i = self.find_idx(start)
        while True:
            e_end = min(self.ends[i], end)
            parents = self.parents_at(start)
            self._advance_known_run(f, parents, (start, e_end))
            if e_end >= end:
                break
            start = e_end
            i += 1

    def _advance_known_run(self, f: Frontier, parents: Sequence[int], span: Span) -> None:
        # reference: src/frontier.rs:251-281
        last = span[1] - 1
        if len(parents) == 1 and len(f) == 1 and parents[0] == f[0]:
            f[0] = last
        elif list(f) == list(parents):
            replace_with_1(f, last)
        else:
            pset = set(parents)
            f[:] = [o for o in f if o not in pset]
            insert_nonoverlapping(f, last)

    def retreat_frontier(self, f: Frontier, rng: Span) -> None:
        """Undo a range of LVs from frontier `f` (reference: src/frontier.rs:290-340)."""
        if span_is_empty(rng):
            return
        start, end = rng
        i = self.find_idx(end - 1)
        while True:
            last_order = end - 1
            t_start = self.starts[i]
            if len(f) == 1:
                if start > t_start:
                    f[0] = start - 1
                    break
                f[:] = list(self.parents[i])
            else:
                f[:] = [t for t in f if t != last_order]
                for parent in self.parents_at(max(start, t_start)):
                    if not self.frontier_contains_version(f, parent):
                        insert_nonoverlapping(f, parent)

            if start >= t_start:
                break
            end = t_start
            i -= 1

    # --- export for the device tier --------------------------------------

    def as_arrays(self):
        """Columnar export: (starts, ends, shadows, parent_idx CSR) as numpy."""
        import numpy as np
        starts = np.asarray(self.starts, dtype=np.int64)
        ends = np.asarray(self.ends, dtype=np.int64)
        shadows = np.asarray(self.shadows, dtype=np.int64)
        indptr = np.zeros(len(self.parents) + 1, dtype=np.int64)
        flat: List[int] = []
        for j, ps in enumerate(self.parents):
            flat.extend(ps)
            indptr[j + 1] = len(flat)
        return starts, ends, shadows, indptr, np.asarray(flat, dtype=np.int64)
