"""Peer-sync handshake: version summaries.

Capability mirror of the reference's summary.rs (reference:
src/causalgraph/summary.rs:13-29, 119-234): a VersionSummary names, per agent,
the seq ranges a peer knows. Intersecting a remote summary with the local
causal graph yields (a) the common version frontier — the point to encode a
patch from — and (b) a remainder summary of ops the remote has that we lack.

Wire shape is plain JSON: {"agent": [[s0, e0], [s1, e1], ...], ...} (matching
the reference's serde encoding), so any transport works.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..core.span import merge_spans
from .causal_graph import CausalGraph

VersionSummary = Dict[str, List[List[int]]]
VersionSummaryFlat = Dict[str, int]


def summarize_versions(cg: CausalGraph) -> VersionSummary:
    """reference: summary.rs:119-132."""
    out: VersionSummary = {}
    aa = cg.agent_assignment
    for agent, runs in enumerate(aa.client_runs):
        if not runs:
            continue
        spans = merge_spans((s0, s1) for (s0, s1, _lv) in runs)
        out[aa.get_agent_name(agent)] = [[a, b] for (a, b) in spans]
    return out


def summarize_versions_flat(cg: CausalGraph) -> VersionSummaryFlat:
    """reference: summary.rs:134-139."""
    out: VersionSummaryFlat = {}
    aa = cg.agent_assignment
    for agent, runs in enumerate(aa.client_runs):
        if runs:
            out[aa.get_agent_name(agent)] = runs[-1][1]
    return out


def intersect_with_summary(cg: CausalGraph, summary: VersionSummary,
                           frontier: Sequence[int] = ()
                           ) -> Tuple[List[int], Optional[VersionSummary]]:
    """Returns (common_frontier, remainder_summary|None)
    (reference: summary.rs:234 intersect_with_summary)."""
    aa = cg.agent_assignment
    versions: List[int] = list(frontier)
    remainder: VersionSummary = {}

    for name, seq_ranges in summary.items():
        agent = aa.try_get_agent(name)
        if agent is None:
            remainder[name] = [list(r) for r in seq_ranges]
            continue
        runs = aa.client_runs[agent]
        for (want0, want1) in seq_ranges:
            expect_next = want0
            for (s0, s1, lv0) in runs:
                lo, hi = max(s0, want0), min(s1, want1)
                if hi <= lo:
                    continue
                if lo > expect_next:
                    remainder.setdefault(name, []).append([expect_next, lo])
                expect_next = hi
                # The covered LV span may cross graph-run boundaries (an
                # agent's contiguous seqs can land on different branches);
                # push the last LV of each graph-run piece so dominators are
                # exact. (The reference pushes one version per client run —
                # summary.rs:199 — a safe approximation that can over-send.)
                lv_lo = lv0 + (lo - s0)
                lv_hi = lv0 + (hi - s0)
                while lv_lo < lv_hi:
                    gi = cg.graph.find_idx(lv_lo)
                    piece_end = min(cg.graph.ends[gi], lv_hi)
                    versions.append(piece_end - 1)
                    lv_lo = piece_end
            if expect_next < want1:
                remainder.setdefault(name, []).append([expect_next, want1])

    return (cg.graph.find_dominators(versions),
            remainder if remainder else None)


def intersect_with_flat_summary(cg: CausalGraph, summary: VersionSummaryFlat,
                                frontier: Sequence[int] = ()
                                ) -> Tuple[List[int], Optional[VersionSummaryFlat]]:
    """reference: summary.rs:186-206."""
    full = {name: [[0, next_seq]] for name, next_seq in summary.items()}
    common, rem = intersect_with_summary(cg, full, frontier)
    flat_rem: Optional[VersionSummaryFlat] = None
    if rem:
        flat_rem = {name: max(r[1] for r in ranges)
                    for name, ranges in rem.items()}
    return common, flat_rem
