"""Probabilistic common-version discovery.

Capability mirror of the reference's stochastic summary sketch (reference:
src/list/stochastic_summary.rs:8-25): when two peers' histories are huge,
sending a full VersionSummary costs bandwidth proportional to the number of
agent runs. Instead, peers exchange a small random sample of their known
(agent, seq) versions per round; each round either finds common versions
(bounding the diff) or shrinks the candidate range — trading round-trips for
bandwidth.
"""

from __future__ import annotations

import random
from typing import List, Optional, Sequence, Tuple

from .causal_graph import CausalGraph

Sample = List[Tuple[str, int]]  # [(agent_name, seq)]


def sample_versions(cg: CausalGraph, k: int = 16,
                    rng: Optional[random.Random] = None) -> Sample:
    """Uniformly sample k known versions, biased to include the frontier
    (the most likely useful anchors)."""
    rng = rng or random.Random(0)
    out: Sample = list(cg.local_to_remote_frontier(cg.version))
    n = len(cg)
    if n == 0:
        return out
    for _ in range(max(0, k - len(out))):
        lv = rng.randrange(n)
        agent, seq = cg.agent_assignment.local_to_agent_version(lv)
        out.append((cg.agent_assignment.get_agent_name(agent), seq))
    return out


def common_versions_from_sample(cg: CausalGraph, sample: Sample) -> List[int]:
    """Which of the remote's sampled versions do we know? Returns the
    dominator frontier of the known subset — a lower bound on the true
    common version that tightens with more rounds."""
    known = []
    for (name, seq) in sample:
        agent = cg.agent_assignment.try_get_agent(name)
        if agent is None:
            continue
        lv = cg.agent_assignment.try_agent_version_to_lv(agent, seq)
        if lv is not None:
            known.append(lv)
    return cg.graph.find_dominators(sorted(set(known)))


def estimate_common_frontier(local: CausalGraph, remote: CausalGraph,
                             rounds: int = 3, k: int = 16,
                             seed: int = 0) -> List[int]:
    """Simulated protocol: `rounds` sample exchanges, accumulating the best
    known lower bound of the common frontier."""
    rng = random.Random(seed)
    best: List[int] = []
    for _ in range(rounds):
        sample = sample_versions(remote, k, rng)
        found = common_versions_from_sample(local, sample)
        best = local.graph.find_dominators_2(best, found)
    return best
