"""Binary wire frames: one self-describing envelope for mesh transport.

Frame grammar (all integers LEB128 varints unless sized)::

    frame     := magic version ftype flags payload_len payload crc
    magic     := "DTWF"              (4 bytes)
    version   := u8                  (currently 1)
    ftype     := u8                  (FRAME_* below)
    flags     := u8                  (bit 0: payload is lz4-compressed)
    payload_len := varint            (byte length of payload as stored)
    payload   := payload_len bytes
    crc       := u32 LE CRC-32C over everything before it

A compressed payload (FLAG_LZ4) stores ``varint uncompressed_len``
followed by one lz4 block; the flag is set only when compression
actually wins. Decoding is total: bad magic, an unknown version, a
truncated buffer, a length overrun or a CRC mismatch all raise the
typed :class:`WireError` — a corrupt frame can never surface as
garbage ops.

Payload schemas (the delta encodings mirror the reference wire format:
agent tables interned once per frame, op runs as length-prefixed
spans — see encoding/encode.py for the patch body itself):

* ``SUMMARY`` — a version summary (causalgraph/summary.py): per agent
  an interned name plus delta-encoded ``[start, end)`` seq ranges.
* ``PATCH`` — a raw v1 ``DMNDTYPS`` patch (encoding/encode.py already
  does agent interning + RLE op spans; the frame adds the envelope).
* ``OPS`` — a proxied edit body: agent, remote-frontier version, and
  the op tape with ``mix_bit``-packed positions.
* ``STATE`` — a proxied read response: remote frontier + text.
* ``SNAPSHOT`` — a compacted snapshot: a record chain (baseline +
  patches, each a ``DMNDTYPS`` blob) replayed via ``decode_into``.
* ``DOCS`` — the anti-entropy doc listing: per doc an optional lease
  (holder interned, ttl in ms) and an optional frontier advert. The
  listing is re-sent every round to every peer, so it dominates the
  channel once deltas stop flowing — the binary form is what makes
  the steady-state round cheap.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple

from ..encoding.crc32c import crc32c
from ..encoding.lz4 import lz4_compress_block, lz4_decompress_block
from ..encoding.varint import decode_leb, encode_leb, mix_bit, strip_bit

MAGIC = b"DTWF"
WIRE_VERSION = 1

# content negotiation: requests advertise `X-DT-Wire: v1`; responses
# are sniffed by magic (DTWF vs DMNDTYPS vs JSON), so old peers that
# ignore the header keep working mid-rolling-upgrade
WIRE_HEADER = "X-DT-Wire"
WIRE_CTYPE = "application/x-dt-wire"

FRAME_SUMMARY = 1
FRAME_PATCH = 2
FRAME_OPS = 3
FRAME_STATE = 4
FRAME_SNAPSHOT = 5
FRAME_DOCS = 6

_FRAME_TYPES = (FRAME_SUMMARY, FRAME_PATCH, FRAME_OPS, FRAME_STATE,
                FRAME_SNAPSHOT, FRAME_DOCS)

FLAG_LZ4 = 0x01

# the transport channels the metrics/scorecard split bytes across, and
# the per-channel counter keys — module-level so the dt-lint
# metrics-schema-drift rule can cross-reference producer bumps against
# them without importing a class
WIRE_CHANNELS = ("antientropy", "proxy", "hydrate", "gossip")
WIRE_KEYS = ("bytes_sent", "bytes_saved", "frames", "snapshot_ships")


class WireError(ValueError):
    """Typed decode rejection: the buffer is not a well-formed frame.
    Callers treat it exactly like a JSON parse error — fall back or
    400, never apply."""


def is_frame(data: bytes) -> bool:
    return data[:4] == MAGIC


# ---- envelope --------------------------------------------------------------

def encode_frame(ftype: int, payload: bytes,
                 compress: bool = False) -> bytes:
    """Wrap ``payload`` in one frame. ``compress=True`` tries lz4 and
    keeps it only when the block (plus its length prefix) is smaller
    than the raw payload."""
    flags = 0
    if compress and len(payload) > 64:
        block = encode_leb(len(payload)) + lz4_compress_block(payload)
        if len(block) < len(payload):
            payload = block
            flags |= FLAG_LZ4
    out = bytearray(MAGIC)
    out.append(WIRE_VERSION)
    out.append(ftype)
    out.append(flags)
    out += encode_leb(len(payload))
    out += payload
    out += struct.pack("<I", crc32c(bytes(out)))
    return bytes(out)


def decode_frame(data: bytes) -> Tuple[int, bytes]:
    """Returns ``(ftype, payload)``; raises WireError on anything that
    is not one intact, CRC-clean frame."""
    if len(data) < 12 or data[:4] != MAGIC:
        raise WireError("bad magic")
    if data[4] != WIRE_VERSION:
        raise WireError(f"unsupported wire version {data[4]}")
    ftype, flags = data[5], data[6]
    if ftype not in _FRAME_TYPES:
        raise WireError(f"unknown frame type {ftype}")
    if flags & ~FLAG_LZ4:
        raise WireError(f"unknown flags 0x{flags:02x}")
    try:
        plen, pos = decode_leb(data, 7)
    except Exception:
        raise WireError("truncated header")
    end = pos + plen
    if end + 4 != len(data):
        raise WireError("length mismatch")
    if struct.unpack("<I", data[end:end + 4])[0] != crc32c(data[:end]):
        raise WireError("crc mismatch")
    payload = data[pos:end]
    if flags & FLAG_LZ4:
        try:
            ulen, p = decode_leb(payload, 0)
            payload = lz4_decompress_block(payload[p:], ulen)
        except WireError:
            raise
        except Exception as e:
            raise WireError(f"bad lz4 payload: {e.__class__.__name__}")
    return ftype, payload


# ---- payload primitives ----------------------------------------------------

def _put_str(out: bytearray, s: str) -> None:
    b = s.encode("utf8")
    out += encode_leb(len(b))
    out += b


def _get_str(buf: bytes, pos: int) -> Tuple[str, int]:
    n, pos = decode_leb(buf, pos)
    end = pos + n
    if end > len(buf):
        raise WireError("truncated string")
    try:
        return buf[pos:end].decode("utf8"), end
    except UnicodeDecodeError:
        raise WireError("bad utf8")


def _put_frontier(out: bytearray, version) -> None:
    """Remote frontier: [[agent, seq], ...]."""
    out += encode_leb(len(version))
    for agent, seq in version:
        _put_str(out, agent)
        out += encode_leb(int(seq))


def _get_frontier(buf: bytes, pos: int) -> Tuple[List[list], int]:
    n, pos = decode_leb(buf, pos)
    version = []
    for _ in range(n):
        agent, pos = _get_str(buf, pos)
        seq, pos = decode_leb(buf, pos)
        version.append([agent, seq])
    return version, pos


def _decode_leb_checked(buf: bytes, pos: int) -> Tuple[int, int]:
    try:
        return decode_leb(buf, pos)
    except Exception:
        raise WireError("truncated varint")


# ---- SUMMARY ---------------------------------------------------------------

def encode_summary(summary: Dict[str, List[List[int]]]) -> bytes:
    """Version summary: agent table interned once, seq ranges
    delta-encoded (``start - prev_end``, ``end - start``) so long run
    chains cost a couple of bytes each."""
    out = bytearray()
    out += encode_leb(len(summary))
    for agent in sorted(summary):
        _put_str(out, agent)
        ranges = summary[agent]
        out += encode_leb(len(ranges))
        prev = 0
        for s, e in ranges:
            out += encode_leb(s - prev)
            out += encode_leb(e - s)
            prev = e
    return bytes(out)


def decode_summary(payload: bytes) -> Dict[str, List[List[int]]]:
    pos = 0
    n_agents, pos = _decode_leb_checked(payload, pos)
    out: Dict[str, List[List[int]]] = {}
    for _ in range(n_agents):
        agent, pos = _get_str(payload, pos)
        n_ranges, pos = _decode_leb_checked(payload, pos)
        ranges = []
        prev = 0
        for _ in range(n_ranges):
            gap, pos = _decode_leb_checked(payload, pos)
            span, pos = _decode_leb_checked(payload, pos)
            s = prev + gap
            ranges.append([s, s + span])
            prev = s + span
        out[agent] = ranges
    if pos != len(payload):
        raise WireError("trailing bytes in summary")
    return out


# ---- OPS (proxied edit body) -----------------------------------------------

def encode_ops(req: dict) -> bytes:
    """The JSON edit body ``{"agent", "version", "ops"}`` as a frame
    payload. Each op packs its position with ``mix_bit`` (the delete
    discriminator rides in the low bit, reference-style); inserts
    carry text, deletes a run length."""
    out = bytearray()
    _put_str(out, req["agent"])
    _put_frontier(out, req.get("version") or [])
    ops = req["ops"]
    out += encode_leb(len(ops))
    for op in ops:
        if op.get("kind") == "ins":
            out += encode_leb(mix_bit(int(op["pos"]), False))
            _put_str(out, op["text"])
        elif op.get("kind") == "del":
            start, end = int(op["start"]), int(op["end"])
            out += encode_leb(mix_bit(start, True))
            out += encode_leb(end - start)
        else:
            raise WireError(f"bad op kind {op.get('kind')!r}")
    return bytes(out)


def decode_ops(payload: bytes) -> dict:
    pos = 0
    agent, pos = _get_str(payload, pos)
    version, pos = _get_frontier(payload, pos)
    n_ops, pos = _decode_leb_checked(payload, pos)
    ops = []
    for _ in range(n_ops):
        mixed, pos = _decode_leb_checked(payload, pos)
        p, is_del = strip_bit(mixed)
        if is_del:
            span, pos = _decode_leb_checked(payload, pos)
            ops.append({"kind": "del", "start": p, "end": p + span})
        else:
            text, pos = _get_str(payload, pos)
            ops.append({"kind": "ins", "pos": p, "text": text})
    if pos != len(payload):
        raise WireError("trailing bytes in ops")
    return {"agent": agent, "version": version, "ops": ops}


# ---- STATE (proxied read response) -----------------------------------------

def encode_state(text: str, version) -> bytes:
    out = bytearray()
    _put_frontier(out, version)
    _put_str(out, text)
    return bytes(out)


def decode_state(payload: bytes) -> Tuple[str, List[list]]:
    pos = 0
    version, pos = _get_frontier(payload, pos)
    text, pos = _get_str(payload, pos)
    if pos != len(payload):
        raise WireError("trailing bytes in state")
    return text, version


# ---- DOCS (anti-entropy listing) -------------------------------------------

_DOC_HAS_LEASE = 0x01
_DOC_HAS_FRONTIER = 0x02


def encode_docs(listing: dict) -> bytes:
    """The ``/replicate/docs`` JSON listing (``{"docs": {...},
    "self": id}``) as a frame payload. Lease holders are interned in a
    table (in a steady mesh a handful of hosts hold every lease), TTLs
    ride as integer milliseconds."""
    docs = listing.get("docs") or {}
    holders: List[str] = []
    hidx: Dict[str, int] = {}
    for info in docs.values():
        lease = (info or {}).get("lease")
        if lease and lease["holder"] not in hidx:
            hidx[lease["holder"]] = len(holders)
            holders.append(lease["holder"])
    out = bytearray()
    _put_str(out, listing.get("self") or "")
    out += encode_leb(len(holders))
    for h in holders:
        _put_str(out, h)
    out += encode_leb(len(docs))
    for doc_id in sorted(docs):
        info = docs[doc_id] or {}
        lease = info.get("lease")
        frontier = info.get("frontier")
        _put_str(out, doc_id)
        flags = (_DOC_HAS_LEASE if lease else 0) \
            | (_DOC_HAS_FRONTIER if frontier is not None else 0)
        out.append(flags)
        if lease:
            out += encode_leb(hidx[lease["holder"]])
            out += encode_leb(int(lease["epoch"]))
            _put_str(out, lease.get("state", "active"))
            out += encode_leb(max(int(round(
                float(lease.get("ttl_s", 0.0)) * 1000)), 0))
        if frontier is not None:
            _put_frontier(out, frontier)
    return bytes(out)


def decode_docs(payload: bytes) -> dict:
    pos = 0
    self_id, pos = _get_str(payload, pos)
    n_holders, pos = _decode_leb_checked(payload, pos)
    holders = []
    for _ in range(n_holders):
        h, pos = _get_str(payload, pos)
        holders.append(h)
    n_docs, pos = _decode_leb_checked(payload, pos)
    docs: Dict[str, dict] = {}
    for _ in range(n_docs):
        doc_id, pos = _get_str(payload, pos)
        if pos >= len(payload):
            raise WireError("truncated doc entry")
        flags = payload[pos]
        pos += 1
        if flags & ~(_DOC_HAS_LEASE | _DOC_HAS_FRONTIER):
            raise WireError(f"unknown doc flags 0x{flags:02x}")
        info: dict = {"lease": None}
        if flags & _DOC_HAS_LEASE:
            hi, pos = _decode_leb_checked(payload, pos)
            if hi >= len(holders):
                raise WireError("bad holder index")
            epoch, pos = _decode_leb_checked(payload, pos)
            state, pos = _get_str(payload, pos)
            ttl_ms, pos = _decode_leb_checked(payload, pos)
            info["lease"] = {"holder": holders[hi], "epoch": epoch,
                             "state": state, "ttl_s": ttl_ms / 1000.0}
        if flags & _DOC_HAS_FRONTIER:
            frontier, pos = _get_frontier(payload, pos)
            info["frontier"] = frontier
        docs[doc_id] = info
    if pos != len(payload):
        raise WireError("trailing bytes in docs listing")
    return {"docs": docs, "self": self_id}


# ---- SNAPSHOT (record chain) -----------------------------------------------

def encode_records(records: List[bytes]) -> bytes:
    """Snapshot payload: a length-prefixed chain of ``DMNDTYPS`` blobs
    (a PagedDocFile baseline + its patch WAL, or one full encode)."""
    out = bytearray()
    out += encode_leb(len(records))
    for rec in records:
        out += encode_leb(len(rec))
        out += rec
    return bytes(out)


def decode_records(payload: bytes) -> List[bytes]:
    pos = 0
    n, pos = _decode_leb_checked(payload, pos)
    records = []
    for _ in range(n):
        rlen, pos = _decode_leb_checked(payload, pos)
        end = pos + rlen
        if end > len(payload):
            raise WireError("truncated record")
        records.append(payload[pos:end])
        pos = end
    if pos != len(payload):
        raise WireError("trailing bytes in snapshot")
    return records
