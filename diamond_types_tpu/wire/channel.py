"""Per-node wire state: negotiation cache, accounting, frame cache.

One ``WireChannel`` hangs off each ``ReplicaNode`` (and is reachable
from the read path via ``store.replica.wire``). It owns three things:

* **negotiation** — which peers speak wire v1. GET requests need no
  cache (the request advertises ``X-DT-Wire: v1`` and the response
  magic is sniffed), but POST *bodies* must be encoded before any
  response arrives, so capability is learned from ping gossip
  (``ping_json`` carries ``"wire": 1``; ``_on_ping`` folds it here).
  Unknown or old peers get the JSON fallback — a mixed-version mesh
  converges byte-identically, just at JSON prices.
* **accounting** — every send on every channel (framed OR JSON
  fallback) lands in ``ReplicationMetrics``'s wire group, so
  before/after scorecards both carry per-channel columns.
* **frame cache** — snapshot frames are frontier-keyed and reused
  across peers catching up to the same point. The cache lock sits on
  the io rung (``wire.frames``) like the rest of the residency tier's
  table guards, and is never held across an encode.

Framing is toggleable (``DT_WIRE_DISABLED=1`` pins a node to JSON —
how the mixed-version test and the before/after baselines simulate an
old peer); accounting is always on.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable, Dict, Optional, Tuple

from ..analysis.witness import make_lock
from .frames import WIRE_CHANNELS, WIRE_KEYS, WIRE_VERSION
from .snapshot import SNAPSHOT_OPS_THRESHOLD


def wire_enabled() -> bool:
    """Process-wide kill switch: ``DT_WIRE_DISABLED=1`` pins this node
    to the JSON fallback (it still *accepts* frames, but peers never
    send it any, because it stops advertising ``"wire"`` in pings)."""
    return os.environ.get("DT_WIRE_DISABLED", "") in ("", "0")


class WireChannel:
    def __init__(self, metrics=None, enabled: Optional[bool] = None,
                 snapshot_ops_threshold: int = SNAPSHOT_OPS_THRESHOLD,
                 cache_entries: int = 64) -> None:
        self.metrics = metrics      # ReplicationMetrics (bump_wire)
        self.enabled = wire_enabled() if enabled is None else enabled
        self.snapshot_ops_threshold = int(snapshot_ops_threshold)
        # peer_id -> advertised wire version (0 / absent = JSON only);
        # plain lock: leaf-level, never nested around another guard
        self._peer_versions: Dict[str, int] = {}
        self._peer_lock = threading.Lock()
        self._frame_cache_lock = make_lock("wire.frames", "io")
        self._frame_cache: "OrderedDict[Tuple[str, tuple], bytes]" = \
            OrderedDict()
        self.cache_entries = max(int(cache_entries), 1)

    # ---- negotiation -----------------------------------------------------

    def header_value(self) -> Optional[str]:
        """The ``X-DT-Wire`` value to advertise on requests (None when
        framing is disabled — the header is simply omitted)."""
        return f"v{WIRE_VERSION}" if self.enabled else None

    def note_peer(self, peer_id: str, version) -> None:
        """Fold a gossiped capability (``ping_json``'s ``"wire"``)."""
        try:
            v = int(version or 0)
        except (TypeError, ValueError):
            v = 0
        with self._peer_lock:
            self._peer_versions[peer_id] = v

    def peer_wire(self, peer_id: str) -> int:
        with self._peer_lock:
            return self._peer_versions.get(peer_id, 0)

    def use_wire(self, peer_id: str) -> bool:
        """May POST bodies to this peer be framed? Requires both our
        own framing switch and the peer's gossiped capability."""
        return self.enabled and self.peer_wire(peer_id) >= WIRE_VERSION

    # ---- accounting ------------------------------------------------------

    def account(self, channel: str, sent_bytes: int = 0,
                json_bytes: Optional[int] = None, framed: bool = False,
                snapshot: bool = False) -> None:
        """One send on ``channel``: always counts ``bytes_sent``;
        framed sends also count ``frames`` and the bytes the frame
        saved over its JSON equivalent."""
        m = self.metrics
        if m is None:
            return
        if sent_bytes:
            m.bump_wire(channel, "bytes_sent", sent_bytes)
        if framed:
            m.bump_wire(channel, "frames")
            if json_bytes is not None and json_bytes > sent_bytes:
                m.bump_wire(channel, "bytes_saved",
                            json_bytes - sent_bytes)
        if snapshot:
            m.bump_wire(channel, "snapshot_ships")

    # ---- snapshot frame cache --------------------------------------------

    def cached_snapshot(self, doc_id: str, frontier_key: tuple,
                        build: Callable[[], bytes]) -> bytes:
        """Frontier-keyed snapshot frame, built at most once per tip
        (best effort — a race builds twice, caches once). The cache
        lock guards only the map, never the encode."""
        key = (doc_id, frontier_key)
        with self._frame_cache_lock:
            frame = self._frame_cache.get(key)
            if frame is not None:
                self._frame_cache.move_to_end(key)
                return frame
        frame = build()
        with self._frame_cache_lock:
            self._frame_cache[key] = frame
            self._frame_cache.move_to_end(key)
            while len(self._frame_cache) > self.cache_entries:
                self._frame_cache.popitem(last=False)
        return frame

    def invalidate(self, doc_id: str) -> None:
        with self._frame_cache_lock:
            stale = [k for k in self._frame_cache if k[0] == doc_id]
            for k in stale:
                del self._frame_cache[k]

    def counters(self) -> dict:
        """The wire counter block (all zeros without metrics) — used
        by tests; the scorecard reads ``ReplicationMetrics`` direct."""
        m = self.metrics
        if m is None:
            return {f"{c}_{k}": 0 for c in WIRE_CHANNELS
                    for k in WIRE_KEYS}
        return m.wire_counters()
