"""Compacted-snapshot shipping: one frame instead of an op replay.

Two transport paths want a whole document, not a delta:

* a peer whose version summary lags the local oplog by more than
  ``snapshot_ops_threshold`` ops (anti-entropy would otherwise encode
  and ship a near-full patch with per-op framing overhead);
* a cold hydration miss on a follower whose durable home is empty —
  fetching the owner's compacted snapshot beats replaying history.

The payload reuses the PR 8 ``PagedDocFile`` store: when the doc has a
durable home on disk, its already-compacted record chain (baseline +
patch WAL, each a ``DMNDTYPS`` blob) is shipped verbatim — no
re-encode on the hot path. A memory-resident doc falls back to one
``ENCODE_FULL`` record. Either way the receiver replays the chain
through ``decode_into``, which is idempotent and dedup-safe, so a
snapshot is applied exactly like a patch — double delivery merges to
the same bytes.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from ..encoding.decode import decode_into
from ..encoding.encode import ENCODE_FULL, encode_oplog
from .frames import (FRAME_SNAPSHOT, WireError, decode_frame,
                     decode_records, encode_frame, encode_records)

# a peer missing more ops than this receives one snapshot frame
# instead of a patch replay (the "snapshot-vs-replay decision rule")
SNAPSHOT_OPS_THRESHOLD = 512


def missing_ops(cg, local_version, common) -> int:
    """How many local ops the peer provably lacks: the span total of
    ``diff(local, common)``'s local-only side. Caller holds the
    store's oplog lock."""
    only_local, _only_common = cg.graph.diff(local_version, common)
    return sum(e - s for s, e in only_local)


def should_ship_snapshot(cg, local_version, common,
                         threshold: int = SNAPSHOT_OPS_THRESHOLD) -> bool:
    """True when the peer is far enough behind that one compacted
    snapshot beats replaying the missing ops."""
    if threshold <= 0:
        return False
    return missing_ops(cg, local_version, common) > threshold


def snapshot_records(ol, store=None, doc_id: Optional[str] = None,
                     oplog_lock=None) -> Tuple[List[bytes], bool]:
    """The doc's compacted record chain. Prefers the durable
    ``PagedDocFile`` home (records shipped verbatim, no re-encode) —
    but only when the home actually covers the live oplog (the warm
    copy may hold unsaved suffix ops). Returns (records, from_disk)."""
    if store is not None and doc_id is not None:
        try:
            path = store.path(doc_id)
            if os.path.exists(path) \
                    and store.is_quarantined(doc_id) is None:
                from ..storage.pages import PagedDocFile
                f = PagedDocFile(path)
                try:
                    covered = len(f.oplog)
                    records = list(f.store.records(f.BASELINE)) \
                        + list(f.store.records(f.PATCHES))
                finally:
                    f.close()
                if records and covered >= len(ol):
                    return records, True
        except Exception:
            pass        # unreadable home: fall through to a live encode
    if oplog_lock is not None:
        with oplog_lock:
            return [encode_oplog(ol, ENCODE_FULL)], False
    return [encode_oplog(ol, ENCODE_FULL)], False


def build_snapshot(ol, store=None, doc_id: Optional[str] = None,
                   oplog_lock=None) -> bytes:
    """One SNAPSHOT frame for the doc (lz4 over the record chain)."""
    records, _from_disk = snapshot_records(ol, store, doc_id,
                                           oplog_lock=oplog_lock)
    return encode_frame(FRAME_SNAPSHOT, encode_records(records),
                        compress=True)


def apply_snapshot(ol, frame: bytes) -> int:
    """Replay a SNAPSHOT frame into ``ol`` (caller holds the oplog
    lock). Returns the number of new ops merged. Raises WireError on
    a malformed frame and lets decode errors from a corrupt record
    propagate — never half-applies garbage silently."""
    ftype, payload = decode_frame(frame)
    if ftype != FRAME_SNAPSHOT:
        raise WireError(f"expected snapshot frame, got type {ftype}")
    pre = len(ol)
    for rec in decode_records(payload):
        decode_into(ol, rec)
    return len(ol) - pre
