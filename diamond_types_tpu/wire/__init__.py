"""Wire tier: versioned binary framing for all inter-host transport.

Every byte that crosses the mesh — anti-entropy handshakes, proxied
writes, follower-read proxies, cold-hydration snapshot fetches — rides
in one self-describing frame format (`frames.py`), negotiated per
channel with a JSON fallback so old peers keep working mid-rolling-
upgrade (`channel.py`). Far-behind peers and hydration misses receive
one compacted snapshot frame instead of an op replay (`snapshot.py`).
"""

from .frames import (FLAG_LZ4, FRAME_DOCS, FRAME_OPS, FRAME_PATCH,
                     FRAME_SNAPSHOT, FRAME_STATE, FRAME_SUMMARY, MAGIC,
                     WIRE_CHANNELS, WIRE_CTYPE, WIRE_HEADER, WIRE_KEYS,
                     WIRE_VERSION, WireError, decode_docs, decode_frame,
                     decode_ops, decode_state, decode_summary,
                     encode_docs, encode_frame, encode_ops,
                     encode_state, encode_summary, is_frame)
from .channel import WireChannel, wire_enabled
from .snapshot import (SNAPSHOT_OPS_THRESHOLD, apply_snapshot,
                       build_snapshot, should_ship_snapshot)

__all__ = [
    "FLAG_LZ4", "FRAME_DOCS", "FRAME_OPS", "FRAME_PATCH",
    "FRAME_SNAPSHOT", "FRAME_STATE", "FRAME_SUMMARY", "MAGIC",
    "WIRE_CHANNELS", "WIRE_CTYPE", "WIRE_HEADER", "WIRE_KEYS",
    "WIRE_VERSION", "WireError", "decode_docs", "decode_frame",
    "decode_ops", "decode_state", "decode_summary", "encode_docs",
    "encode_frame", "encode_ops", "encode_state", "encode_summary",
    "is_frame", "WireChannel", "wire_enabled",
    "SNAPSHOT_OPS_THRESHOLD", "apply_snapshot", "build_snapshot",
    "should_ship_snapshot",
]
