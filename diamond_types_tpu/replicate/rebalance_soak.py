"""Flash-crowd elastic-mesh soak (CLI: `rebalance-soak`).

Boots N in-process sync servers (one serve shard each, follower reads
on) into one replication mesh, lets rendezvous placement spread the
docs, then runs a deterministic closed-loop load model against a
tight custom SLO:

  * healthy phase — per-edit RTT observations land under the latency
    threshold on every owner; the `soak_edit_rtt` objective reads
    `ok` everywhere;
  * flash crowd — one doc goes hot and its owner's capacity saturates
    (modeled as a fixed load boost on top of that host's held-lease
    count); every edit owned by the crowded host observes an
    over-threshold RTT, its objective burns, and the REBALANCER —
    ticked from the same single-threaded control-plane step as probes
    and anti-entropy, no operator in the loop — sheds the hot doc
    first (attribution-ranked) and keeps shedding until the host fits
    its capacity again;
  * scale-out — on the first non-`ok` evaluation a fresh host joins
    the mesh via /replicate/join; with gossiped load 0 it is the
    least-loaded target and must absorb at least one migrated doc;
  * self-healing — one migration is aimed at an unreachable target on
    purpose: the handoff must abort back to ACTIVE at the source with
    the SAME epoch and the placement override tombstoned (a failed
    target never strands a doc);
  * recovery — with the crowd still running, the migrated layout keeps
    every host under capacity, good observations dilute / age out the
    burn windows, and the objective returns to `ok`.

Exit-0 verdict (the `--flash-crowd` acceptance gate): the SLO journey
ok -> burning -> ok completed without operator action, at least one
migration ran, the joined host absorbed load, the seeded abort rolled
back cleanly, every server converged byte-identically on every doc,
and the activation-history scan found zero split-brain.

Like the other soaks, the replication control plane is stepped inline
and single-threaded so a given seed replays exactly; only the HTTP
servers run real threads.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Dict, List, Optional

from ..obs import Objective
from .node import attach_replication
from .rebalance import attach_rebalancer
from .soak import _converged, _final_texts, _split_brain

# per-event latency budget of the soak objective; the load model emits
# 0.01 s (healthy) or 1.0 s (saturated) observations around it
_RTT_THRESHOLD_S = 0.5
_RTT_GOOD_S = 0.01
_RTT_BAD_S = 1.0
# observation weights: the hot doc is hammered, the crowded host's
# other docs feel the contention, everything else idles along
_W_HOT = 12
_W_CROWDED = 3


def _objective(fast_window_s: float, slow_window_s: float) -> Objective:
    # target 0.7 => warning at bad-fraction 0.3, burning at 0.6 on
    # both windows — tight enough that one saturated round pages,
    # short enough that recovery is observable in soak wall time
    return Objective("soak_edit_rtt", "soak.edit_rtt",
                     threshold_s=_RTT_THRESHOLD_S, target=0.7,
                     fast_window_s=fast_window_s,
                     slow_window_s=slow_window_s,
                     fast_burn=2.0, slow_burn=2.0)


def run_rebalance_soak(servers: int = 3, docs: int = 8, seed: int = 7,
                       capacity: int = 5, crowd_boost: int = 3,
                       healthy_rounds: int = 3,
                       crowd_rounds: int = 6,
                       recover_rounds: int = 60,
                       reconcile_rounds: int = 20,
                       flash_crowd: bool = True,
                       join: bool = True,
                       inject_abort: bool = True,
                       lease_ttl_s: float = 30.0,
                       fast_window_s: float = 3.0,
                       slow_window_s: float = 6.0,
                       progress: bool = False) -> dict:
    from ..tools.server import SyncClient, serve

    rng = random.Random(seed)
    doc_ids = [f"elastic-{i}" for i in range(docs)]
    # sample_rate=1.0 so every edit carries a journey — the verdict's
    # convergence-lag column needs advert_usable stamps to aggregate
    obs_opts = dict(sample_rate=1.0, ts_window_s=0.5, ts_windows=64,
                    objectives=[_objective(fast_window_s,
                                           slow_window_s)])
    node_opts = dict(seed=seed, lease_ttl_s=lease_ttl_s,
                     probe_interval_s=0.25,
                     antientropy_interval_s=0.25,
                     timeout_s=2.0, backoff_base_s=0.02,
                     backoff_cap_s=0.1)
    # act only on burning: the gate's SLO journey must REACH burning
    # before the first migration cures the crowd — acting on warning
    # too (the default) would race the journey against the fix under
    # wall-clock contention
    rb_opts = dict(cooldown_s=0.2, max_migrations_per_tick=1,
                   min_load_gap=2, top_n=4, act_on=("burning",))

    httpds: List = []
    nodes: List = []
    addrs: List[str] = []

    def boot(join_to: Optional[str] = None):
        httpd = serve(port=0, serve_shards=1, follower_reads=True,
                      obs_opts=dict(obs_opts))
        httpd.socket.listen(128)
        addr = f"127.0.0.1:{httpd.server_address[1]}"
        node = attach_replication(httpd, addr, [], **node_opts)
        attach_rebalancer(node, **rb_opts)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        if join_to is not None:
            node.join_mesh(join_to)
        return httpd, node, addr

    for i in range(servers):
        httpd = serve(port=0, serve_shards=1, follower_reads=True,
                      obs_opts=dict(obs_opts))
        httpd.socket.listen(128)
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    for i, httpd in enumerate(httpds):
        node = attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            **node_opts)
        attach_rebalancer(node, **rb_opts)
        nodes.append(node)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()

    migrations: List[List[str]] = []
    tick_aborts: List[List[str]] = []

    def step_control_plane() -> None:
        for n in nodes:
            n.table.probe_once()
            n.maintain()
        for n in nodes:
            rep = n.rebalancer.tick()
            migrations.extend(rep["migrated"])
            tick_aborts.extend(rep["aborted"])
        for n in nodes:
            n.antientropy.run_round()

    clients: Dict[tuple, SyncClient] = {}

    def client(i: int, doc_id: str) -> SyncClient:
        key = (i, doc_id)
        if key not in clients:
            clients[key] = SyncClient(
                f"http://{addrs[i]}", doc_id,
                f"agent-{i}-{doc_id}", retries=2)
        return clients[key]

    def edit(i: int, doc_id: str, word: str) -> bool:
        c = client(i, doc_id)
        try:
            c.pull()
        except OSError:
            pass
        c.insert(rng.randrange(len(c.text()) + 1), word + " ")
        try:
            c.sync()
            return True
        except OSError:
            return False

    def owner_of(doc_id: str):
        holders = [n for n in nodes
                   if n.leases.active_epoch(doc_id) > 0]
        return holders[0] if len(holders) == 1 else None

    crowd_target = None     # the host the SLO journey is tracked on
    hot_doc = doc_ids[0]    # re-picked after settle (most-loaded host)

    def observe_round(crowd_on: bool) -> None:
        """The load model: weighted RTT observations per doc at its
        owner. The crowd load FOLLOWS the hot doc — whichever host
        currently owns it carries the boost on top of its held-lease
        count, so migrating the hot doc to a host with headroom (and
        only that) is what restores the SLO."""
        hot_owner = owner_of(hot_doc) if crowd_on else None
        for doc_id in doc_ids:
            own = owner_of(doc_id)
            if own is None:
                continue
            eff = own.leases.held_count() \
                + (crowd_boost if own is hot_owner else 0)
            rtt = _RTT_BAD_S if eff > capacity else _RTT_GOOD_S
            if not crowd_on:
                weight = 1
            elif doc_id == hot_doc:
                weight = _W_HOT
            elif own is hot_owner:
                weight = _W_CROWDED
            else:
                weight = 1
            for _ in range(weight):
                own.obs.ts.observe("soak.edit_rtt", rtt)
            own.obs.attrib.note("ops", doc=doc_id, n=float(weight))

    def slo_state() -> str:
        if crowd_target is None:
            return "ok"
        return crowd_target.obs.slo.evaluate()[0]["state"]

    t0 = time.monotonic()
    edits = 0

    # ---- seed + settle: one ACTIVE owner per doc --------------------------
    for doc_id in doc_ids:
        if edit(rng.randrange(servers), doc_id, "seed"):
            edits += 1
    for _ in range(40):
        step_control_plane()
        if all(owner_of(d) is not None for d in doc_ids):
            break
        time.sleep(0.02)
    settled = all(owner_of(d) is not None for d in doc_ids)
    held_initial = {n.self_id: n.leases.held_count() for n in nodes}
    # the hot doc lives on the most-loaded host: with boost just under
    # capacity, saturation needs co-resident load, and the crowded
    # host only recovers by SHEDDING (a one-doc host never saturates)
    crowd_target = max(nodes, key=lambda n: n.leases.held_count())
    held = crowd_target.leases.held_ids()
    if held:
        hot_doc = held[0]

    states: List[str] = []

    # ---- healthy phase ----------------------------------------------------
    for _ in range(healthy_rounds):
        if edit(rng.randrange(servers), rng.choice(doc_ids), "calm"):
            edits += 1
        observe_round(crowd_on=False)
        step_control_plane()
        states.append(slo_state())
        time.sleep(0.02)
    healthy_state = states[-1] if states else "ok"

    joined_addr: Optional[str] = None
    joined_node = None
    burn_seen = False

    # ---- flash crowd ------------------------------------------------------
    if flash_crowd and crowd_target is not None:
        # adaptive: at least crowd_rounds, and keep crowding until the
        # SLO actually reaches burning (capped) — window rollover
        # timing under a loaded machine must not decide the journey
        max_crowd = max(crowd_rounds, 40)
        r = -1
        while (r := r + 1) < crowd_rounds \
                or (not burn_seen and r < max_crowd):
            for _ in range(2):
                if edit(rng.randrange(len(addrs)), hot_doc, "crowd"):
                    edits += 1
            if edit(rng.randrange(len(addrs)),
                    rng.choice(doc_ids), "bg"):
                edits += 1
            observe_round(crowd_on=True)
            st = slo_state()
            states.append(st)
            burn_seen = burn_seen or st == "burning"
            # scale-out response: the join lands BEFORE this round's
            # rebalancer tick, so the fresh (load 0) host is already
            # the preferred target when migrations are planned
            if st != "ok" and join and joined_node is None:
                httpd, joined_node, joined_addr = boot(
                    join_to=addrs[0])
                httpds.append(httpd)
                nodes.append(joined_node)
                addrs.append(joined_addr)
                if progress:
                    print(f"crowd round {r + 1}: slo={st}; "
                          f"joined {joined_addr}")
            step_control_plane()
            if progress:
                print(f"crowd round {r + 1}: slo={st} target.held="
                      f"{crowd_target.leases.held_count()} "
                      f"migrations={len(migrations)}")
            time.sleep(0.05)

        # ---- recovery: the crowd keeps running ----------------------------
        for r in range(recover_rounds):
            if edit(rng.randrange(len(addrs)), hot_doc, "crowd"):
                edits += 1
            observe_round(crowd_on=True)
            step_control_plane()
            st = slo_state()
            states.append(st)
            if st == "ok":
                break
            time.sleep(0.25)

    # ---- seeded abort: migration at an unreachable target -----------------
    abort_rollback_ok = None
    if inject_abort:
        victims = [n for n in nodes if n.leases.held_count() > 0]
        src = victims[0] if victims else nodes[0]
        doc_id = src.leases.held_ids()[0]
        epoch_before = src.leases.active_epoch(doc_id)
        aborted_before = src.metrics.get("rebalance",
                                         "migrations_aborted")
        moved = src.rebalancer.migrate(doc_id, "127.0.0.1:1")
        abort_rollback_ok = (
            not moved
            and src.leases.active_epoch(doc_id) == epoch_before
            and epoch_before > 0
            and src.overrides.target_of(doc_id) is None
            and src.metrics.get("rebalance", "migrations_aborted")
            == aborted_before + 1)

    # ---- reconcile to convergence -----------------------------------------
    converged_after = None
    for r in range(reconcile_rounds):
        step_control_plane()
        if _converged(addrs, doc_ids):
            converged_after = r + 1
            break
        time.sleep(0.05)
    texts = _final_texts(addrs, doc_ids)
    converged = all(len(set(v.values())) == 1 for v in texts.values())
    split_brain = _split_brain(nodes)

    slo_journey_ok = (not flash_crowd) or (
        healthy_state == "ok" and burn_seen
        and bool(states) and states[-1] == "ok")
    join_absorbed = (not (flash_crowd and join)) or (
        joined_node is not None
        and (joined_node.leases.held_count() > 0
             or any(n.overrides.target_of(d) == joined_addr
                    for n in nodes for d in doc_ids)))
    ok = bool(
        settled and converged and not split_brain
        and slo_journey_ok and join_absorbed
        and (not flash_crowd or len(migrations) >= 1)
        and (abort_rollback_ok is None or abort_rollback_ok))

    report = {
        "config": {"servers": servers, "docs": docs, "seed": seed,
                   "capacity": capacity, "crowd_boost": crowd_boost,
                   "flash_crowd": flash_crowd, "join": join,
                   "inject_abort": inject_abort,
                   "lease_ttl_s": lease_ttl_s},
        "edits_applied": edits,
        "settled": settled,
        "held_initial": held_initial,
        "crowd_target": getattr(crowd_target, "self_id", None),
        "hot_doc": hot_doc,
        "slo_states": states,
        "slo_journey_ok": slo_journey_ok,
        "burning_seen": burn_seen,
        "migrations": migrations,
        "tick_aborts": tick_aborts,
        "joined": joined_addr,
        "join_absorbed": join_absorbed,
        "abort_rollback_ok": abort_rollback_ok,
        "held_final": {n.self_id: n.leases.held_count()
                       for n in nodes},
        "override_tables": {n.self_id: n.overrides.size()
                            for n in nodes},
        "converged": converged,
        "converged_after_reconcile_rounds": converged_after,
        "split_brain": split_brain,
        "zero_split_brain": not split_brain,
        "wall_s": round(time.monotonic() - t0, 3),
        "metrics": {n.self_id: n.metrics_json() for n in nodes},
        # edit-to-visibility per peer (admitted -> advert_usable); a
        # migration that stalls replication shows up here even when
        # the lease counters look healthy
        "convergence_lag": {
            n.self_id: n.obs.journey.lag_summary()
            for n in nodes if getattr(n, "obs", None) is not None},
        "ok": ok,
    }
    if not ok:
        events = []
        for n in nodes:
            obs = getattr(n, "obs", None)
            if obs is None:
                continue
            for ev in obs.recorder.tail(50):
                events.append(dict(ev, node=n.self_id))
        events.sort(key=lambda e: e.get("t", 0.0))
        report["events_tail"] = events[-50:]
    for httpd in httpds:
        httpd.shutdown()
        httpd.server_close()
    return report


def run_split_soak(servers: int = 3, docs: int = 4, seed: int = 11,
                   capacity_per_round: int = 4,
                   offered_per_round: int = 10,
                   measure_rounds: int = 6,
                   lease_ttl_s: float = 30.0,
                   group_ttl_s: float = 1.5,
                   fast_window_s: float = 3.0,
                   slow_window_s: float = 6.0,
                   progress: bool = False) -> dict:
    """Hot-doc write-splitting soak (CLI: `rebalance-soak
    --split-hot-doc`).

    The single-writer wall: every hot-doc write must be APPLIED at the
    one lease holder — writes ingested elsewhere are proxied to it —
    so one host's apply capacity caps the doc no matter how many peers
    idle. Like the flash-crowd soak's RTT model, capacity is modeled
    explicitly (`capacity_per_round` applied writes per WRITER host per
    control round, offered load above it); every admitted write is a
    REAL HTTP edit with a unique marker, so convergence, acked-loss
    and split-brain are checked for real, not modeled.

    Phases, all driven by the closed loop (no operator action):

      * single-writer baseline — offered load arrives at two ingress
        hosts; the non-owner PROXIES (its merge gate admits nothing),
        so per-round admission is 1x capacity;
      * promotion — sustained hot-doc burn makes the REBALANCER
        promote the doc to a {leader, member} writer group;
      * split measurement — the same two ingress hosts now BOTH accept
        locally (the member's merge gate admits under the group
        epoch): per-round admission is 2x capacity — the >= 2x
        throughput gate — while raw wall-clock rates are reported
        unmodeled alongside;
      * member-crash — the member is isolated from the whole mesh
        (mesh-indistinguishable from a crash): it must self-fence to
        proxy-only immediately, and the leader must demote once the
        registration TTL has provably expired;
      * partition-minority — after re-promotion, an ASYMMETRIC cut
        (member cannot reach the leader, the leader still hears the
        member): renewals fail, the member self-fences on expiry, the
        leader's un-renewed registration expires and demotes cleanly.

    Exit-0 verdict: promotion and both demotions happened without
    operator action, admission scaled >= 2x with 2 writers, every
    acked marker is present on every server byte-identically, and the
    activation-history scan found zero split-brain."""
    from ..tools.server import SyncClient, serve
    from .faults import FaultInjector

    rng = random.Random(seed)
    doc_ids = [f"split-{i}" for i in range(docs)]
    faults = FaultInjector(seed=seed)
    obs_opts = dict(sample_rate=1.0, ts_window_s=0.5, ts_windows=64,
                    objectives=[_objective(fast_window_s,
                                           slow_window_s)])
    node_opts = dict(seed=seed, lease_ttl_s=lease_ttl_s,
                     group_ttl_s=group_ttl_s, faults=faults,
                     probe_interval_s=0.25,
                     antientropy_interval_s=0.25,
                     timeout_s=2.0, backoff_base_s=0.02,
                     backoff_cap_s=0.1)
    # demote_after_s is pushed out of soak range on purpose: the two
    # demotions under test are the FAULT paths (maintain-loop demote on
    # an unhealthy member after TTL), not cooled load
    rb_opts = dict(cooldown_s=0.2, max_migrations_per_tick=1,
                   min_load_gap=2, top_n=4,
                   act_on=("warning", "burning"),
                   split_hot_docs=True, group_size=2,
                   promote_after_ticks=2, demote_after_s=300.0)

    httpds: List = []
    nodes: List = []
    addrs: List[str] = []
    for i in range(servers):
        httpd = serve(port=0, serve_shards=1, follower_reads=True,
                      obs_opts=dict(obs_opts))
        httpd.socket.listen(128)
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    for i, httpd in enumerate(httpds):
        node = attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            **node_opts)
        attach_rebalancer(node, **rb_opts)
        nodes.append(node)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()

    promotions: List[List] = []
    demotions: List[str] = []

    def step_control_plane() -> None:
        for n in nodes:
            n.table.probe_once()
            n.maintain()
        for n in nodes:
            rep = n.rebalancer.tick()
            promotions.extend(rep["promoted"])
            demotions.extend(rep["demoted"])
        for n in nodes:
            n.antientropy.run_round()

    clients: Dict[tuple, SyncClient] = {}

    def client(addr: str, doc_id: str) -> SyncClient:
        key = (addr, doc_id)
        if key not in clients:
            clients[key] = SyncClient(
                f"http://{addr}", doc_id,
                f"agent-{addr}-{doc_id}", retries=2)
        return clients[key]

    acked_markers: List[Tuple[str, str]] = []   # (doc_id, marker)
    marker_seq = 0

    def write(addr: str, doc_id: str) -> bool:
        nonlocal marker_seq
        marker = f"w{marker_seq}."
        marker_seq += 1
        c = client(addr, doc_id)
        try:
            c.pull()
        except OSError:
            pass
        # always PREPEND: concurrent inserts at position 0 order
        # themselves but can never split an existing marker run, so
        # the acked-loss scan's substring check stays sound under
        # two-writer concurrency
        c.insert(0, marker + " ")
        try:
            c.sync()
        except OSError:
            return False
        acked_markers.append((doc_id, marker))
        return True

    def owner_of(doc_id: str):
        holders = [n for n in nodes
                   if n.leases.active_epoch(doc_id) > 0]
        return holders[0] if len(holders) == 1 else None

    t0 = time.monotonic()

    # ---- seed + settle ----------------------------------------------------
    for doc_id in doc_ids:
        write(addrs[rng.randrange(servers)], doc_id)
    for _ in range(40):
        step_control_plane()
        if all(owner_of(d) is not None for d in doc_ids):
            break
        time.sleep(0.02)
    settled = all(owner_of(d) is not None for d in doc_ids)
    hot_doc = doc_ids[0]
    leader = owner_of(hot_doc)
    if leader is None:
        leader = nodes[0]
    # the co-writer the rebalancer will pick (same selection code)
    picked = leader.rebalancer._pick_members(1)
    member_addr = picked[0] if picked else \
        next(a for a in addrs if a != leader.self_id)
    member = next(n for n in nodes if n.self_id == member_addr)
    ingress = [leader.self_id, member_addr]

    def measure_phase(writers: int):
        """`measure_rounds` control rounds of the capacity model:
        offered load round-robins across both ingress hosts, the first
        `capacity_per_round * writers` writes per round are applied as
        real HTTP edits, the rest are deferred (capacity, not
        transport, is the modeled limit)."""
        acked = 0
        deferred = 0
        t = time.monotonic()
        for _ in range(measure_rounds):
            cap = capacity_per_round * writers
            for i in range(offered_per_round):
                if i >= cap:
                    deferred += 1
                    continue
                if write(ingress[i % 2], hot_doc):
                    acked += 1
            step_control_plane()
        return acked, deferred, time.monotonic() - t

    # ---- single-writer baseline -------------------------------------------
    member_admits_0 = member.metrics.get("writergroup", "member_admits")
    single_acked, single_deferred, single_wall = measure_phase(1)
    single_member_admits = member.metrics.get(
        "writergroup", "member_admits") - member_admits_0

    # ---- promotion under sustained burn -----------------------------------
    promoted = False
    for r in range(40):
        leader.obs.ts.observe("soak.edit_rtt", _RTT_BAD_S)
        leader.obs.attrib.note("ops", doc=hot_doc, n=float(_W_HOT))
        step_control_plane()
        g = leader.writergroups.get(hot_doc)
        if g is not None and g.leader == leader.self_id:
            promoted = True
            break
        time.sleep(0.02)
    g = leader.writergroups.get(hot_doc)
    group_members = list(g.members) if g is not None else []
    member_in_group = member_addr in group_members
    # let the burn windows drain so the measured phase is load-model
    # only (and the member's registration is renewed at least once)
    for _ in range(4):
        leader.obs.ts.observe("soak.edit_rtt", _RTT_GOOD_S)
        step_control_plane()
        time.sleep(0.02)

    # ---- split measurement ------------------------------------------------
    member_admits_1 = member.metrics.get("writergroup", "member_admits")
    group_acked, group_deferred, group_wall = measure_phase(2)
    group_member_admits = member.metrics.get(
        "writergroup", "member_admits") - member_admits_1

    speedup = (group_acked / measure_rounds) \
        / max(single_acked / measure_rounds, 1e-9)
    rate_single = single_acked / max(single_wall, 1e-9)
    rate_group = group_acked / max(group_wall, 1e-9)

    def demote_phase(mem, cut: List[tuple], oneway: bool) -> dict:
        """Inject the cut, require the member to self-fence and the
        leader to demote (TTL-gated, closed loop), then heal."""
        for a, b in cut:
            faults.partition(a, b, oneway=oneway)
        self_fenced = False
        demoted = False
        # count demotions instead of polling for a missing entry: the
        # still-hot rebalancer may legally re-promote (with a healthy
        # co-writer) between our observations
        d0 = leader.metrics.get("writergroup", "demotions")
        deadline = time.monotonic() + max(group_ttl_s * 8, 8.0)
        while time.monotonic() < deadline:
            step_control_plane()
            self_fenced = self_fenced \
                or not mem.group_accepts(hot_doc)
            if leader.metrics.get("writergroup", "demotions") > d0:
                demoted = True
                break
            time.sleep(0.05)
        # the member's registration must be gone BEFORE the heal
        # (self-fence on expiry, or the leader's demote fence); after
        # the heal a still-hot rebalancer may legally re-grant one
        entry_gone = mem.writergroups.get(hot_doc) is None
        if not entry_gone:
            for _ in range(20):
                step_control_plane()
                if mem.writergroups.get(hot_doc) is None:
                    entry_gone = True
                    break
                time.sleep(0.02)
        self_fenced = self_fenced or not mem.group_accepts(hot_doc)
        faults.heal()
        for _ in range(6):
            step_control_plane()
            time.sleep(0.02)
        return {"self_fenced": bool(self_fenced),
                "leader_demoted": demoted,
                "member_entry_gone": entry_gone,
                "owner_active": owner_of(hot_doc) is leader}

    # ---- member-crash: full isolation -------------------------------------
    crash_phase = None
    if promoted:
        crash_phase = demote_phase(
            member,
            [(member_addr, a) for a in addrs if a != member_addr],
            oneway=False)

    # ---- partition-minority: asymmetric member->leader cut ----------------
    repromoted = False
    minority_phase = None
    if promoted and crash_phase is not None:
        member2 = None
        for r in range(40):
            leader.obs.ts.observe("soak.edit_rtt", _RTT_BAD_S)
            leader.obs.attrib.note("ops", doc=hot_doc, n=float(_W_HOT))
            step_control_plane()
            g2 = leader.writergroups.get(hot_doc)
            if g2 is not None and g2.leader == leader.self_id:
                repromoted = True
                others = [m for m in g2.members
                          if m != leader.self_id]
                member2 = next(n for n in nodes
                               if n.self_id == others[0])
                break
            time.sleep(0.02)
        if repromoted and member2 is not None:
            minority_phase = demote_phase(
                member2, [(member2.self_id, leader.self_id)],
                oneway=True)

    # ---- wind-down: cooled-load demotion ----------------------------------
    # stop the burn and let the rebalancer's cooled-load path drain any
    # still-standing group (the closed loop end to end). Re-promotion
    # is blocked by an unreachable tick floor rather than by disabling
    # the policy, so the demote plan stays armed.
    for n in nodes:
        n.rebalancer.promote_after_ticks = 10 ** 9
        n.rebalancer.demote_after_s = 0.0
    winddown_rounds = None
    for r in range(200):
        leader.obs.ts.observe("soak.edit_rtt", _RTT_GOOD_S)
        step_control_plane()
        if all(not n.writergroups.entries() for n in nodes):
            winddown_rounds = r + 1
            break
        time.sleep(0.02)

    # ---- reconcile + verdict ----------------------------------------------
    converged_after = None
    for r in range(40):
        step_control_plane()
        if _converged(addrs, doc_ids):
            converged_after = r + 1
            break
        time.sleep(0.05)
    texts = _final_texts(addrs, doc_ids)
    converged = all(len(set(v.values())) == 1 for v in texts.values())
    split_brain = _split_brain(nodes)
    lost = sorted(
        m for d, m in acked_markers
        if not texts.get(d)
        or any(m not in t for t in texts[d].values()))
    groups_clear = all(not n.writergroups.entries() for n in nodes)

    throughput_ok = (
        single_member_admits == 0          # baseline really proxied
        and group_member_admits > 0        # split really local-accepts
        and speedup >= 2.0)
    demotes_ok = (
        crash_phase is not None
        and all(crash_phase.values())
        and minority_phase is not None
        and all(minority_phase.values()))
    ok = bool(settled and promoted and member_in_group
              and throughput_ok and repromoted and demotes_ok
              and converged and not lost and not split_brain
              and groups_clear)

    report = {
        "config": {"servers": servers, "docs": docs, "seed": seed,
                   "capacity_per_round": capacity_per_round,
                   "offered_per_round": offered_per_round,
                   "measure_rounds": measure_rounds,
                   "group_ttl_s": group_ttl_s,
                   "lease_ttl_s": lease_ttl_s},
        "settled": settled,
        "hot_doc": hot_doc,
        "leader": getattr(leader, "self_id", None),
        "member": member_addr,
        "promoted": promoted,
        "group_members": group_members,
        "single_writer": {
            "acked": single_acked, "deferred": single_deferred,
            "wall_s": round(single_wall, 3),
            "rate_per_s": round(rate_single, 1),
            "member_admits": single_member_admits},
        "writer_group": {
            "acked": group_acked, "deferred": group_deferred,
            "wall_s": round(group_wall, 3),
            "rate_per_s": round(rate_group, 1),
            "member_admits": group_member_admits},
        "speedup": round(speedup, 3),
        "throughput_ok": throughput_ok,
        "member_crash": crash_phase,
        "repromoted": repromoted,
        "partition_minority": minority_phase,
        "rebalancer_promotions": promotions,
        "rebalancer_demotions": demotions,
        "acked_markers": len(acked_markers),
        "lost_markers": lost,
        "converged": converged,
        "winddown_rounds": winddown_rounds,
        "converged_after_reconcile_rounds": converged_after,
        "split_brain": split_brain,
        "zero_split_brain": not split_brain,
        "groups_clear": groups_clear,
        "faults": faults.snapshot(),
        "wall_s": round(time.monotonic() - t0, 3),
        "metrics": {n.self_id:
                    n.metrics_json()["writergroup"] for n in nodes},
        "ok": ok,
    }
    for httpd in httpds:
        httpd.shutdown()
        httpd.server_close()
    return report


def main(argv=None) -> int:  # pragma: no cover - exercised via cli.py
    import argparse
    p = argparse.ArgumentParser(prog="rebalance-soak")
    p.add_argument("--servers", type=int, default=3)
    p.add_argument("--docs", type=int, default=8)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--flash-crowd", action="store_true")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    report = run_rebalance_soak(servers=args.servers, docs=args.docs,
                                seed=args.seed,
                                flash_crowd=args.flash_crowd)
    print(json.dumps(report if args.json else {
        k: report[k] for k in ("ok", "slo_journey_ok", "burning_seen",
                               "migrations", "join_absorbed",
                               "abort_rollback_ok", "converged",
                               "zero_split_brain", "wall_s")}))
    return 0 if report["ok"] else 1
