"""Flash-crowd elastic-mesh soak (CLI: `rebalance-soak`).

Boots N in-process sync servers (one serve shard each, follower reads
on) into one replication mesh, lets rendezvous placement spread the
docs, then runs a deterministic closed-loop load model against a
tight custom SLO:

  * healthy phase — per-edit RTT observations land under the latency
    threshold on every owner; the `soak_edit_rtt` objective reads
    `ok` everywhere;
  * flash crowd — one doc goes hot and its owner's capacity saturates
    (modeled as a fixed load boost on top of that host's held-lease
    count); every edit owned by the crowded host observes an
    over-threshold RTT, its objective burns, and the REBALANCER —
    ticked from the same single-threaded control-plane step as probes
    and anti-entropy, no operator in the loop — sheds the hot doc
    first (attribution-ranked) and keeps shedding until the host fits
    its capacity again;
  * scale-out — on the first non-`ok` evaluation a fresh host joins
    the mesh via /replicate/join; with gossiped load 0 it is the
    least-loaded target and must absorb at least one migrated doc;
  * self-healing — one migration is aimed at an unreachable target on
    purpose: the handoff must abort back to ACTIVE at the source with
    the SAME epoch and the placement override tombstoned (a failed
    target never strands a doc);
  * recovery — with the crowd still running, the migrated layout keeps
    every host under capacity, good observations dilute / age out the
    burn windows, and the objective returns to `ok`.

Exit-0 verdict (the `--flash-crowd` acceptance gate): the SLO journey
ok -> burning -> ok completed without operator action, at least one
migration ran, the joined host absorbed load, the seeded abort rolled
back cleanly, every server converged byte-identically on every doc,
and the activation-history scan found zero split-brain.

Like the other soaks, the replication control plane is stepped inline
and single-threaded so a given seed replays exactly; only the HTTP
servers run real threads.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Dict, List, Optional

from ..obs import Objective
from .node import attach_replication
from .rebalance import attach_rebalancer
from .soak import _converged, _final_texts, _split_brain

# per-event latency budget of the soak objective; the load model emits
# 0.01 s (healthy) or 1.0 s (saturated) observations around it
_RTT_THRESHOLD_S = 0.5
_RTT_GOOD_S = 0.01
_RTT_BAD_S = 1.0
# observation weights: the hot doc is hammered, the crowded host's
# other docs feel the contention, everything else idles along
_W_HOT = 12
_W_CROWDED = 3


def _objective(fast_window_s: float, slow_window_s: float) -> Objective:
    # target 0.7 => warning at bad-fraction 0.3, burning at 0.6 on
    # both windows — tight enough that one saturated round pages,
    # short enough that recovery is observable in soak wall time
    return Objective("soak_edit_rtt", "soak.edit_rtt",
                     threshold_s=_RTT_THRESHOLD_S, target=0.7,
                     fast_window_s=fast_window_s,
                     slow_window_s=slow_window_s,
                     fast_burn=2.0, slow_burn=2.0)


def run_rebalance_soak(servers: int = 3, docs: int = 8, seed: int = 7,
                       capacity: int = 5, crowd_boost: int = 3,
                       healthy_rounds: int = 3,
                       crowd_rounds: int = 6,
                       recover_rounds: int = 60,
                       reconcile_rounds: int = 20,
                       flash_crowd: bool = True,
                       join: bool = True,
                       inject_abort: bool = True,
                       lease_ttl_s: float = 30.0,
                       fast_window_s: float = 3.0,
                       slow_window_s: float = 6.0,
                       progress: bool = False) -> dict:
    from ..tools.server import SyncClient, serve

    rng = random.Random(seed)
    doc_ids = [f"elastic-{i}" for i in range(docs)]
    # sample_rate=1.0 so every edit carries a journey — the verdict's
    # convergence-lag column needs advert_usable stamps to aggregate
    obs_opts = dict(sample_rate=1.0, ts_window_s=0.5, ts_windows=64,
                    objectives=[_objective(fast_window_s,
                                           slow_window_s)])
    node_opts = dict(seed=seed, lease_ttl_s=lease_ttl_s,
                     probe_interval_s=0.25,
                     antientropy_interval_s=0.25,
                     timeout_s=2.0, backoff_base_s=0.02,
                     backoff_cap_s=0.1)
    # act only on burning: the gate's SLO journey must REACH burning
    # before the first migration cures the crowd — acting on warning
    # too (the default) would race the journey against the fix under
    # wall-clock contention
    rb_opts = dict(cooldown_s=0.2, max_migrations_per_tick=1,
                   min_load_gap=2, top_n=4, act_on=("burning",))

    httpds: List = []
    nodes: List = []
    addrs: List[str] = []

    def boot(join_to: Optional[str] = None):
        httpd = serve(port=0, serve_shards=1, follower_reads=True,
                      obs_opts=dict(obs_opts))
        httpd.socket.listen(128)
        addr = f"127.0.0.1:{httpd.server_address[1]}"
        node = attach_replication(httpd, addr, [], **node_opts)
        attach_rebalancer(node, **rb_opts)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        if join_to is not None:
            node.join_mesh(join_to)
        return httpd, node, addr

    for i in range(servers):
        httpd = serve(port=0, serve_shards=1, follower_reads=True,
                      obs_opts=dict(obs_opts))
        httpd.socket.listen(128)
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    for i, httpd in enumerate(httpds):
        node = attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            **node_opts)
        attach_rebalancer(node, **rb_opts)
        nodes.append(node)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()

    migrations: List[List[str]] = []
    tick_aborts: List[List[str]] = []

    def step_control_plane() -> None:
        for n in nodes:
            n.table.probe_once()
            n.maintain()
        for n in nodes:
            rep = n.rebalancer.tick()
            migrations.extend(rep["migrated"])
            tick_aborts.extend(rep["aborted"])
        for n in nodes:
            n.antientropy.run_round()

    clients: Dict[tuple, SyncClient] = {}

    def client(i: int, doc_id: str) -> SyncClient:
        key = (i, doc_id)
        if key not in clients:
            clients[key] = SyncClient(
                f"http://{addrs[i]}", doc_id,
                f"agent-{i}-{doc_id}", retries=2)
        return clients[key]

    def edit(i: int, doc_id: str, word: str) -> bool:
        c = client(i, doc_id)
        try:
            c.pull()
        except OSError:
            pass
        c.insert(rng.randrange(len(c.text()) + 1), word + " ")
        try:
            c.sync()
            return True
        except OSError:
            return False

    def owner_of(doc_id: str):
        holders = [n for n in nodes
                   if n.leases.active_epoch(doc_id) > 0]
        return holders[0] if len(holders) == 1 else None

    crowd_target = None     # the host the SLO journey is tracked on
    hot_doc = doc_ids[0]    # re-picked after settle (most-loaded host)

    def observe_round(crowd_on: bool) -> None:
        """The load model: weighted RTT observations per doc at its
        owner. The crowd load FOLLOWS the hot doc — whichever host
        currently owns it carries the boost on top of its held-lease
        count, so migrating the hot doc to a host with headroom (and
        only that) is what restores the SLO."""
        hot_owner = owner_of(hot_doc) if crowd_on else None
        for doc_id in doc_ids:
            own = owner_of(doc_id)
            if own is None:
                continue
            eff = own.leases.held_count() \
                + (crowd_boost if own is hot_owner else 0)
            rtt = _RTT_BAD_S if eff > capacity else _RTT_GOOD_S
            if not crowd_on:
                weight = 1
            elif doc_id == hot_doc:
                weight = _W_HOT
            elif own is hot_owner:
                weight = _W_CROWDED
            else:
                weight = 1
            for _ in range(weight):
                own.obs.ts.observe("soak.edit_rtt", rtt)
            own.obs.attrib.note("ops", doc=doc_id, n=float(weight))

    def slo_state() -> str:
        if crowd_target is None:
            return "ok"
        return crowd_target.obs.slo.evaluate()[0]["state"]

    t0 = time.monotonic()
    edits = 0

    # ---- seed + settle: one ACTIVE owner per doc --------------------------
    for doc_id in doc_ids:
        if edit(rng.randrange(servers), doc_id, "seed"):
            edits += 1
    for _ in range(40):
        step_control_plane()
        if all(owner_of(d) is not None for d in doc_ids):
            break
        time.sleep(0.02)
    settled = all(owner_of(d) is not None for d in doc_ids)
    held_initial = {n.self_id: n.leases.held_count() for n in nodes}
    # the hot doc lives on the most-loaded host: with boost just under
    # capacity, saturation needs co-resident load, and the crowded
    # host only recovers by SHEDDING (a one-doc host never saturates)
    crowd_target = max(nodes, key=lambda n: n.leases.held_count())
    held = crowd_target.leases.held_ids()
    if held:
        hot_doc = held[0]

    states: List[str] = []

    # ---- healthy phase ----------------------------------------------------
    for _ in range(healthy_rounds):
        if edit(rng.randrange(servers), rng.choice(doc_ids), "calm"):
            edits += 1
        observe_round(crowd_on=False)
        step_control_plane()
        states.append(slo_state())
        time.sleep(0.02)
    healthy_state = states[-1] if states else "ok"

    joined_addr: Optional[str] = None
    joined_node = None
    burn_seen = False

    # ---- flash crowd ------------------------------------------------------
    if flash_crowd and crowd_target is not None:
        # adaptive: at least crowd_rounds, and keep crowding until the
        # SLO actually reaches burning (capped) — window rollover
        # timing under a loaded machine must not decide the journey
        max_crowd = max(crowd_rounds, 40)
        r = -1
        while (r := r + 1) < crowd_rounds \
                or (not burn_seen and r < max_crowd):
            for _ in range(2):
                if edit(rng.randrange(len(addrs)), hot_doc, "crowd"):
                    edits += 1
            if edit(rng.randrange(len(addrs)),
                    rng.choice(doc_ids), "bg"):
                edits += 1
            observe_round(crowd_on=True)
            st = slo_state()
            states.append(st)
            burn_seen = burn_seen or st == "burning"
            # scale-out response: the join lands BEFORE this round's
            # rebalancer tick, so the fresh (load 0) host is already
            # the preferred target when migrations are planned
            if st != "ok" and join and joined_node is None:
                httpd, joined_node, joined_addr = boot(
                    join_to=addrs[0])
                httpds.append(httpd)
                nodes.append(joined_node)
                addrs.append(joined_addr)
                if progress:
                    print(f"crowd round {r + 1}: slo={st}; "
                          f"joined {joined_addr}")
            step_control_plane()
            if progress:
                print(f"crowd round {r + 1}: slo={st} target.held="
                      f"{crowd_target.leases.held_count()} "
                      f"migrations={len(migrations)}")
            time.sleep(0.05)

        # ---- recovery: the crowd keeps running ----------------------------
        for r in range(recover_rounds):
            if edit(rng.randrange(len(addrs)), hot_doc, "crowd"):
                edits += 1
            observe_round(crowd_on=True)
            step_control_plane()
            st = slo_state()
            states.append(st)
            if st == "ok":
                break
            time.sleep(0.25)

    # ---- seeded abort: migration at an unreachable target -----------------
    abort_rollback_ok = None
    if inject_abort:
        victims = [n for n in nodes if n.leases.held_count() > 0]
        src = victims[0] if victims else nodes[0]
        doc_id = src.leases.held_ids()[0]
        epoch_before = src.leases.active_epoch(doc_id)
        aborted_before = src.metrics.get("rebalance",
                                         "migrations_aborted")
        moved = src.rebalancer.migrate(doc_id, "127.0.0.1:1")
        abort_rollback_ok = (
            not moved
            and src.leases.active_epoch(doc_id) == epoch_before
            and epoch_before > 0
            and src.overrides.target_of(doc_id) is None
            and src.metrics.get("rebalance", "migrations_aborted")
            == aborted_before + 1)

    # ---- reconcile to convergence -----------------------------------------
    converged_after = None
    for r in range(reconcile_rounds):
        step_control_plane()
        if _converged(addrs, doc_ids):
            converged_after = r + 1
            break
        time.sleep(0.05)
    texts = _final_texts(addrs, doc_ids)
    converged = all(len(set(v.values())) == 1 for v in texts.values())
    split_brain = _split_brain(nodes)

    slo_journey_ok = (not flash_crowd) or (
        healthy_state == "ok" and burn_seen
        and bool(states) and states[-1] == "ok")
    join_absorbed = (not (flash_crowd and join)) or (
        joined_node is not None
        and (joined_node.leases.held_count() > 0
             or any(n.overrides.target_of(d) == joined_addr
                    for n in nodes for d in doc_ids)))
    ok = bool(
        settled and converged and not split_brain
        and slo_journey_ok and join_absorbed
        and (not flash_crowd or len(migrations) >= 1)
        and (abort_rollback_ok is None or abort_rollback_ok))

    report = {
        "config": {"servers": servers, "docs": docs, "seed": seed,
                   "capacity": capacity, "crowd_boost": crowd_boost,
                   "flash_crowd": flash_crowd, "join": join,
                   "inject_abort": inject_abort,
                   "lease_ttl_s": lease_ttl_s},
        "edits_applied": edits,
        "settled": settled,
        "held_initial": held_initial,
        "crowd_target": getattr(crowd_target, "self_id", None),
        "hot_doc": hot_doc,
        "slo_states": states,
        "slo_journey_ok": slo_journey_ok,
        "burning_seen": burn_seen,
        "migrations": migrations,
        "tick_aborts": tick_aborts,
        "joined": joined_addr,
        "join_absorbed": join_absorbed,
        "abort_rollback_ok": abort_rollback_ok,
        "held_final": {n.self_id: n.leases.held_count()
                       for n in nodes},
        "override_tables": {n.self_id: n.overrides.size()
                            for n in nodes},
        "converged": converged,
        "converged_after_reconcile_rounds": converged_after,
        "split_brain": split_brain,
        "zero_split_brain": not split_brain,
        "wall_s": round(time.monotonic() - t0, 3),
        "metrics": {n.self_id: n.metrics_json() for n in nodes},
        # edit-to-visibility per peer (admitted -> advert_usable); a
        # migration that stalls replication shows up here even when
        # the lease counters look healthy
        "convergence_lag": {
            n.self_id: n.obs.journey.lag_summary()
            for n in nodes if getattr(n, "obs", None) is not None},
        "ok": ok,
    }
    if not ok:
        events = []
        for n in nodes:
            obs = getattr(n, "obs", None)
            if obs is None:
                continue
            for ev in obs.recorder.tail(50):
                events.append(dict(ev, node=n.self_id))
        events.sort(key=lambda e: e.get("t", 0.0))
        report["events_tail"] = events[-50:]
    for httpd in httpds:
        httpd.shutdown()
        httpd.server_close()
    return report


def main(argv=None) -> int:  # pragma: no cover - exercised via cli.py
    import argparse
    p = argparse.ArgumentParser(prog="rebalance-soak")
    p.add_argument("--servers", type=int, default=3)
    p.add_argument("--docs", type=int, default=8)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--flash-crowd", action="store_true")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    report = run_rebalance_soak(servers=args.servers, docs=args.docs,
                                seed=args.seed,
                                flash_crowd=args.flash_crowd)
    print(json.dumps(report if args.json else {
        k: report[k] for k in ("ok", "slo_journey_ok", "burning_seen",
                               "migrations", "join_absorbed",
                               "abort_rollback_ok", "converged",
                               "zero_split_brain", "wall_s")}))
    return 0 if report["ok"] else 1
