"""Writer groups: hot-doc write splitting over the lease protocol.

A doc whose write SLO keeps burning is capped by its single ACTIVE
lease holder — migration (replicate/rebalance.py) moves whole docs, so
one viral doc still funnels through one host. The CRDT itself is
multi-writer by construction (OpLog merge is deterministic from any
interleaving), so the wall is pure policy: the lease system made docs
single-writer for device efficiency, not correctness.

A *writer group* splits the write path for one doc:

  * **Promotion** (leader = the current ACTIVE holder) runs a quorum
    round at a bumped epoch — `max(lease.epoch, floor) + 1`, the same
    planning rule every acquisition uses — then re-keys its own ACTIVE
    lease to that epoch (`LeaseManager.promote_epoch`) and records the
    member set at it, journaled like any lease state. Members receive a
    directed group grant over `/replicate/lease`; installing it folds
    the leader's lease claim (raising the member's fencing floor to the
    group epoch) and registers a TTL-bounded entry.

  * **Member writes** are admitted locally (`ReplicaNode.owns` /
    `group_accepts`) and stamped with the group epoch — fenced exactly
    like `X-DT-Lease-Epoch` proxied writes: a floor that passes the
    group epoch invalidates the registration. Convergence rides the
    existing anti-entropy + merge path; nothing new is needed there
    because merge order never mattered.

  * **Demotion is the robustness centerpiece.** The group drains back
    to one writer by bumping the epoch once more: the leader runs a
    quorum round at `group_epoch + 1`, fences every member (reachable
    members drain their pending admissions into the oplog, drop the
    registration and evict their admission queue; an unreachable
    member must first be provably past its registration TTL — the
    demotion epoch is never committed while a silent member could
    still be accepting), then re-keys its lease. Replayed grants from
    the superseded group are refused at install time (`epoch < floor`).

  * **Self-fencing**: a member that cannot reach the leader plus a
    majority of the group, or whose registration expired un-renewed,
    stops accepting writes immediately (proxy-only) rather than
    accumulating acked edits the group may already have fenced away.
    Registrations are renewed through the leader on the maintain loop.

Epochs are shared with the lease space on purpose: every existing
fencing mechanism (floors, 409s on stale claims, journal restore,
rejoining fences) applies to group state with no parallel machinery.
The model checker covers the protocol first — see
analysis/explore/model.py's `writer-group` scenario, the
`group-epoch-exclusivity` invariant, and the `demote-without-drain` /
`promote-floor-drop` seeded mutations.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple


class WriterGroup:
    __slots__ = ("doc_id", "epoch", "members", "leader", "expires_at")

    def __init__(self, doc_id: str, epoch: int,
                 members: Sequence[str], leader: str,
                 expires_at: float) -> None:
        self.doc_id = doc_id
        self.epoch = epoch
        self.members = tuple(sorted(members))
        self.leader = leader
        self.expires_at = expires_at

    def quorum_size(self) -> int:
        return len(self.members) // 2 + 1

    def as_json(self, now: float) -> dict:
        return {"epoch": self.epoch, "members": list(self.members),
                "leader": self.leader,
                "ttl_s": round(max(self.expires_at - now, 0.0), 3)}


class WriterGroupTable:
    """Per-host writer-group registrations (one entry per doc this host
    is a member or leader of), journaled alongside the lease table.

    Lock discipline: the table lock is a *late* rung — it is taken
    while holding the lease lock (the floor-raise hook fences entries
    atomically with the floor) and never the other way around, and no
    method calls into the lease manager, peer table, or network while
    holding it. Every method is a pure dict operation plus at most a
    journal append (the journal lock is a leaf).
    """

    def __init__(self, self_id: str, ttl_s: float = 4.0,
                 metrics=None,
                 clock: Optional[Callable[[], float]] = None) -> None:
        import time
        self.self_id = self_id
        self.ttl_s = ttl_s
        self.metrics = metrics
        self.clock: Callable[[], float] = \
            time.monotonic if clock is None else clock
        self.journal = None
        self.groups: Dict[str, WriterGroup] = {}
        from ..analysis.witness import make_lock
        self.lock = make_lock("repl.writergroup", "repl.writergroup")

    def _bump(self, key: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.bump("writergroup", key, n)

    # ---- crash-restart restore -------------------------------------------

    def restore(self, journal,
                floor_of: Callable[[str], int]) -> int:
        """Adopt journaled group registrations at boot. Entries are
        restored EXPIRED (accepting again requires a fresh renewal
        through the leader — the rejoining fence denies admits anyway)
        and entries below the restored fencing floor are not restored
        at all: the group they belonged to has been superseded."""
        n = 0
        now = self.clock()
        with self.lock:
            for doc, info in journal.restored_groups().items():
                if int(info.get("epoch", 0)) < floor_of(doc):
                    continue
                self.groups[doc] = WriterGroup(
                    doc, int(info["epoch"]),
                    [str(m) for m in info.get("members", [])],
                    str(info.get("leader", "")), now)
                n += 1
        self.journal = journal
        return n

    # ---- views ------------------------------------------------------------

    def get(self, doc_id: str) -> Optional[WriterGroup]:
        with self.lock:
            return self.groups.get(doc_id)

    def entries(self) -> List[Tuple[str, WriterGroup]]:
        with self.lock:
            return sorted(self.groups.items())

    def peer_set(self) -> frozenset:
        """Every OTHER host that co-writes some doc with us — the
        anti-entropy loop reconciles these peers first so in-group
        visibility stays tight."""
        with self.lock:
            return frozenset(
                m for g in self.groups.values() for m in g.members
                if m != self.self_id)

    def sizes(self) -> Dict[str, int]:
        """Snapshot-time gauges injected into the metrics block."""
        with self.lock:
            led = sum(1 for g in self.groups.values()
                      if g.leader == self.self_id)
            return {"active_groups": led,
                    "member_entries": len(self.groups) - led}

    def fingerprint(self) -> dict:
        """Deterministic state digest for the model checker."""
        with self.lock:
            return {d: [g.epoch, list(g.members), g.leader,
                        round(g.expires_at, 6)]
                    for d, g in sorted(self.groups.items())}

    def as_json(self) -> dict:
        now = self.clock()
        with self.lock:
            return {d: g.as_json(now)
                    for d, g in sorted(self.groups.items())}

    # ---- mutation ----------------------------------------------------------

    def install(self, doc_id: str, epoch: int,
                members: Sequence[str], leader: str,
                floor: int) -> bool:
        """Record a group registration. Refuses epochs below the
        caller-supplied fencing floor — a replayed grant from a
        superseded group must not resurrect it. Idempotent re-installs
        at the current epoch refresh the TTL (renewal propagation)."""
        if epoch < floor:
            return False
        now = self.clock()
        with self.lock:
            cur = self.groups.get(doc_id)
            if cur is not None and cur.epoch > epoch:
                return False
            self.groups[doc_id] = WriterGroup(
                doc_id, epoch, members, leader, now + self.ttl_s)
        if self.journal is not None:
            self.journal.note_group(doc_id, epoch,
                                    sorted(members), leader)
        return True

    def refresh(self, doc_id: str, epoch: int) -> bool:
        """Extend the registration TTL (a successful renewal round
        trip, or the leader folding a member's renewal)."""
        now = self.clock()
        with self.lock:
            g = self.groups.get(doc_id)
            if g is None or g.epoch != epoch:
                return False
            g.expires_at = now + self.ttl_s
            return True

    def drop(self, doc_id: str,
             at_or_below: Optional[int] = None) -> bool:
        """Remove a registration. `at_or_below` guards replayed
        demotions: a demote for epoch E must not fence a NEWER group
        registered after it."""
        with self.lock:
            g = self.groups.get(doc_id)
            if g is None:
                return False
            if at_or_below is not None and g.epoch > at_or_below:
                return False
            del self.groups[doc_id]
        if self.journal is not None:
            self.journal.drop_group(doc_id)
        return True

    def fence_below(self, doc_id: str, floor: int) -> None:
        """Floor-raise hook (wired to LeaseManager.on_floor_raise,
        called UNDER the lease lock): a fencing floor that passes a
        registration's epoch supersedes the group — drop the entry in
        the same critical section so no admit can slip between the
        floor raise and the fence. Pending admissions are NOT touched
        here; they flush into the oplog on the next drain (acked work
        survives — only the right to accept new work is revoked)."""
        with self.lock:
            g = self.groups.get(doc_id)
            if g is None or g.epoch >= floor:
                return
            del self.groups[doc_id]
        if self.journal is not None:
            self.journal.drop_group(doc_id)
        self._bump("self_fenced")
