"""Doc-ownership leases on rendezvous placement extended to hosts.

Placement reuses the exact scheme serve/router.py proved out for chips
— blake2b rendezvous (highest-random-weight) over the candidate set —
but the candidates are *host ids* (`host:port`) and the set is the
membership universe (membership.MembershipView.universe: ALIVE +
SUSPECT members). Every host computes the same owner for a doc given
the same view; transient view disagreements are resolved by the lease
epoch, and convergence never depends on ownership anyway (anti-entropy
replicates to non-owners).

A lease is a host-local assertion "I run doc X's device merges until
`expires_at`". Exactly-one-merger comes from the combination:

  * a host only admits scheduler work for docs whose ACTIVE lease it
    holds (`LeaseManager.ensure_local` — consulted by the scheduler's
    admit gate);
  * becoming ACTIVE at epoch E requires a MAJORITY of the voter set to
    promise (doc, E) to this holder (quorum.QuorumCoordinator). A
    voter promises an epoch to at most one holder, so two majorities
    for one (doc, E) cannot both exist: at most one ACTIVE lease per
    (doc, epoch), under any partition/crash/churn combination. With no
    quorum hook attached (standalone use, tests) acquisition is
    immediate — PR 2's TTL-delayed behavior;
  * epochs are FENCING tokens: every promise or observation of epoch E
    raises this host's per-doc floor `max_epoch[doc]`; an ACTIVE lease
    below the floor has been superseded and is revoked on its next
    admit check, and proxied writes claiming a below-floor epoch are
    rejected (HTTP 409), not merged;
  * moving ownership while both hosts are alive goes through the
    explicit handoff state machine (driven by node.ReplicaNode):

        ACTIVE --grant sent--> GRANTING --scheduler drained-->
        DRAINING --final patch pushed--> TRANSFER --activate acked-->
        RELEASED (local) / ACTIVE (remote, epoch+1)

    A failure at any step rolls the local lease back to ACTIVE (same
    epoch); the remote side's granted-but-never-activated lease simply
    expires. The doc keeps exactly one active merger throughout. The
    receiver's GRANTED→ACTIVE flip is the step that runs the quorum
    round (one round per handoff covers the new epoch).

Equal-epoch arbitration (`observe_remote`): two differing holders at
one epoch can only reach us through pre-quorum history or observation
races — the quorum protocol itself cannot mint them. The rule is
deterministic and symmetric on every host regardless of arrival order:
the lexically SMALLER holder id wins (the same tie-break rendezvous
uses for score ties), and each arbitration is counted
(`leases.tie_breaks`).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .metrics import ReplicationMetrics

# lease states
ACTIVE = "active"        # we merge this doc
GRANTING = "granting"    # handoff: grant offered to the new owner
DRAINING = "draining"    # handoff: draining our pending merges
TRANSFER = "transfer"    # handoff: pushing the final patch
GRANTED = "granted"      # remote offered US the lease; not active yet
RELEASED = "released"    # terminal; kept briefly for observability

_HANDOFF_STATES = (GRANTING, DRAINING, TRANSFER)

# cap on the activation history kept for split-brain auditing
_ACTIVATION_LOG_MAX = 4096


def _score(doc_id: str, host_id: str, salt: bytes) -> int:
    h = hashlib.blake2b(digest_size=8, salt=salt[:16])
    h.update(doc_id.encode("utf8"))
    h.update(host_id.encode("utf8"))
    return int.from_bytes(h.digest(), "little")


def owner_of(doc_id: str, host_ids: Sequence[str],
             salt: str = "dt-replicate") -> str:
    """Rendezvous owner of `doc_id` among `host_ids` — pure function of
    its arguments, so every process that sees the same healthy set
    picks the same owner (ties broken by the lexically smaller id)."""
    if not host_ids:
        raise ValueError("empty host set")
    salt_b = salt.encode("utf8")
    best, best_score = None, -1
    for hid in sorted(host_ids):
        sc = _score(doc_id, hid, salt_b)
        if sc > best_score:
            best, best_score = hid, sc
    return best


class Lease:
    __slots__ = ("doc_id", "holder", "epoch", "state", "expires_at",
                 "granted_at")

    def __init__(self, doc_id: str, holder: str, epoch: int,
                 state: str, expires_at: float,
                 now: Optional[float] = None) -> None:
        self.doc_id = doc_id
        self.holder = holder
        self.epoch = epoch
        self.state = state
        self.expires_at = expires_at     # monotonic, local clock
        self.granted_at = time.monotonic() if now is None else now

    def expired(self, now: Optional[float] = None) -> bool:
        return (time.monotonic() if now is None else now) \
            >= self.expires_at

    def as_json(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        # TTL remaining, not absolute time: peer clocks are not synced
        return {"holder": self.holder, "epoch": self.epoch,
                "state": self.state,
                "ttl_s": round(max(self.expires_at - now, 0.0), 3)}


class LeaseManager:
    """Host-local lease records for every doc this host has an opinion
    about (its own leases + leases observed from peers via grant
    messages and /replicate/docs piggyback), plus the voter-side quorum
    state: the promise table and the per-doc fencing floors."""

    def __init__(self, self_id: str, ttl_s: float = 2.0,
                 metrics: Optional[ReplicationMetrics] = None,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.self_id = self_id
        self.ttl_s = ttl_s
        self.metrics = metrics
        # time source for every lease decision; the model checker
        # (analysis/explore) injects a virtual clock here
        self.clock: Callable[[], float] = \
            time.monotonic if clock is None else clock
        self.leases: Dict[str, Lease] = {}
        # per-doc fencing floor: highest epoch ever promised/observed
        self.max_epoch: Dict[str, int] = {}
        # voter promise table: doc -> (epoch, holder); an epoch is
        # promised to AT MOST one holder (the quorum safety core)
        self.promised: Dict[str, Tuple[int, str]] = {}
        # every local transition to ACTIVE, for split-brain audits
        self.activation_log: List[dict] = []
        # hooks wired by node.ReplicaNode: quorum(doc, epoch, takeover)
        # runs the majority round (called with NO locks held); journal
        # persists floors/promises/held leases across restarts
        self.quorum: Optional[Callable[[str, int, bool], bool]] = None
        self.journal = None
        # floor-raise hook (wired by node.ReplicaNode to
        # WriterGroupTable.fence_below): called UNDER self.lock every
        # time a doc's fencing floor rises, so group registrations the
        # new floor supersedes are fenced in the same critical section
        self.on_floor_raise: Optional[Callable[[str, int], None]] = None
        # obs.recorder.FlightRecorder (wired by node.ReplicaNode);
        # every lease transition is rare enough to record
        self.recorder = None
        from ..analysis.witness import make_lock
        self.lock = make_lock("repl.leases", "repl.leases",
                              reentrant=True)

    def _bump(self, key: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.bump("leases", key, n)

    def _bump_group(self, group: str, key: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.bump(group, key, n)

    def _event(self, kind: str, doc_id: str, epoch: int,
               **fields) -> None:
        r = self.recorder
        if r is not None:
            r.record(kind, doc=doc_id, epoch=epoch, **fields)

    # ---- fencing floor / journal (callers hold self.lock) ----------------

    def _note_epoch_locked(self, doc_id: str, epoch: int) -> None:
        if epoch > self.max_epoch.get(doc_id, 0):
            self.max_epoch[doc_id] = epoch
            if self.journal is not None:
                self.journal.note_epoch(doc_id, epoch)
            if self.on_floor_raise is not None:
                self.on_floor_raise(doc_id, epoch)

    def _log_activation_locked(self, doc_id: str, epoch: int) -> None:
        self.activation_log.append(
            {"doc": doc_id, "epoch": epoch, "holder": self.self_id,
             "t": self.clock()})
        if len(self.activation_log) > _ACTIVATION_LOG_MAX:
            del self.activation_log[:_ACTIVATION_LOG_MAX // 4]

    def max_epoch_of(self, doc_id: str) -> int:
        with self.lock:
            return self.max_epoch.get(doc_id, 0)

    def activation_history(self) -> List[dict]:
        with self.lock:
            return list(self.activation_log)

    # ---- crash-restart restore -------------------------------------------

    def restore(self, journal) -> int:
        """Adopt journal state at boot. Fencing floors and the promise
        table are restored verbatim (the safety payload: a recovered
        voter must never re-promise a taken epoch, and a recovered
        holder must never re-issue a stale one). Leases this host HELD
        are restored as RELEASED — their epoch feeds the next
        acquisition plan (`max(epoch, floor) + 1`), but serving them
        again requires a fresh quorum round."""
        n = 0
        with self.lock:
            for doc, e in journal.restored_max_epochs().items():
                if e > self.max_epoch.get(doc, 0):
                    self.max_epoch[doc] = e
                    n += 1
            for doc, p in journal.restored_promises().items():
                cur = self.promised.get(doc)
                if cur is None or p["epoch"] > cur[0]:
                    self.promised[doc] = (int(p["epoch"]),
                                          str(p["holder"]))
            now = self.clock()
            for doc, info in journal.restored_leases().items():
                if doc in self.leases:
                    continue
                holder = str(info["holder"])
                state = RELEASED if holder == self.self_id \
                    else str(info.get("state", ACTIVE))
                # expires_at = now: an expired hint, never admissible
                self.leases[doc] = Lease(doc, holder,
                                         int(info["epoch"]), state, now,
                                         now=now)
        self.journal = journal
        return n

    # ---- views -----------------------------------------------------------

    def get(self, doc_id: str) -> Optional[Lease]:
        with self.lock:
            return self.leases.get(doc_id)

    def held_ids(self) -> List[str]:
        with self.lock:
            return sorted(d for d, l in self.leases.items()
                          if l.holder == self.self_id
                          and l.state in (ACTIVE,) + _HANDOFF_STATES)

    def held_count(self) -> int:
        return len(self.held_ids())

    def holder_of(self, doc_id: str,
                  now: Optional[float] = None) -> Optional[str]:
        """Current unexpired lease holder, if any is known."""
        with self.lock:
            lease = self.leases.get(doc_id)
            if lease is None or lease.state == RELEASED \
                    or lease.expired(self.clock() if now is None
                                     else now):
                return None
            return lease.holder

    def active_epoch(self, doc_id: str) -> int:
        """Epoch of the ACTIVE lease THIS host holds for the doc, or 0.
        The scheduler's flush-time fencing recheck keys on this."""
        with self.lock:
            lease = self.leases.get(doc_id)
            if lease is None or lease.holder != self.self_id \
                    or lease.state != ACTIVE:
                return 0
            return lease.epoch

    # ---- acquisition -----------------------------------------------------

    def ensure_local(self, doc_id: str, is_desired_owner: bool,
                     now: Optional[float] = None) -> bool:
        """The merge-admission question: may THIS host run doc X's
        merges right now? Renewal of a held ACTIVE lease is local; a
        NEW acquisition (first grant or takeover) is planned under the
        lock, put through the quorum hook with the lock RELEASED (the
        round is network I/O), and committed under the lock with
        re-validation. Returns False while another host's unexpired
        lease stands, during our own outbound handoff, while a quorum
        round is lost, or when our lease has been fenced off."""
        now = self.clock() if now is None else now
        plan = self._admit_or_plan(doc_id, is_desired_owner, now)
        if plan is True or plan is False:
            return plan
        epoch, takeover = plan
        if self.quorum is not None \
                and not self.quorum(doc_id, epoch, takeover):
            return False
        return self._commit_acquire(doc_id, epoch, takeover, now)

    def _admit_or_plan(self, doc_id: str, is_desired_owner: bool,
                       now: float):
        """Under the lock: admit (True), deny (False), or return the
        (epoch, takeover) plan a quorum round must ratify."""
        with self.lock:
            lease = self.leases.get(doc_id)
            floor = self.max_epoch.get(doc_id, 0)
            if lease is not None and lease.holder == self.self_id:
                if lease.state == ACTIVE:
                    if lease.epoch < floor:
                        # superseded: a higher epoch was promised or
                        # observed — the fencing token revokes us
                        del self.leases[doc_id]
                        self._bump_group("fencing",
                                         "stale_lease_revoked")
                        self._event("lease_fenced", doc_id,
                                    lease.epoch, floor=floor)
                        if self.journal is not None:
                            self.journal.drop_lease(doc_id)
                        return False
                    if not is_desired_owner:
                        # placement moved away; keep serving until the
                        # handoff runs (node drives it) — merges must
                        # not stall in the gap
                        pass
                    lease.expires_at = now + self.ttl_s
                    self._bump("renewals")
                    return True
                if lease.state in _HANDOFF_STATES:
                    return False     # outbound handoff in progress
                if lease.state == GRANTED:
                    # we were offered the lease but activation hasn't
                    # arrived; the grantor is still draining/merging
                    return False
            if not is_desired_owner:
                return False
            if lease is not None and lease.holder != self.self_id \
                    and not lease.expired(now):
                return False         # live remote lease wins
            # free (no lease, expired, or released): plan the acquire
            epoch = max(lease.epoch if lease is not None else 0,
                        floor) + 1
            takeover = (lease is not None
                        and lease.holder != self.self_id
                        and lease.state != RELEASED)
            return (epoch, takeover)

    def _commit_acquire(self, doc_id: str, epoch: int, takeover: bool,
                        now: float) -> bool:
        """Re-validate and activate after the (lock-free) quorum round:
        the plan is void if a live conflicting lease or a higher
        promise appeared meanwhile."""
        with self.lock:
            lease = self.leases.get(doc_id)
            if lease is not None and lease.holder != self.self_id \
                    and not lease.expired(now) and lease.epoch >= epoch:
                return False
            floor = self.max_epoch.get(doc_id, 0)
            if floor > epoch or (
                    floor == epoch and self.promised.get(doc_id)
                    not in (None, (epoch, self.self_id))):
                return False
            self.leases[doc_id] = Lease(doc_id, self.self_id, epoch,
                                        ACTIVE, now + self.ttl_s,
                                        now=now)
            self._note_epoch_locked(doc_id, epoch)
            self._log_activation_locked(doc_id, epoch)
            self._bump("takeovers" if takeover else "acquires")
            self._event("lease_acquired", doc_id, epoch,
                        takeover=takeover)
            if self.journal is not None:
                self.journal.note_lease(doc_id, self.self_id, epoch,
                                        ACTIVE)
            return True

    def promote_epoch(self, doc_id: str, epoch: int) -> bool:
        """Writer-group rekey: move our own ACTIVE lease to `epoch` — a
        strictly higher, quorum-ratified bump — without ever leaving
        ACTIVE. Promotion registers the member set at the new epoch;
        demotion bumps once more so every member grant below it is
        fenced by the ordinary floor machinery. The caller MUST have
        won the quorum round for `epoch` first (node-level), exactly
        like a handoff activation."""
        now = self.clock()
        with self.lock:
            lease = self.leases.get(doc_id)
            if lease is None or lease.holder != self.self_id \
                    or lease.state != ACTIVE or epoch <= lease.epoch:
                return False
            lease.epoch = epoch
            lease.expires_at = now + self.ttl_s
            self._note_epoch_locked(doc_id, epoch)
            self._log_activation_locked(doc_id, epoch)
            self._event("lease_rekeyed", doc_id, epoch)
            if self.journal is not None:
                self.journal.note_lease(doc_id, self.self_id, epoch,
                                        ACTIVE)
            return True

    # ---- voter side of the quorum round ----------------------------------

    def promise(self, doc_id: str, epoch: int, holder: str,
                now: Optional[float] = None) -> Tuple[bool, str]:
        """May `holder` become ACTIVE for (doc_id, epoch)? The promise
        is binding and exclusive: once granted, no OTHER holder can be
        promised the same (doc, epoch) by this voter — ever (the table
        survives restarts via the journal). Granting also raises the
        fencing floor, so a superseded local lease self-revokes.
        Returns (ok, reason)."""
        now = self.clock() if now is None else now
        with self.lock:
            if epoch < self.max_epoch.get(doc_id, 0):
                return False, "stale_epoch"
            p = self.promised.get(doc_id)
            if p is not None:
                p_epoch, p_holder = p
                if epoch < p_epoch:
                    return False, "promised_higher"
                if epoch == p_epoch and holder != p_holder:
                    self._bump_group("quorum", "promise_conflicts")
                    self._event("promise_conflict", doc_id, epoch,
                                holder=holder, promised_to=p_holder)
                    return False, "promise_conflict"
            cur = self.leases.get(doc_id)
            if cur is not None and cur.holder != holder \
                    and cur.state != RELEASED \
                    and not cur.expired(now) and cur.epoch >= epoch:
                return False, "live_lease"
            if p != (epoch, holder):
                self.promised[doc_id] = (epoch, holder)
                if self.journal is not None:
                    self.journal.note_promise(doc_id, epoch, holder)
            self._note_epoch_locked(doc_id, epoch)
            return True, "promised"

    # ---- remote observations ---------------------------------------------

    def observe_remote(self, doc_id: str, holder: str, epoch: int,
                       state: str, ttl_s: float) -> None:
        """Fold a peer's lease claim (grant message or /replicate/docs
        piggyback). Higher epoch wins. Equal epoch + same holder
        refreshes the record (renewal propagation) — except our own
        lease, whose TTL only we manage (a peer's echo must never
        shorten it). Equal epoch + DIFFERING holders is the arbitration
        event documented in the module docstring: lexically smaller
        holder id wins, counted in `leases.tie_breaks`."""
        now = self.clock()
        with self.lock:
            cur = self.leases.get(doc_id)
            if cur is not None:
                if cur.epoch > epoch:
                    return
                if cur.epoch == epoch:
                    if cur.holder == holder:
                        if cur.holder == self.self_id:
                            return
                        cur.state = state
                        cur.expires_at = now + max(ttl_s, 0.0)
                        return
                    self._bump("tie_breaks")
                    self._event("lease_tie_break", doc_id, epoch,
                                incumbent=cur.holder, claimant=holder)
                    if cur.holder < holder:
                        return       # incumbent (smaller id) wins
                    # incoming smaller id wins: fall through, replace
            self.leases[doc_id] = Lease(
                doc_id, holder, epoch, state, now + max(ttl_s, 0.0),
                now=now)
            self._note_epoch_locked(doc_id, epoch)

    def accept_grant(self, doc_id: str, epoch: int,
                     ttl_s: float) -> bool:
        """Remote handoff step 1 (receiver): record the offered lease
        as GRANTED-not-active. Idempotent; refuses stale epochs (both
        vs the current lease and vs the fencing floor)."""
        now = self.clock()
        with self.lock:
            if epoch < self.max_epoch.get(doc_id, 0):
                return False
            cur = self.leases.get(doc_id)
            if cur is not None and cur.epoch >= epoch \
                    and not (cur.holder == self.self_id
                             and cur.epoch == epoch):
                return False
            self.leases[doc_id] = Lease(doc_id, self.self_id, epoch,
                                        GRANTED, now + max(ttl_s, 0.0),
                                        now=now)
            self._note_epoch_locked(doc_id, epoch)
            self._event("lease_granted", doc_id, epoch)
            return True

    def activate_grant(self, doc_id: str, epoch: int) -> bool:
        """Remote handoff final step (receiver): flip GRANTED→ACTIVE.
        Idempotent (duplicate activate messages are harmless). The
        quorum round for the new epoch runs BEFORE this (node-level),
        so activation here is purely local state."""
        now = self.clock()
        with self.lock:
            cur = self.leases.get(doc_id)
            if cur is None or cur.holder != self.self_id \
                    or cur.epoch != epoch:
                return False
            if cur.state == ACTIVE:
                return True
            if cur.state != GRANTED:
                return False
            cur.state = ACTIVE
            cur.expires_at = now + self.ttl_s
            self._note_epoch_locked(doc_id, epoch)
            self._log_activation_locked(doc_id, epoch)
            self._bump("acquires")
            self._event("lease_activated", doc_id, epoch)
            if self.journal is not None:
                self.journal.note_lease(doc_id, self.self_id, epoch,
                                        ACTIVE)
            return True

    # ---- handoff (sender side; steps driven by node.ReplicaNode) ---------

    def begin_handoff(self, doc_id: str) -> Optional[int]:
        """ACTIVE → GRANTING. Returns the epoch the NEW owner's lease
        will carry (max of ours and the fencing floor, plus one), or
        None if we don't hold the doc."""
        with self.lock:
            lease = self.leases.get(doc_id)
            if lease is None or lease.holder != self.self_id \
                    or lease.state != ACTIVE:
                return None
            lease.state = GRANTING
            new_epoch = max(lease.epoch,
                            self.max_epoch.get(doc_id, 0)) + 1
            self._event("handoff_granting", doc_id, new_epoch)
            return new_epoch

    def advance_handoff(self, doc_id: str, state: str) -> None:
        assert state in (DRAINING, TRANSFER)
        with self.lock:
            lease = self.leases[doc_id]
            lease.state = state
            self._event(f"handoff_{state}", doc_id, lease.epoch)

    def finish_handoff(self, doc_id: str, new_holder: str,
                       new_epoch: int) -> None:
        """Local release + record the new owner's active lease."""
        now = self.clock()
        with self.lock:
            self.leases[doc_id] = Lease(doc_id, new_holder, new_epoch,
                                        ACTIVE, now + self.ttl_s,
                                        now=now)
            self._note_epoch_locked(doc_id, new_epoch)
            self._bump("releases")
            self._event("lease_released", doc_id, new_epoch,
                        new_holder=new_holder)
            if self.journal is not None:
                self.journal.note_lease(doc_id, new_holder, new_epoch,
                                        ACTIVE)

    def abort_handoff(self, doc_id: str) -> None:
        """Roll a failed handoff back to ACTIVE (same epoch): the
        receiver's GRANTED lease is never activated and just expires."""
        with self.lock:
            lease = self.leases.get(doc_id)
            if lease is not None and lease.holder == self.self_id \
                    and lease.state in _HANDOFF_STATES:
                lease.state = ACTIVE
                lease.expires_at = self.clock() + self.ttl_s
                self._event("handoff_aborted", doc_id, lease.epoch)

    # ---- export ----------------------------------------------------------

    def as_json(self) -> dict:
        now = self.clock()
        with self.lock:
            return {d: lease.as_json(now)
                    for d, lease in sorted(self.leases.items())}
