"""Doc-ownership leases on rendezvous placement extended to hosts.

Placement reuses the exact scheme serve/router.py proved out for chips
— blake2b rendezvous (highest-random-weight) over the candidate set —
but the candidates are *host ids* (`host:port`) and the set is the
*currently healthy* mesh (PeerTable.healthy_ids). Every host computes
the same owner for a doc given the same healthy set; transient health
disagreements are resolved by the lease epoch, and convergence never
depends on ownership anyway (anti-entropy replicates to non-owners).

A lease is a host-local assertion "I run doc X's device merges until
`expires_at`". Exactly-one-merger comes from the combination:

  * a host only admits scheduler work for docs whose ACTIVE lease it
    holds (`LeaseManager.ensure_local` — consulted by the scheduler's
    admit gate);
  * a host only acquires when rendezvous names it owner AND any known
    remote lease has expired (dead-owner takeover bumps the epoch);
  * moving ownership while both hosts are alive goes through the
    explicit handoff state machine (driven by node.ReplicaNode):

        ACTIVE --grant sent--> GRANTING --scheduler drained-->
        DRAINING --final patch pushed--> TRANSFER --activate acked-->
        RELEASED (local) / ACTIVE (remote, epoch+1)

    A failure at any step rolls the local lease back to ACTIVE (same
    epoch); the remote side's granted-but-never-activated lease simply
    expires. The doc keeps exactly one active merger throughout.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, List, Optional, Sequence

from .metrics import ReplicationMetrics

# lease states
ACTIVE = "active"        # we merge this doc
GRANTING = "granting"    # handoff: grant offered to the new owner
DRAINING = "draining"    # handoff: draining our pending merges
TRANSFER = "transfer"    # handoff: pushing the final patch
GRANTED = "granted"      # remote offered US the lease; not active yet
RELEASED = "released"    # terminal; kept briefly for observability

_HANDOFF_STATES = (GRANTING, DRAINING, TRANSFER)


def _score(doc_id: str, host_id: str, salt: bytes) -> int:
    h = hashlib.blake2b(digest_size=8, salt=salt[:16])
    h.update(doc_id.encode("utf8"))
    h.update(host_id.encode("utf8"))
    return int.from_bytes(h.digest(), "little")


def owner_of(doc_id: str, host_ids: Sequence[str],
             salt: str = "dt-replicate") -> str:
    """Rendezvous owner of `doc_id` among `host_ids` — pure function of
    its arguments, so every process that sees the same healthy set
    picks the same owner (ties broken by the lexically smaller id)."""
    if not host_ids:
        raise ValueError("empty host set")
    salt_b = salt.encode("utf8")
    best, best_score = None, -1
    for hid in sorted(host_ids):
        sc = _score(doc_id, hid, salt_b)
        if sc > best_score:
            best, best_score = hid, sc
    return best


class Lease:
    __slots__ = ("doc_id", "holder", "epoch", "state", "expires_at",
                 "granted_at")

    def __init__(self, doc_id: str, holder: str, epoch: int,
                 state: str, expires_at: float) -> None:
        self.doc_id = doc_id
        self.holder = holder
        self.epoch = epoch
        self.state = state
        self.expires_at = expires_at     # monotonic, local clock
        self.granted_at = time.monotonic()

    def expired(self, now: Optional[float] = None) -> bool:
        return (time.monotonic() if now is None else now) \
            >= self.expires_at

    def as_json(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        # TTL remaining, not absolute time: peer clocks are not synced
        return {"holder": self.holder, "epoch": self.epoch,
                "state": self.state,
                "ttl_s": round(max(self.expires_at - now, 0.0), 3)}


class LeaseManager:
    """Host-local lease records for every doc this host has an opinion
    about (its own leases + leases observed from peers via grant
    messages and /replicate/docs piggyback)."""

    def __init__(self, self_id: str, ttl_s: float = 2.0,
                 metrics: Optional[ReplicationMetrics] = None) -> None:
        self.self_id = self_id
        self.ttl_s = ttl_s
        self.metrics = metrics
        self.leases: Dict[str, Lease] = {}
        self.lock = threading.RLock()

    def _bump(self, key: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.bump("leases", key, n)

    # ---- views -----------------------------------------------------------

    def get(self, doc_id: str) -> Optional[Lease]:
        with self.lock:
            return self.leases.get(doc_id)

    def held_ids(self) -> List[str]:
        with self.lock:
            return sorted(d for d, l in self.leases.items()
                          if l.holder == self.self_id
                          and l.state in (ACTIVE,) + _HANDOFF_STATES)

    def held_count(self) -> int:
        return len(self.held_ids())

    def holder_of(self, doc_id: str,
                  now: Optional[float] = None) -> Optional[str]:
        """Current unexpired lease holder, if any is known."""
        with self.lock:
            lease = self.leases.get(doc_id)
            if lease is None or lease.state == RELEASED \
                    or lease.expired(now):
                return None
            return lease.holder

    # ---- acquisition -----------------------------------------------------

    def ensure_local(self, doc_id: str, is_desired_owner: bool,
                     now: Optional[float] = None) -> bool:
        """The merge-admission question: may THIS host run doc X's
        merges right now? Acquires/renews the local lease when
        rendezvous names us owner and no live conflicting lease exists.
        Returns False while another host's unexpired lease stands
        (handoff pending or split health view) and during our own
        outbound handoff (the new owner merges next, not us)."""
        now = time.monotonic() if now is None else now
        with self.lock:
            lease = self.leases.get(doc_id)
            if lease is not None and lease.holder == self.self_id:
                if lease.state == ACTIVE:
                    if not is_desired_owner:
                        # placement moved away; keep serving until the
                        # handoff runs (node drives it) — merges must
                        # not stall in the gap
                        pass
                    lease.expires_at = now + self.ttl_s
                    self._bump("renewals")
                    return True
                if lease.state in _HANDOFF_STATES:
                    return False     # outbound handoff in progress
                if lease.state == GRANTED:
                    # we were offered the lease but activation hasn't
                    # arrived; the grantor is still draining/merging
                    return False
            if not is_desired_owner:
                return False
            if lease is not None and lease.holder != self.self_id \
                    and not lease.expired(now):
                return False         # live remote lease wins
            # free (no lease, expired, or released): acquire
            epoch = 1 if lease is None else lease.epoch + 1
            takeover = (lease is not None
                        and lease.holder != self.self_id
                        and lease.state != RELEASED)
            self.leases[doc_id] = Lease(doc_id, self.self_id, epoch,
                                        ACTIVE, now + self.ttl_s)
            self._bump("takeovers" if takeover else "acquires")
            return True

    # ---- remote observations ---------------------------------------------

    def observe_remote(self, doc_id: str, holder: str, epoch: int,
                       state: str, ttl_s: float) -> None:
        """Fold a peer's lease claim (grant message or /replicate/docs
        piggyback). Higher epoch wins; equal epochs keep the holder with
        the lexically smaller id (same tie-break as rendezvous)."""
        now = time.monotonic()
        with self.lock:
            cur = self.leases.get(doc_id)
            if cur is not None and (cur.epoch > epoch or (
                    cur.epoch == epoch and cur.holder <= holder)):
                return
            self.leases[doc_id] = Lease(
                doc_id, holder, epoch, state, now + max(ttl_s, 0.0))

    def accept_grant(self, doc_id: str, epoch: int,
                     ttl_s: float) -> bool:
        """Remote handoff step 1 (receiver): record the offered lease
        as GRANTED-not-active. Idempotent; refuses stale epochs."""
        now = time.monotonic()
        with self.lock:
            cur = self.leases.get(doc_id)
            if cur is not None and cur.epoch >= epoch \
                    and not (cur.holder == self.self_id
                             and cur.epoch == epoch):
                return False
            self.leases[doc_id] = Lease(doc_id, self.self_id, epoch,
                                        GRANTED, now + max(ttl_s, 0.0))
            return True

    def activate_grant(self, doc_id: str, epoch: int) -> bool:
        """Remote handoff final step (receiver): flip GRANTED→ACTIVE.
        Idempotent (duplicate activate messages are harmless)."""
        now = time.monotonic()
        with self.lock:
            cur = self.leases.get(doc_id)
            if cur is None or cur.holder != self.self_id \
                    or cur.epoch != epoch:
                return False
            if cur.state == ACTIVE:
                return True
            if cur.state != GRANTED:
                return False
            cur.state = ACTIVE
            cur.expires_at = now + self.ttl_s
            self._bump("acquires")
            return True

    # ---- handoff (sender side; steps driven by node.ReplicaNode) ---------

    def begin_handoff(self, doc_id: str) -> Optional[int]:
        """ACTIVE → GRANTING. Returns the epoch the NEW owner's lease
        will carry (ours + 1), or None if we don't hold the doc."""
        with self.lock:
            lease = self.leases.get(doc_id)
            if lease is None or lease.holder != self.self_id \
                    or lease.state != ACTIVE:
                return None
            lease.state = GRANTING
            return lease.epoch + 1

    def advance_handoff(self, doc_id: str, state: str) -> None:
        assert state in (DRAINING, TRANSFER)
        with self.lock:
            lease = self.leases[doc_id]
            lease.state = state

    def finish_handoff(self, doc_id: str, new_holder: str,
                       new_epoch: int) -> None:
        """Local release + record the new owner's active lease."""
        now = time.monotonic()
        with self.lock:
            self.leases[doc_id] = Lease(doc_id, new_holder, new_epoch,
                                        ACTIVE, now + self.ttl_s)
            self._bump("releases")

    def abort_handoff(self, doc_id: str) -> None:
        """Roll a failed handoff back to ACTIVE (same epoch): the
        receiver's GRANTED lease is never activated and just expires."""
        with self.lock:
            lease = self.leases.get(doc_id)
            if lease is not None and lease.holder == self.self_id \
                    and lease.state in _HANDOFF_STATES:
                lease.state = ACTIVE
                lease.expires_at = time.monotonic() + self.ttl_s

    # ---- export ----------------------------------------------------------

    def as_json(self) -> dict:
        now = time.monotonic()
        with self.lock:
            return {d: lease.as_json(now)
                    for d, lease in sorted(self.leases.items())}
