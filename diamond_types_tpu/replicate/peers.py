"""Peer table + health tracking for the replication mesh.

Peers are seeded at startup (`--peers host:port,...`) and can now
change at runtime: `add_peer`/`remove_peer` back the /replicate/join
and /replicate/leave endpoints, and the probe loop doubles as the
gossip transport — each ping response body is handed to the `on_ping`
hook, which membership.MembershipView uses to merge remote member
tables. Every outbound HTTP call gets a hard timeout, failures feed a
consecutive-failure circuit breaker, and re-probes back off with
jittered exponential delays so a dead peer costs one cheap probe per
backoff window instead of a timeout per request.

`Backoff` and `call_with_retries` are deliberately standalone: the
client-side `SyncClient` (tools/server.py) shares them for its bounded
pull/push retries.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from .faults import FaultInjector
from .metrics import ReplicationMetrics


class Backoff:
    """Jittered exponential backoff: delay(attempt) grows as
    base * 2**attempt, capped, with deterministic jitter in
    [0.5, 1.0) of the nominal delay (seeded so tests replay)."""

    def __init__(self, base_s: float = 0.05, cap_s: float = 5.0,
                 seed: int = 0, key: str = "") -> None:
        self.base_s = base_s
        self.cap_s = cap_s
        self._rng = random.Random(f"{seed}:{key}")

    def delay(self, attempt: int) -> float:
        # exponent bounded the same way as DocStore's flush backoff:
        # 2**attempt overflows float conversion near attempt=1025
        nominal = min(self.base_s * (2 ** min(max(attempt, 0), 20)),
                      self.cap_s)
        return nominal * (0.5 + 0.5 * self._rng.random())


def call_with_retries(fn: Callable, retries: int = 3,
                      backoff: Optional[Backoff] = None,
                      sleep: Callable[[float], None] = time.sleep):
    """Run `fn()` with up to `retries` retries on transient transport
    errors (connection failures, timeouts, HTTP 5xx). Client errors
    (HTTP 4xx) are NOT transient — retrying a rejected patch can't
    succeed — so they raise immediately."""
    backoff = backoff or Backoff()
    attempt = 0
    while True:
        try:
            return fn()
        except urllib.error.HTTPError as e:
            if e.code < 500 or attempt >= retries:
                raise
        except OSError:
            # URLError, ConnectionError, socket.timeout, FaultDrop
            if attempt >= retries:
                raise
        sleep(backoff.delay(attempt))
        attempt += 1


class CircuitOpen(ConnectionError):
    """Peer's circuit breaker is open; call refused without touching
    the network."""

    def __init__(self, peer_id: str, retry_at: float) -> None:
        self.peer_id = peer_id
        self.retry_at = retry_at
        super().__init__(f"circuit open for peer {peer_id}")


class _PeerState:
    __slots__ = ("addr", "failures", "open_until", "down_since",
                 "last_ok", "backoff")

    def __init__(self, addr: str, backoff: Backoff) -> None:
        self.addr = addr
        self.failures = 0          # consecutive
        self.open_until = 0.0      # monotonic; 0 = circuit closed
        self.down_since = 0.0      # when the circuit FIRST opened
        self.last_ok: Optional[float] = None
        self.backoff = backoff


class PeerTable:
    """Health-tracked view of the static mesh. `self_id` is this
    server's own `host:port` (its rendezvous identity); it is never a
    callable peer. Thread-safe; call() performs network I/O outside
    the table lock."""

    def __init__(self, self_id: str, peer_addrs: List[str],
                 timeout_s: float = 2.0, fail_threshold: int = 3,
                 backoff_base_s: float = 0.1, backoff_cap_s: float = 5.0,
                 seed: int = 0,
                 faults: Optional[FaultInjector] = None,
                 metrics: Optional[ReplicationMetrics] = None) -> None:
        self.self_id = self_id
        self.timeout_s = timeout_s
        self.fail_threshold = max(int(fail_threshold), 1)
        self.faults = faults
        self.metrics = metrics
        # obs.recorder.FlightRecorder (wired by ReplicaNode): circuit
        # transitions are the events partition post-mortems need
        self.recorder = None
        self._backoff_base_s = backoff_base_s
        self._backoff_cap_s = backoff_cap_s
        self._seed = seed
        # gossip hook: on_ping(peer_id, parsed_ping_body) — wired by
        # ReplicaNode to fold the responder's member table
        self.on_ping: Optional[Callable[[str, dict], None]] = None
        from ..analysis.witness import make_lock
        self._lock = make_lock("repl.peers", "repl.peers")
        self.peers: Dict[str, _PeerState] = {}
        for addr in peer_addrs:
            if addr and addr != self_id:
                self.peers[addr] = _PeerState(
                    addr, Backoff(backoff_base_s, backoff_cap_s,
                                  seed=seed, key=f"{self_id}->{addr}"))
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None

    # ---- membership / health views ---------------------------------------

    def add_peer(self, addr: str) -> bool:
        """Register a peer discovered at runtime (join announcement or
        gossip). Idempotent; never adds self."""
        if not addr or addr == self.self_id:
            return False
        with self._lock:
            if addr in self.peers:
                return False
            self.peers[addr] = _PeerState(
                addr, Backoff(self._backoff_base_s, self._backoff_cap_s,
                              seed=self._seed,
                              key=f"{self.self_id}->{addr}"))
            return True

    def remove_peer(self, addr: str) -> bool:
        """Drop a peer that explicitly left the mesh."""
        with self._lock:
            return self.peers.pop(addr, None) is not None

    def peer_ids(self) -> List[str]:
        return sorted(self.peers)

    def all_ids(self) -> List[str]:
        return sorted(list(self.peers) + [self.self_id])

    def is_healthy(self, peer_id: str, now: Optional[float] = None) -> bool:
        if peer_id == self.self_id:
            return True
        st = self.peers.get(peer_id)
        if st is None:
            return False
        return st.open_until == 0.0

    def healthy_ids(self, now: Optional[float] = None) -> List[str]:
        """Self plus every peer whose circuit is closed — the live host
        set rendezvous ownership is computed over."""
        return sorted([self.self_id] +
                      [p for p, st in self.peers.items()
                       if st.open_until == 0.0])

    def down_duration(self, peer_id: str,
                      now: Optional[float] = None) -> Optional[float]:
        """Seconds the peer has been continuously unhealthy (since its
        circuit first opened), or None while healthy. Ownership uses
        this to delay takeover past a full lease TTL — a short blip or
        partition must not produce two hosts that both believe they are
        the rendezvous owner."""
        if peer_id == self.self_id:
            return None
        st = self.peers.get(peer_id)
        if st is None:
            return float("inf")
        with self._lock:
            if st.open_until == 0.0:
                return None
            return (now if now is not None
                    else time.monotonic()) - st.down_since

    def state(self, peer_id: str) -> dict:
        st = self.peers[peer_id]
        now = time.monotonic()
        return {"consecutive_failures": st.failures,
                "circuit_open": st.open_until > 0.0,
                "backoff_s": round(max(st.open_until - now, 0.0), 3),
                "last_ok_age_s": (round(now - st.last_ok, 3)
                                  if st.last_ok is not None else None)}

    def states(self) -> dict:
        return {p: self.state(p) for p in self.peer_ids()}

    # ---- outcome accounting ----------------------------------------------

    def _record_ok(self, st: _PeerState) -> None:
        with self._lock:
            reopened = st.open_until > 0.0
            st.failures = 0
            st.open_until = 0.0
            st.last_ok = time.monotonic()
        if reopened:
            if self.metrics is not None:
                self.metrics.bump("probes", "circuit_closes")
            if self.recorder is not None:
                self.recorder.record("circuit_close", peer=st.addr)

    def _record_failure(self, st: _PeerState) -> None:
        with self._lock:
            st.failures += 1
            failures = st.failures
            opened = False
            if st.failures >= self.fail_threshold:
                now = time.monotonic()
                opened = st.open_until == 0.0
                if opened:
                    st.down_since = now
                st.open_until = now + st.backoff.delay(
                    st.failures - self.fail_threshold)
        if opened:
            if self.metrics is not None:
                self.metrics.bump("probes", "circuit_opens")
            if self.recorder is not None:
                self.recorder.record("circuit_open", peer=st.addr,
                                     failures=failures)

    # ---- calls -----------------------------------------------------------

    def call(self, peer_id: str, path: str, data: Optional[bytes] = None,
             timeout: Optional[float] = None, probe: bool = False,
             headers: Optional[dict] = None) -> Tuple[int, bytes]:
        """One HTTP request to a peer: fault injection first, then a
        hard-timeout urllib call. Returns (status, body). An open
        circuit refuses the call immediately (CircuitOpen) — except for
        probes once the backoff window has lapsed (half-open trial).
        Raises the transport error on failure; both refusal and failure
        feed the breaker."""
        st = self.peers.get(peer_id)
        if st is None:
            raise KeyError(f"unknown peer {peer_id!r}")
        now = time.monotonic()
        with self._lock:
            open_until = st.open_until
        if open_until > 0.0 and now < open_until:
            # inside the backoff window: refuse without touching the
            # network. Once the window lapses any call (probe or not)
            # is the half-open trial — success closes the circuit,
            # failure re-opens it with a longer window.
            raise CircuitOpen(peer_id, open_until)
        dup = False
        if self.faults is not None:
            try:
                dup = self.faults.before_call(self.self_id, peer_id)
            except OSError:
                # injected drops/partitions must feed the breaker
                # exactly like real transport failures
                self._record_failure(st)
                raise
        url = f"http://{st.addr}{path}"
        req = urllib.request.Request(url, data=data)
        req.add_header("X-DT-Peer", self.self_id)
        for k, v in (headers or {}).items():
            req.add_header(k, v)
        t = timeout if timeout is not None else self.timeout_s
        try:
            with urllib.request.urlopen(req, timeout=t) as r:
                body = r.read()
                status = r.status
            if dup:   # duplicate delivery: idempotent peer endpoints
                with urllib.request.urlopen(
                        urllib.request.Request(
                            url, data=data,
                            headers=dict(req.header_items())),
                        timeout=t) as r2:
                    body = r2.read()
                    status = r2.status
        except urllib.error.HTTPError as e:
            # the peer is UP and answered: not a health failure
            self._record_ok(st)
            raise
        except OSError:
            self._record_failure(st)
            raise
        self._record_ok(st)
        return status, body

    def call_json(self, peer_id: str, path: str,
                  obj: Optional[dict] = None,
                  timeout: Optional[float] = None,
                  headers: Optional[dict] = None) -> dict:
        data = (json.dumps(obj).encode("utf8")
                if obj is not None else None)
        _status, body = self.call(peer_id, path, data=data,
                                  timeout=timeout, headers=headers)
        return json.loads(body or b"{}")

    # ---- probe loop ------------------------------------------------------

    def probe(self, peer_id: str) -> bool:
        """One health probe (`GET /replicate/ping`). Returns up/down.
        A 200 body is parsed and handed to the `on_ping` gossip hook
        (membership piggyback rides the probe loop for free)."""
        body = b""
        t0 = time.monotonic()
        try:
            status, body = self.call(peer_id, "/replicate/ping",
                                     probe=True)
            ok = status == 200
        except CircuitOpen:
            return False        # still inside the backoff window
        except (OSError, urllib.error.HTTPError):
            ok = False
        if self.metrics is not None:
            self.metrics.bump("probes", "ok" if ok else "failed")
            self.metrics.observe_latency("probe",
                                         time.monotonic() - t0)
        if ok and self.on_ping is not None:
            try:
                self.on_ping(peer_id, json.loads(body or b"{}"))
            except (ValueError, TypeError):
                pass            # malformed gossip never fails a probe
        return ok

    def probe_once(self) -> Dict[str, bool]:
        return {p: self.probe(p) for p in self.peer_ids()}

    def start_probe_loop(self, interval_s: float = 0.5) -> None:
        if self._probe_thread is not None:
            return

        def loop():
            while not self._probe_stop.wait(interval_s):
                try:
                    self.probe_once()
                except Exception:    # pragma: no cover - keep probing
                    pass

        self._probe_thread = threading.Thread(target=loop, daemon=True)
        self._probe_thread.start()

    def stop_probe_loop(self) -> None:
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=2)
            self._probe_thread = None
        self._probe_stop = threading.Event()
