"""ReplicaNode: one server's membership in the replication mesh.

Composes the peer table (health), membership view (who is in the
mesh), lease manager (ownership + quorum voter state), quorum
coordinator (majority rounds), replica journal (crash durability) and
anti-entropy loop (convergence) around a DocStore, and implements the
protocols the HTTP tier delegates to it:

  * mutation routing — `route_mutation(doc_id)` names the host that
    should apply a write (current lease holder when known and healthy,
    rendezvous owner otherwise); `proxy()` forwards the raw request
    body there, stamping the lease epoch it routed by as a fencing
    token (`X-DT-Lease-Epoch`). A receiver whose fencing floor has
    passed that epoch answers 409 — the write is NOT merged under a
    stale lease; the proxier falls back to accepting locally and
    anti-entropy reconciles once the new epoch propagates. When the
    target is simply unreachable the server also falls back to
    accepting locally (availability over placement);

  * quorum — lease acquisition, takeover, and handoff activation all
    run the promise round (quorum.QuorumCoordinator) against the
    membership voter set before a lease becomes ACTIVE;

  * membership — `/replicate/join` and `/replicate/leave` mutate the
    view explicitly; the probe loop feeds local health evidence into
    it and gossips member tables on every ping (peers.PeerTable
    `on_ping` hook). Rendezvous ownership is computed over
    `membership.universe()`, so lease migration on view changes is
    deterministic — every host recomputes the same owner from the
    same view;

  * handoff — `handoff(doc_id, new_owner)` drives the sender side of
    the lease state machine (see ownership.py):
    grant → drain pending merges → final patch transfer → activate
    (the receiver runs the quorum round for the new epoch before
    flipping GRANTED → ACTIVE);

  * crash recovery — when constructed with a `journal_prefix`, fencing
    floors, promises, held leases and the membership incarnation are
    journaled (quorum.ReplicaJournal). A restart restores them, bumps
    the incarnation, and boots into a fenced `rejoining` state: every
    merge admit is denied until a quorum of voters has been confirmed
    reachable (`maintain` clears it), so a node that slept through a
    takeover cannot merge under its pre-crash beliefs.

`maintain()` is the periodic control step (piggybacked on the probe
loop): clear rejoining when earned, renew held leases, and hand off
docs whose rendezvous owner moved (peer recovered, view changed).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
from typing import List, Optional, Set, Tuple

from ..causalgraph.summary import intersect_with_summary
from ..encoding.encode import ENCODE_PATCH, encode_oplog
from ..obs.trace import NOOP_SPAN, TRACE_HEADER, format_context
from ..wire import WIRE_VERSION, WireChannel, WireError
from ..wire.frames import FRAME_OPS, encode_frame, encode_ops
from .antientropy import AntiEntropy
from .faults import FaultInjector
from .membership import ALIVE, LEFT, MembershipView
from .metrics import ReplicationMetrics
from .ownership import ACTIVE, DRAINING, TRANSFER, LeaseManager, owner_of
from .peers import PeerTable
from .quorum import QuorumCoordinator, ReplicaJournal
from .rebalance import PlacementOverrides
from .writergroup import WriterGroupTable

MUTATION_ACTIONS = ("push", "edit", "ops")


class ReplicaNode:
    def __init__(self, store, self_id: str, peer_addrs: List[str],
                 seed: int = 0, lease_ttl_s: float = 2.0,
                 probe_interval_s: float = 0.5,
                 antientropy_interval_s: float = 0.5,
                 timeout_s: float = 2.0, fail_threshold: int = 3,
                 backoff_base_s: float = 0.1,
                 backoff_cap_s: float = 5.0,
                 takeover_after_s: Optional[float] = None,
                 faults: Optional[FaultInjector] = None,
                 journal_prefix: Optional[str] = None,
                 obs=None, clock=None, table=None,
                 journal=None, wire_enabled: Optional[bool] = None,
                 snapshot_ops_threshold: Optional[int] = None,
                 group_ttl_s: Optional[float] = None) -> None:
        self.store = store
        self.self_id = self_id
        # clock/table/journal are dependency seams: the model checker
        # (analysis/explore) substitutes a virtual clock, a synchronous
        # simulated transport, and an in-memory journal so the real
        # protocol code runs under exhaustive scheduling. Production
        # callers leave all three None and get wall time + PeerTable +
        # file-backed ReplicaJournal, exactly as before.
        self.clock = time.monotonic if clock is None else clock
        self.started_at = self.clock()
        # how long a peer must stay continuously down before it is
        # declared DEAD and ownership reassigns its docs; defaults to
        # the lease TTL so a takeover can only be PROPOSED after the
        # old holder's lease has expired (the quorum round is what
        # makes the proposal safe)
        self.takeover_after_s = (lease_ttl_s if takeover_after_s is None
                                 else takeover_after_s)
        self.metrics = ReplicationMetrics(self_id)
        # wire tier: binary framing + per-channel transport accounting.
        # Negotiated per peer off ping gossip; framing can be pinned
        # off (JSON fallback) while accounting stays on.
        wire_opts = {} if snapshot_ops_threshold is None \
            else {"snapshot_ops_threshold": snapshot_ops_threshold}
        self.wire = WireChannel(metrics=self.metrics,
                                enabled=wire_enabled, **wire_opts)
        self.faults = faults
        if table is not None:
            self.table = table
            self.table.metrics = self.metrics
        else:
            self.table = PeerTable(self_id, peer_addrs,
                                   timeout_s=timeout_s,
                                   fail_threshold=fail_threshold,
                                   seed=seed,
                                   backoff_base_s=backoff_base_s,
                                   backoff_cap_s=backoff_cap_s,
                                   faults=faults, metrics=self.metrics)
        self.leases = LeaseManager(self_id, ttl_s=lease_ttl_s,
                                   metrics=self.metrics,
                                   clock=self.clock)
        # obs.Observability bundle (usually the DocStore's, via
        # attach_replication): spans on proxy/handoff/quorum, flight
        # recorder for lease/fencing/circuit events
        self.obs = obs
        if obs is not None:
            self.table.recorder = obs.recorder
            self.leases.recorder = obs.recorder
            # live telemetry: replication counters + quorum/handoff
            # latencies double-write into the windowed TimeSeries
            self.metrics.ts = getattr(obs, "ts", None)
            # journey: a peer's frontier advert closing the loop on a
            # tracked edit stamps `advert_usable` (read/follower.py)
            reads = getattr(store, "reads", None)
            if reads is not None:
                reads.index.journey = getattr(obs, "journey", None)
        # ---- crash-restart restore ----
        self.journal: Optional[ReplicaJournal] = None
        self.rejoining = False
        incarnation = 1
        if journal is not None:
            self.journal = journal
        elif journal_prefix is not None:
            self.journal = ReplicaJournal(journal_prefix)
        if self.journal is not None:
            self.rejoining = self.journal.has_prior_state()
            incarnation = self.journal.restored_incarnation() + 1
            self.journal.note_incarnation(incarnation)
            self.leases.restore(self.journal)
        # writer groups (replicate/writergroup.py): hot-doc write
        # splitting. Restored after the lease floors so a registration
        # a journaled floor supersedes is never resurrected; restored
        # entries come back EXPIRED (accepting again takes a renewal).
        self.writergroups = WriterGroupTable(
            self_id,
            ttl_s=lease_ttl_s * 2 if group_ttl_s is None
            else group_ttl_s,
            metrics=self.metrics, clock=self.clock)
        if self.journal is not None:
            self.writergroups.restore(
                self.journal,
                lambda d: self.leases.max_epoch.get(d, 0))
        # fencing floor raises fence superseded group registrations in
        # the same lease-lock critical section (no admit can interleave)
        self.leases.on_floor_raise = self.writergroups.fence_below
        # seam for the model checker's demote-without-drain mutation:
        # the member-side demotion fence drains pending admissions into
        # the oplog before evicting its queue iff this flag stands
        self._group_demote_drains = True
        self.membership = MembershipView(self_id, incarnation,
                                         metrics=self.metrics)
        # bootstrap peers start ALIVE (assumed healthy until the probe
        # loop says otherwise — same optimism the static table had)
        for addr in self.table.peer_ids():
            self.membership.add(addr, state=ALIVE)
        self.quorum = QuorumCoordinator(self)
        self.leases.quorum = self._run_quorum
        self.table.on_ping = self._on_ping
        # elastic-mesh tier (replicate/rebalance.py): the placement-
        # override table layered over rendezvous hashing. Restored from
        # the journal, gossiped on pings, consulted by desired_owner —
        # so routing, the merge-admission gate and the maintain loop
        # all follow an override the moment it lands.
        self.overrides = PlacementOverrides(journal=self.journal,
                                            metrics=self.metrics)
        # gossiped held-lease counts (ping "load" field): the
        # rebalancer's target-selection signal. A just-joined host has
        # no entry and reads as load 0 — the preferred target.
        self.peer_load = {}
        # attach_rebalancer hangs the SLO-driven control loop here; the
        # probe loop ticks it after maintain()
        self.rebalancer = None
        # follower->follower frontier advert relay: doc -> (origin,
        # frontier, hops, heard_at). Entries at hops <= max_relay_hops
        # ride our ping bodies so a follower two hops from the owner
        # still gets staleness evidence without an owner link.
        self._relay_adverts = {}
        self.max_relay_hops = 1
        self.antientropy = AntiEntropy(
            self, interval_s=antientropy_interval_s)
        self.probe_interval_s = probe_interval_s
        # docs whose merges this host has admitted — the test surface
        # for the exactly-one-merger property
        self.merged_docs: Set[str] = set()
        from ..analysis.witness import make_lock
        self._maintain_lock = make_lock("repl.maintain", "repl.maintain")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- quorum hook -----------------------------------------------------

    def _run_quorum(self, doc_id: str, epoch: int,
                    takeover: bool) -> bool:
        """LeaseManager's acquisition hook. A rejoining node may not
        propose — it must first re-earn its place (maintain clears the
        state once a quorum of voters is confirmed reachable)."""
        if self.rejoining:
            return False
        return self.quorum.acquire(doc_id, epoch, takeover)

    # ---- ownership -------------------------------------------------------

    def _sync_membership(self) -> None:
        """Fold the probe loop's health evidence into the membership
        view: reachable → ALIVE, down < takeover_after_s → SUSPECT
        (still in the rendezvous universe, so a short partition does
        not collapse each side's host set to itself), down past it →
        DEAD (out of the universe; its docs reassign — safely, because
        reassignment still needs a quorum)."""
        now = self.clock()
        for p in self.table.peer_ids():
            self.membership.note_health(
                p, self.table.down_duration(p, now),
                self.takeover_after_s)

    def ownership_ids(self) -> List[str]:
        """Hosts rendezvous ownership is computed over — the
        membership universe (ALIVE + SUSPECT + JOINING, always
        including self)."""
        self._sync_membership()
        return self.membership.universe()

    def desired_owner(self, doc_id: str) -> str:
        """Placement: the override table wins when its target is still
        in the ownership universe; rendezvous hashing otherwise. An
        override pointing at a DEAD/LEFT host is simply ignored, so a
        failed migration target never strands a doc."""
        ids = self.ownership_ids()
        override = self.overrides.target_of(doc_id)
        if override is not None and override in ids:
            return override
        return owner_of(doc_id, ids)

    def owns(self, doc_id: str) -> bool:
        """The scheduler's merge-admission gate: True iff this host
        holds (or can now acquire, quorum permitting) the doc's ACTIVE
        lease. Denied outright while rejoining after a crash."""
        if self.rejoining:
            self.metrics.bump("fencing", "rejoin_denials")
            self.metrics.bump("merge_gate", "denials")
            return False
        if self.group_accepts(doc_id):
            # writer-group member in good standing: admitted locally,
            # stamped with the group epoch (active_epoch below)
            self.metrics.bump("merge_gate", "admits")
            self.metrics.bump("writergroup", "member_admits")
            self.merged_docs.add(doc_id)
            return True
        ok = self.leases.ensure_local(
            doc_id, self.desired_owner(doc_id) == self.self_id)
        self.metrics.bump("merge_gate", "admits" if ok else "denials")
        if ok:
            self.merged_docs.add(doc_id)
        return ok

    def active_epoch(self, doc_id: str) -> int:
        """Scheduler fencing callback: epoch of the ACTIVE lease this
        host holds for the doc — or the group epoch when we write as a
        group member — 0 when neither stands."""
        epoch = self.leases.active_epoch(doc_id)
        if epoch:
            return epoch
        if self.group_accepts(doc_id):
            g = self.writergroups.get(doc_id)
            if g is not None:
                return g.epoch
        return 0

    # ---- writer groups (replicate/writergroup.py) ------------------------

    def group_accepts(self, doc_id: str) -> bool:
        """May this host accept writes for `doc_id` as a writer-group
        MEMBER? (The leader admits through its own ACTIVE lease.)
        Pure read — no state is mutated, so the model checker can use
        it as action enabledness. False once the registration expired
        un-renewed, once the fencing floor passed the group epoch, or
        when the leader plus a majority of the group is unreachable —
        the self-fence: a cut-off member degrades to proxy-only rather
        than accepting writes the group may already have fenced away."""
        if self.rejoining:
            return False
        g = self.writergroups.get(doc_id)
        if g is None or g.leader == self.self_id:
            return False
        if g.epoch < self.leases.max_epoch_of(doc_id):
            return False      # belt: fence_below drops these eagerly
        if self.clock() >= g.expires_at:
            return False
        if not self.table.is_healthy(g.leader):
            return False
        reach = sum(1 for m in g.members
                    if m == self.self_id or self.table.is_healthy(m))
        return reach >= g.quorum_size()

    def promote_writer_group(self, doc_id: str,
                             members: List[str]) -> bool:
        """Split `doc_id`'s write path: promote our single ACTIVE lease
        to a writer group of `members` (us included) at a bumped epoch.
        The epoch is planned exactly like any acquisition
        (`max(lease.epoch, floor) + 1`), ratified by a majority promise
        round, and committed by re-keying our lease; members get a
        directed group grant whose install raises their fencing floor
        to the group epoch. A member that misses its grant simply never
        co-writes — convergence does not depend on it."""
        if self.rejoining:
            return False
        member_set = sorted(set(members) | {self.self_id})
        if len(member_set) < 2:
            return False
        if self.writergroups.get(doc_id) is not None:
            return False
        with self.leases.lock:
            lease = self.leases.leases.get(doc_id)
            if lease is None or lease.holder != self.self_id \
                    or lease.state != ACTIVE:
                return False
            epoch = max(lease.epoch,
                        self.leases.max_epoch.get(doc_id, 0)) + 1
        if not self._run_quorum(doc_id, epoch, False):
            return False
        if not self.leases.promote_epoch(doc_id, epoch):
            return False     # revoked between the round and the rekey
        self.writergroups.install(
            doc_id, epoch, member_set, self.self_id,
            floor=self.leases.max_epoch_of(doc_id))
        self.metrics.bump("writergroup", "promotions")
        if self.obs is not None:
            self.obs.recorder.record("group_promoted", doc=doc_id,
                                     epoch=epoch, members=member_set)
        grant = {"action": "group", "doc": doc_id, "epoch": epoch,
                 "members": member_set, "leader": self.self_id,
                 "ttl_s": self.writergroups.ttl_s}
        for m in member_set:
            if m == self.self_id:
                continue
            try:
                self.table.call_json(m, "/replicate/lease", grant)
                self.metrics.bump("writergroup", "member_grants")
            except (OSError, KeyError, ValueError,
                    urllib.error.HTTPError):
                continue
        return True

    def can_demote(self, doc_id: str) -> bool:
        """Would `demote_writer_group` commit right now? True when we
        lead the group with an ACTIVE lease and every other member is
        reachable (drainable) or the registration TTL has provably
        expired (a silent member can no longer be accepting)."""
        g = self.writergroups.get(doc_id)
        if g is None or g.leader != self.self_id:
            return False
        lease = self.leases.get(doc_id)
        if lease is None or lease.holder != self.self_id \
                or lease.state != ACTIVE:
            return False
        if self.clock() >= g.expires_at:
            return True
        return all(self.table.is_healthy(m) for m in g.members
                   if m != self.self_id)

    def demote_writer_group(self, doc_id: str) -> bool:
        """Drain the group back to a single writer (us) — the
        robustness centerpiece. The demotion epoch `group_epoch + 1`
        wins a majority round, every reachable member is fenced (it
        drains pending admissions into its oplog, drops the
        registration and evicts its queue), and only then is our lease
        re-keyed. An unreachable member blocks the demotion until its
        registration TTL has expired: committing earlier would let a
        silent-but-alive member keep accepting writes under the
        superseded epoch."""
        g = self.writergroups.get(doc_id)
        if g is None or g.leader != self.self_id:
            return False
        now = self.clock()
        expired = now >= g.expires_at
        others = [m for m in g.members if m != self.self_id]
        if not expired:
            for m in others:
                if not self.table.is_healthy(m):
                    self.metrics.bump("writergroup", "demote_aborts")
                    return False
        with self.leases.lock:
            lease = self.leases.leases.get(doc_id)
            if lease is None or lease.holder != self.self_id \
                    or lease.state != ACTIVE:
                return False
            epoch = max(lease.epoch,
                        self.leases.max_epoch.get(doc_id, 0)) + 1
        if not self._run_quorum(doc_id, epoch, False):
            self.metrics.bump("writergroup", "demote_aborts")
            return False
        demote = {"action": "group-demote", "doc": doc_id,
                  "epoch": epoch, "leader": self.self_id}
        for m in others:
            try:
                self.table.call_json(m, "/replicate/lease", demote)
            except (OSError, KeyError, ValueError,
                    urllib.error.HTTPError):
                # unreachable member: its registration is past TTL
                # (checked above) or fenced by the quorum round's
                # floor raise the moment it reconnects
                continue
        if not self.leases.promote_epoch(doc_id, epoch):
            self.metrics.bump("writergroup", "demote_aborts")
            return False
        self.writergroups.drop(doc_id)
        self.metrics.bump("writergroup", "demotions")
        if self.obs is not None:
            self.obs.recorder.record("group_demoted", doc=doc_id,
                                     epoch=epoch)
        return True

    def _group_fence_local(self, doc_id: str,
                           epoch: Optional[int] = None) -> bool:
        """Member-side demotion fence: drain pending admissions into
        the oplog, drop the registration (only at or below `epoch` — a
        replayed demote must not fence a newer group), and evict the
        admission queue. The drain barrier is what `no-acked-loss`
        guards: eviction without it discards acked work (the
        demote-without-drain seeded mutation)."""
        g = self.writergroups.get(doc_id)
        if g is not None and epoch is not None and g.epoch > epoch:
            return False
        if self._group_demote_drains:
            sched = getattr(self.store, "scheduler", None)
            if sched is not None:
                sched.drain()
        self.writergroups.drop(doc_id, at_or_below=epoch)
        pending = getattr(self.store, "pending", None)
        if pending is not None:
            pending.pop(doc_id, None)
        return True

    def route_mutation(self, doc_id: str) -> str:
        """The host a write for `doc_id` should land on."""
        holder = self.leases.holder_of(doc_id)
        if holder is not None and (holder == self.self_id
                                   or self.table.is_healthy(holder)):
            return holder
        return self.desired_owner(doc_id)

    # ---- proxy -----------------------------------------------------------

    def proxy(self, target: str, path: str, body: bytes,
              doc_id: Optional[str] = None,
              trace=None,
              qos: Optional[str] = None) -> Optional[Tuple[int, bytes]]:
        """Forward a mutation to its owner, stamping the lease epoch we
        routed by (the fencing token). Returns (status, body) to relay,
        or None when the caller should accept locally instead: target
        unreachable, or target fenced the epoch (our routing info was
        stale — anti-entropy reconciles once the new lease propagates).
        `trace` (obs SpanContext of the local HTTP span) rides the
        X-DT-Trace header so the owner's handling joins the trace;
        `qos` rides X-DT-QoS so the owner admits the work under the
        class the edge classified (a proxied hop must not be
        re-classified as replication traffic)."""
        headers = {"X-DT-Proxied": "1"}
        if qos is not None:
            headers["X-DT-QoS"] = qos
        if doc_id is not None:
            lease = self.leases.get(doc_id)
            if lease is not None and lease.holder == target:
                headers["X-DT-Lease-Epoch"] = str(lease.epoch)
        span = NOOP_SPAN
        if self.obs is not None:
            span = self.obs.tracer.start(
                "repl.proxy", parent=trace,
                attrs={"target": target, "doc": doc_id})
        ctx = span.context() if span.sampled else trace
        if ctx is not None:
            headers[TRACE_HEADER] = format_context(ctx)
        # wire tier: a JSON edit body proxied to a v1 peer rides as one
        # OPS frame (the receiver sniffs the magic). Any re-encode
        # hiccup just sends the original JSON — correctness never
        # depends on the frame path.
        send_body, framed = body, False
        if path.endswith("/edit") and self.wire.use_wire(target):
            try:
                frame = encode_frame(FRAME_OPS,
                                     encode_ops(json.loads(body)),
                                     compress=True)
                if len(frame) < len(body):
                    send_body, framed = frame, True
            except (ValueError, KeyError, TypeError, WireError):
                pass
        try:
            try:
                status, resp = self.table.call(target, path,
                                               data=send_body,
                                               headers=headers)
                self.wire.account("proxy", sent_bytes=len(send_body),
                                  json_bytes=len(body), framed=framed)
            except urllib.error.HTTPError as e:
                # owner answered with an application error: relay it
                status, resp = e.code, e.read()
            except OSError:
                self.metrics.bump("proxy", "fallback_local")
                span.annotate(outcome="fallback_local")
                return None
            if status == 409:
                try:
                    fenced = json.loads(resp or b"{}").get("error") \
                        == "fenced"
                except ValueError:
                    fenced = False
                if fenced:
                    self.metrics.bump("proxy", "fenced_relays")
                    span.annotate(outcome="fenced")
                    return None
            self.metrics.bump("proxy", "proxied")
            span.annotate(outcome="proxied", status=status)
            return status, resp
        finally:
            span.end()

    def check_write_fence(self, doc_id: str,
                          claimed_epoch: int) -> bool:
        """Receiver side of write fencing: may a proxied mutation
        claiming `claimed_epoch` be applied to `doc_id`? False when the
        fencing floor has passed the claim — the proxier routed by a
        lease that has been superseded."""
        floor = self.leases.max_epoch_of(doc_id)
        if claimed_epoch >= floor:
            return True
        self.metrics.bump("fencing", "rejected_writes")
        if self.obs is not None:
            self.obs.recorder.record("fencing_rejected", doc=doc_id,
                                     claimed_epoch=claimed_epoch,
                                     floor=floor)
        return False

    # ---- handoff (sender) ------------------------------------------------

    def handoff(self, doc_id: str, new_owner: str,
                override_version: Optional[int] = None) -> bool:
        """Move doc ownership to `new_owner` without ever having two
        active mergers: grant → drain → final patch → activate (the
        receiver's activate runs the quorum round for the new epoch).
        Any failure aborts back to ACTIVE (the remote GRANTED lease
        simply expires). `override_version` (rebalancer migrations)
        ships the placement-override entry ON the grant message, so the
        receiver keeps the doc instead of rendezvous handing it back."""
        t0 = time.monotonic()
        new_epoch = self.leases.begin_handoff(doc_id)
        if new_epoch is None:
            return False
        self.metrics.bump("handoffs", "started")
        span = NOOP_SPAN
        if self.obs is not None:
            span = self.obs.tracer.start(
                "repl.handoff", attrs={"doc": doc_id, "to": new_owner,
                                       "epoch": new_epoch})

        def phase(name):
            # child span per handoff stage; the grant/activate calls
            # carry the trace header so the receiver's lease handling
            # joins the same trace
            if not span.sampled:
                return NOOP_SPAN
            return self.obs.tracer.start(name, parent=span.context())

        hdrs = {TRACE_HEADER: span.header()} if span.sampled else None
        try:
            # grant: the receiver records a not-yet-active lease (its
            # TTL covers the whole handoff, so a crashed sender leaves
            # a lease that expires rather than a stuck doc)
            with phase("repl.handoff.grant"):
                grant = {"action": "grant", "doc": doc_id,
                         "epoch": new_epoch,
                         "ttl_s": self.leases.ttl_s * 4}
                if override_version is not None:
                    grant["override"] = [doc_id, new_owner,
                                         int(override_version)]
                resp = self.table.call_json(
                    new_owner, "/replicate/lease", grant, headers=hdrs)
                if not resp.get("ok"):
                    raise ValueError(f"grant refused: {resp!r}")
            # drain: flush our pending merge work for the doc so the
            # final patch includes every admitted op
            with phase("repl.handoff.drain"):
                td = time.monotonic()
                self.leases.advance_handoff(doc_id, DRAINING)
                sched = getattr(self.store, "scheduler", None)
                if sched is not None:
                    sched.drain()
                self.metrics.observe_latency("rebalance_drain",
                                             time.monotonic() - td)
            # final patch transfer (from the receiver's common version)
            with phase("repl.handoff.transfer"):
                self.leases.advance_handoff(doc_id, TRANSFER)
                remote_summary = self.table.call_json(
                    new_owner, f"/doc/{doc_id}/summary")
                ol = self.store.get(doc_id)
                with self.store.lock:
                    common, _rem = intersect_with_summary(
                        ol.cg, remote_summary)
                    patch = None
                    if sorted(common) != sorted(ol.version):
                        patch = encode_oplog(ol, ENCODE_PATCH,
                                             from_version=common)
                if patch is not None:
                    self.table.call(new_owner, f"/doc/{doc_id}/push",
                                    data=patch)
            # activate: receiver runs the quorum round for new_epoch,
            # then flips GRANTED -> ACTIVE; we release
            with phase("repl.handoff.activate"):
                resp = self.table.call_json(
                    new_owner, "/replicate/lease",
                    {"action": "activate", "doc": doc_id,
                     "epoch": new_epoch},
                    headers=hdrs)
                if not resp.get("ok"):
                    raise ValueError(f"activate refused: {resp!r}")
            self.leases.finish_handoff(doc_id, new_owner, new_epoch)
            self.metrics.bump("handoffs", "completed")
            self.metrics.observe_handoff_latency(time.monotonic() - t0)
            span.end(outcome="completed")
            return True
        except (OSError, ValueError, KeyError,
                urllib.error.HTTPError) as e:
            self.leases.abort_handoff(doc_id)
            self.metrics.bump("handoffs", "failed")
            if self.obs is not None:
                self.obs.recorder.record(
                    "handoff_failed", doc=doc_id, to=new_owner,
                    epoch=new_epoch,
                    error=f"{e.__class__.__name__}: {e}"[:120])
            span.end(outcome="failed")
            return False

    # ---- lease wire handler (receiver) -----------------------------------

    def handle_lease_message(self, req: dict) -> dict:
        action = req.get("action")
        doc_id = req.get("doc")
        if not isinstance(doc_id, str) or not doc_id:
            return {"ok": False, "error": "bad doc"}
        epoch = int(req.get("epoch", 0))
        if action == "propose":
            holder = req.get("holder")
            if not isinstance(holder, str) or not holder:
                return {"ok": False, "error": "bad holder"}
            ok, reason = self.leases.promise(doc_id, epoch, holder)
            return {"ok": ok, "reason": reason,
                    "max_epoch": self.leases.max_epoch_of(doc_id)}
        if action == "grant":
            ok = self.leases.accept_grant(
                doc_id, epoch, float(req.get("ttl_s", 0.0)))
            if ok and req.get("override") is not None:
                # rebalancer migration rider: install the placement
                # override atomically with the grant, so our own
                # maintain loop keeps the doc once it activates
                self.overrides.merge([req["override"]])
            return {"ok": ok}
        if action == "activate":
            # the handoff's quorum round: the new epoch must win a
            # majority before this node becomes the active merger
            if not self._run_quorum(doc_id, epoch, False):
                return {"ok": False, "error": "quorum"}
            ok = self.leases.activate_grant(doc_id, epoch)
            if ok:
                self._pin_migrated_doc(doc_id)
            return {"ok": ok}
        if action == "group":
            # writer-group grant (leader -> member): fold the leader's
            # lease claim at the group epoch FIRST — that raises our
            # fencing floor to it — then register. A replayed grant
            # from a superseded group fails the install's floor check.
            members = req.get("members")
            leader = req.get("leader")
            if not isinstance(leader, str) or not leader \
                    or not isinstance(members, list) \
                    or self.self_id not in members:
                return {"ok": False, "error": "bad group"}
            self.leases.observe_remote(doc_id, leader, epoch, ACTIVE,
                                       float(req.get("ttl_s", 0.0)))
            ok = self.writergroups.install(
                doc_id, epoch, [str(m) for m in members], leader,
                floor=self.leases.max_epoch_of(doc_id))
            if ok:
                self.metrics.bump("writergroup", "member_grants")
            else:
                self.metrics.bump("writergroup",
                                  "stale_installs_rejected")
            return {"ok": ok}
        if action == "group-renew":
            # member -> leader: extend the member's registration while
            # the group at that epoch is still current on our side
            member = req.get("member")
            g = self.writergroups.get(doc_id)
            if g is None or g.leader != self.self_id \
                    or g.epoch != epoch or member not in g.members:
                self.metrics.bump("writergroup", "renewal_denials")
                return {"ok": False}
            self.writergroups.refresh(doc_id, epoch)
            self.metrics.bump("writergroup", "renewals")
            return {"ok": True, "ttl_s": self.writergroups.ttl_s}
        if action == "group-demote":
            # leader -> member: the demotion epoch has won its quorum
            # round. Raise our floor to it (the promise is idempotent;
            # a refusal means the floor already passed it) and fence:
            # drain, drop the registration, evict the queue.
            leader = req.get("leader")
            if isinstance(leader, str) and leader:
                self.leases.promise(doc_id, epoch, leader)
            self._group_fence_local(doc_id, epoch - 1)
            return {"ok": True}
        if action == "status":
            lease = self.leases.get(doc_id)
            g = self.writergroups.get(doc_id)
            return {"ok": True,
                    "lease": lease.as_json() if lease else None,
                    "desired": self.desired_owner(doc_id),
                    "max_epoch": self.leases.max_epoch_of(doc_id),
                    "group": g.as_json(self.clock())
                    if g is not None else None,
                    "rejoining": self.rejoining}
        return {"ok": False, "error": f"bad action {action!r}"}

    def _pin_migrated_doc(self, doc_id: str) -> None:
        """On activating a migrated doc, steer it onto this host's
        least-loaded shard (ShardRouter.pin). Rendezvous shard routing
        knows nothing about load, and a doc hot enough to migrate is
        hot enough to deserve the emptiest chip. Best-effort: no
        scheduler/router (raw stores, sims) means no pin."""
        if self.overrides.target_of(doc_id) != self.self_id:
            return
        sched = getattr(self.store, "scheduler", None)
        router = getattr(sched, "router", None)
        if router is None or router.n_shards < 2:
            return
        try:
            counts = router.counts()
            router.pin(doc_id, counts.index(min(counts)))
        except (ValueError, AttributeError):  # pragma: no cover
            pass

    # ---- membership wire handlers ----------------------------------------

    def ping_json(self) -> dict:
        """Body of `GET /replicate/ping` — health ack + gossip
        piggyback (the probe loop is the gossip transport)."""
        out = {"ok": True, "id": self.self_id,
               "uptime_s": round(self.clock() - self.started_at, 3),
               "incarnation": self.membership.self_incarnation,
               "view_version": self.membership.view_version,
               "rejoining": self.rejoining,
               # held-lease count: the rebalancer's load signal
               "load": self.leases.held_count(),
               "members": self.membership.gossip_payload()}
        # wire capability gossip: POST bodies can only be framed once
        # the sender KNOWS the receiver decodes frames, and ping is the
        # one channel every peer already exchanges
        if self.wire.enabled:
            out["wire"] = WIRE_VERSION
        overrides = self.overrides.gossip_payload()
        if overrides:
            out["overrides"] = overrides
        frontiers = self._owned_frontiers()
        if frontiers is not None:
            out["frontiers"] = frontiers
            relayed = self._relayed_frontiers()
            if relayed:
                out["relayed_frontiers"] = relayed
                self.metrics.bump("antientropy", "adverts_relayed",
                                  len(relayed))
        return out

    def _owned_frontiers(self, cap: int = 32):
        """Frontier advertisements for the follower-read tier: the
        current frontier of every doc whose ACTIVE lease we hold
        (capped — ping bodies must stay small). None when follower
        reads aren't attached anywhere, so the ping body is unchanged
        on meshes without the feature."""
        if getattr(self.store, "reads", None) is None:
            return None
        held = self.leases.held_ids()[:cap]
        if not held:
            return {}
        frontiers = {}
        with self.store.lock:
            for doc_id in held:
                ol = self.store.docs.get(doc_id)
                if ol is not None:
                    frontiers[doc_id] = \
                        ol.cg.local_to_remote_frontier(ol.version)
        return frontiers

    def _relayed_frontiers(self, cap: int = 32):
        """Follower->follower advert relay: re-advertise frontiers we
        heard DIRECTLY from their owners (hops <= max_relay_hops), so a
        follower without an owner link still accumulates staleness
        evidence. Entries age out after a few probe intervals — a
        relay must never outlive the evidence it carries."""
        now = self.clock()
        ttl = max(self.probe_interval_s * 6, 3.0)
        stale = [d for d, (_o, _f, _h, at) in
                 self._relay_adverts.items() if now - at > ttl]
        for d in stale:
            self._relay_adverts.pop(d, None)
        out = {}
        for doc_id, (origin, frontier, hops, _at) in \
                sorted(self._relay_adverts.items())[:cap]:
            if hops <= self.max_relay_hops:
                out[doc_id] = [origin, frontier, hops]
        return out

    def _on_ping(self, peer_id: str, body: dict) -> None:
        """Probe-loop gossip hook: fold the responder's member table,
        and open transport to any member we just learned about."""
        # wire capability: absent/0 = JSON-only peer (old build, or
        # framing pinned off) — every POST body to it stays JSON
        self.wire.note_peer(peer_id, body.get("wire"))
        members = body.get("members")
        if isinstance(members, dict):
            self.membership.merge_remote(members)
            for mid, info in members.items():
                if isinstance(info, dict) \
                        and info.get("state") != LEFT:
                    self.table.add_peer(mid)
        # rebalancer gossip: the responder's held-lease count (target
        # selection) and its placement-override table (LWW merge)
        load = body.get("load")
        if isinstance(load, int):
            self.peer_load[peer_id] = load
        overrides = body.get("overrides")
        if overrides:
            self.overrides.merge(overrides)
        # frontier advertisements for the follower-read tier: the
        # responder gossips the frontiers of docs it holds ACTIVE
        # leases on. Fold time stands in for send time (sub-RTT slop;
        # the staleness contract's useful bounds are >= hundreds of ms).
        frontiers = body.get("frontiers")
        reads = getattr(self.store, "reads", None)
        if reads is not None and isinstance(frontiers, dict):
            now = self.clock()
            for doc_id, frontier in frontiers.items():
                if frontier:
                    reads.index.note_advert(doc_id, peer_id, frontier)
                    # owner-direct advert: candidate for one relay hop
                    self._relay_adverts[doc_id] = (peer_id, frontier,
                                                   1, now)
            if frontiers:
                self.metrics.bump("antientropy", "frontier_adverts",
                                  len(frontiers))
        # relayed adverts: credit the ORIGIN owner, with the advert
        # aged by the relay hops (one probe interval per hop) so the
        # staleness contract stays conservative
        relayed = body.get("relayed_frontiers")
        if reads is not None and isinstance(relayed, dict):
            for doc_id, row in relayed.items():
                if not (isinstance(row, list) and len(row) == 3):
                    continue
                origin, frontier, hops = row
                if origin == self.self_id or not frontier:
                    continue
                age = self.probe_interval_s * max(int(hops), 1)
                reads.index.note_advert(
                    doc_id, origin, frontier,
                    as_of=time.monotonic() - age)

    def handle_join(self, req: dict) -> dict:
        """`POST /replicate/join` — a node announces itself (bootstrap
        or re-join after restart, with a bumped incarnation). Gossip
        spreads the new member from here; the response carries our
        member table so the joiner learns the mesh in one round trip."""
        member_id = req.get("id")
        if not isinstance(member_id, str) or not member_id:
            return {"ok": False, "error": "bad id"}
        incarnation = int(req.get("incarnation", 0))
        self.table.add_peer(member_id)
        self.membership.add(member_id, state=ALIVE,
                            incarnation=incarnation)
        return {"ok": True, "self": self.self_id,
                "members": self.membership.gossip_payload(),
                "peers": self.table.all_ids()}

    def handle_leave(self, req: dict) -> dict:
        """`POST /replicate/leave` — explicit, operator-driven removal:
        the ONLY operation that shrinks the quorum denominator."""
        member_id = req.get("id")
        if not isinstance(member_id, str) or not member_id:
            return {"ok": False, "error": "bad id"}
        left = self.membership.leave(member_id)
        self.table.remove_peer(member_id)
        return {"ok": True, "left": left}

    def join_mesh(self, seed_addr: str) -> bool:
        """Announce ourselves to `seed_addr` and adopt its view (used
        by `serve --join` and the chaos soak's churn phase)."""
        self.table.add_peer(seed_addr)
        self.membership.add(seed_addr, state=ALIVE)
        try:
            resp = self.table.call_json(
                seed_addr, "/replicate/join",
                {"id": self.self_id,
                 "incarnation": self.membership.self_incarnation})
        except (OSError, urllib.error.HTTPError, ValueError):
            return False
        if not resp.get("ok"):
            return False
        members = resp.get("members")
        if isinstance(members, dict):
            self._on_ping(seed_addr, {"members": members})
        return True

    # ---- periodic control ------------------------------------------------

    def _rejoin_check(self) -> None:
        """Clear the post-crash `rejoining` fence once a quorum of
        voters is confirmed reachable (probed OK at least once, circuit
        closed). Until then every merge admit is denied."""
        if not self.rejoining:
            return
        confirmed = 1       # self
        for v in self.membership.voters():
            if v == self.self_id:
                continue
            st = self.table.peers.get(v)
            if st is not None and st.last_ok is not None \
                    and st.open_until == 0.0:
                confirmed += 1
        if confirmed >= self.membership.quorum_size():
            self.rejoining = False
            self.metrics.bump("quorum", "rejoins_completed")

    def maintain(self) -> dict:
        """Clear rejoining when earned; renew held leases; hand off
        docs whose rendezvous owner moved to a healthy peer.
        Serialized (probe loop + manual test calls must not race two
        handoffs for one doc)."""
        out = {"renewed": 0, "handoffs": 0, "group_renewed": 0,
               "group_demotions": 0, "group_fenced": 0}
        with self._maintain_lock:
            self._sync_membership()
            self._rejoin_check()
            if self.rejoining:
                return out
            for doc_id in self.leases.held_ids():
                # a doc we lead a writer group for must NOT hand off on
                # rendezvous drift — the group is pinned to its leader;
                # demotion is the only exit
                g = self.writergroups.get(doc_id)
                desired = self.desired_owner(doc_id)
                if desired == self.self_id or (
                        g is not None and g.leader == self.self_id):
                    self.leases.ensure_local(doc_id, True)
                    out["renewed"] += 1
                elif self.table.is_healthy(desired):
                    if self.handoff(doc_id, desired):
                        out["handoffs"] += 1
            self._group_maintain(out)
        return out

    def _group_maintain(self, out: dict) -> None:
        """Writer-group upkeep on the maintain tick. Leaders demote
        groups with a crashed/partitioned member (the demote itself
        waits out the registration TTL when the member is silent — no
        operator action either way). Members renew their registration
        through the leader and self-fence once it expired un-renewed."""
        for doc_id, g in self.writergroups.entries():
            if g.leader == self.self_id:
                # member renewals are the group's liveness signal: an
                # expired registration means no member renewed for a
                # whole TTL even if probes look healthy (the asymmetric
                # partition — members can't reach us, we still hear
                # them), so it demotes exactly like an unhealthy member
                if self.clock() >= g.expires_at \
                        or any(not self.table.is_healthy(m)
                               for m in g.members
                               if m != self.self_id):
                    if self.demote_writer_group(doc_id):
                        out["group_demotions"] += 1
                continue
            renewed = False
            if self.table.is_healthy(g.leader):
                try:
                    resp = self.table.call_json(
                        g.leader, "/replicate/lease",
                        {"action": "group-renew", "doc": doc_id,
                         "epoch": g.epoch, "member": self.self_id})
                except (OSError, KeyError, ValueError,
                        urllib.error.HTTPError):
                    resp = None
                if resp is not None and resp.get("ok"):
                    self.writergroups.refresh(doc_id, g.epoch)
                    out["group_renewed"] += 1
                    renewed = True
                elif resp is not None:
                    # the leader no longer recognizes this group (it
                    # demoted, re-acquired, or restarted): fence now
                    self._group_fence_local(doc_id, g.epoch)
                    self.metrics.bump("writergroup", "self_fenced")
                    out["group_fenced"] += 1
                    continue
            if not renewed and self.clock() >= g.expires_at:
                self._group_fence_local(doc_id, g.epoch)
                self.metrics.bump("writergroup", "self_fenced")
                out["group_fenced"] += 1

    # ---- docs listing (for anti-entropy peers) ---------------------------

    def docs_json(self) -> dict:
        now = self.clock()
        doc_ids = self.store.doc_ids()
        # frontier advertisement per IN-MEMORY doc (not-yet-loaded .dt
        # files aren't worth a load just to advertise). Always included:
        # the follower-read tier folds them as staleness evidence, and
        # anti-entropy's frontier short-circuit skips the whole per-doc
        # summary round trip when the advertised frontier matches.
        # Computed under the store's oplog guard BEFORE the lease guard
        # below — the two are never nested.
        frontiers = {}
        with self.store.lock:
            for doc_id, ol in self.store.docs.items():
                frontiers[doc_id] = \
                    ol.cg.local_to_remote_frontier(ol.version)
        docs = {}
        with self.leases.lock:
            for doc_id in doc_ids:
                lease = self.leases.leases.get(doc_id)
                docs[doc_id] = {
                    "lease": lease.as_json(now) if lease is not None
                    and not lease.expired(now) else None}
                if doc_id in frontiers:
                    docs[doc_id]["frontier"] = frontiers[doc_id]
        return {"docs": docs, "self": self.self_id}

    # ---- wire-tier snapshot fetch (hydrator hook) ------------------------

    def fetch_remote_snapshot(self, doc_id: str) -> Optional[bytes]:
        """One GET of the doc owner's compacted snapshot frame, for a
        cold hydration miss whose durable home is empty. Best-effort:
        any transport error, a 404 (old peer or unknown doc) or a
        non-frame body returns None and the miss stays a fresh doc."""
        from ..wire.frames import WIRE_HEADER, is_frame
        target = self.route_mutation(doc_id)
        if target == self.self_id or not self.wire.enabled:
            return None
        try:
            st, body = self.table.call(
                target, f"/doc/{doc_id}/snapshot",
                headers={WIRE_HEADER: self.wire.header_value()})
        except (OSError, urllib.error.HTTPError, KeyError):
            return None
        if st != 200 or not is_frame(body):
            return None
        return body

    # ---- metrics ---------------------------------------------------------

    def metrics_json(self) -> dict:
        return self.metrics.snapshot(
            leases_held=self.leases.held_count(),
            per_peer=self.table.states(),
            faults=self.faults.snapshot()
            if self.faults is not None else None,
            membership_view=self.membership.as_json(),
            quorum_view={"voters": self.membership.voters(),
                         "quorum": self.membership.quorum_size(),
                         "rejoining": self.rejoining},
            override_table_size=self.overrides.size(),
            writergroup_sizes=self.writergroups.sizes())

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Probe + maintain loop and the anti-entropy loop."""
        self.antientropy.start()
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.probe_interval_s):
                try:
                    self.table.probe_once()
                    self.maintain()
                    rb = self.rebalancer
                    if rb is not None:
                        rb.tick()
                except Exception:   # pragma: no cover - keep running
                    pass

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.antientropy.stop()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self._stop = threading.Event()
        self.table.stop_probe_loop()
        if self.journal is not None:
            self.journal.close()


def attach_replication(httpd, self_id: str, peer_addrs: List[str],
                       **opts) -> ReplicaNode:
    """Wire a ReplicaNode onto a running server (tools/server.serve):
    the store gains `.replica`, and the merge scheduler (when present)
    gets the ownership admit gate plus the epoch fencing callback.
    Split from serve() because tests bind port 0 first and only then
    know their own `host:port` identity."""
    store = httpd.store
    if "obs" not in opts:
        opts["obs"] = getattr(store, "obs", None)
    node = ReplicaNode(store, self_id, peer_addrs, **opts)
    store.replica = node
    if getattr(store, "scheduler", None) is not None:
        store.scheduler.admit = node.owns
        store.scheduler.epoch_of = node.active_epoch
        # wire tier: a cold hydration miss with an EMPTY durable home
        # asks the doc's owner for one compacted snapshot frame (the
        # `/doc/{id}/snapshot` endpoint is wire-v1-only, so a node
        # pinned to JSON never fetches — old-peer semantics preserved)
        hyd = getattr(store.scheduler, "hydrator", None)
        if hyd is not None and node.wire.enabled:
            hyd.remote_fetch = node.fetch_remote_snapshot
    return node
